"""Offline checkpoint consolidation: sharded epoch checkpoint -> one .npz file.

Parity with `python3 -m torch_xla.distributed.fsdp.consolidate_sharded_ckpts`
(cited at reference utils.py:27-29): produces a single-file, framework-neutral
export of the full (unsharded) parameters for serving/analysis.

Unlike the reference's tool, no shard metadata is needed — Orbax checkpoints are
already topology-independent; this tool simply restores on host and flattens.

The export is the direct input to the serving stack:
`vitax.serve.InferenceEngine.from_npz` restores the exact param tree from it
via the shared `flatten_tree` / `unflatten_tree` helpers below (see the
README "Serving" section and vitax/serve/engine.py).

Usage:
    python -m vitax.checkpoint.consolidate --ckpt_dir /path --epoch 10 --out full.npz
    python -m vitax.checkpoint.consolidate ... --params_only
    python -m vitax.checkpoint.consolidate ... --dtype bfloat16   # half-size export
    python -m vitax.checkpoint.consolidate ... --dtype int8       # quantized export
"""

from __future__ import annotations

import argparse
import json
from typing import Dict, Optional, Tuple

import numpy as np

from vitax.checkpoint.orbax_io import epoch_ckpt_path

# npz has no native bfloat16: bf16 arrays are stored as uint16 bit-views and
# their keys recorded under this manifest entry, so load_npz can restore the
# exact dtype. The key cannot collide with a param path ("/"-joined names).
BF16_MANIFEST_KEY = "__bfloat16_keys__"

# --dtype int8/float8_e4m3 manifest: a JSON document under this key records
# which leaves were quantized, keyed BY QUANTIZED DTYPE:
#     {"schema": 1, "dtypes": {"int8": ["params/head/kernel", ...]}}
# Each quantized leaf's per-output-channel float32 scales live beside it at
# QUANT_SCALE_PREFIX + key. Neither key can collide with a param path
# ("/"-joined names never start with "__"). fp8 leaves are stored as uint8
# bit-views (npz has no fp8 dtype — same trick as the bf16 uint16 views) and
# restored by dtype from this manifest.
QUANT_MANIFEST_KEY = "__quant__"
QUANT_SCALE_PREFIX = "__scale__/"
QUANT_SCHEMA_VERSION = 1
QUANT_DTYPES = ("int8", "float8_e4m3")

# Leaves never quantized, by path name: the MoE router and every LayerNorm —
# the same names vitax/parallel/sharding.py KEEP_F32_PARAMS keeps out of the
# bf16 comm cast, MINUS "head": the head kernel is a full (d, num_classes)
# matmul weight and dequantizes back to f32 at use, so int8 storage does not
# change where its compute happens (tests/test_quant.py pins the relation to
# KEEP_F32_PARAMS).
QUANT_SKIP_NAMES = ("router", "norm", "norm1", "norm2")

# matmul weight leaf names: Dense/Conv kernels plus the MoE expert matrices
# (vitax/models/moe.py w1/w2). Biases, LN params, pos_embed and every other
# 1-D/scalar leaf stay f32.
QUANT_WEIGHT_NAMES = ("kernel", "w1", "w2")


def _is_float(v: np.ndarray) -> bool:
    """Floating leaves only — integer/bool leaves (step counters, already-
    quantized int8 weights) must never be touched by a --dtype cast."""
    import ml_dtypes
    return bool(np.issubdtype(v.dtype, np.floating)
                or v.dtype == ml_dtypes.bfloat16)


def should_quantize(key: str, v: np.ndarray) -> bool:
    """Whether a quantized --dtype quantizes this leaf: a 2-D+ floating matmul
    weight (patchify/QKV/proj/MLP/head) not under a skip name."""
    parts = key.split("/")
    return (_is_float(v) and v.ndim >= 2
            and parts[-1] in QUANT_WEIGHT_NAMES
            and not any(p in QUANT_SKIP_NAMES for p in parts))


def _contraction_axes(key: str, ndim: int) -> Tuple[int, ...]:
    """Axes reduced by the absmax scale: everything except the output-channel
    (last) axis and any leading stacking axes — the scan-stacked layer dim of
    block params ("blocks" in the path) and the experts dim of MoE w1/w2 —
    so scales stay per (layer[, expert], out_channel)."""
    parts = key.split("/")
    stack = 1 if "blocks" in parts else 0
    if parts[-1] in ("w1", "w2"):
        stack += 1  # (…, E, in, out): experts are independent matmuls
    return tuple(range(stack, ndim - 1))


def quant_max(dtype: str) -> float:
    """The largest magnitude the quantized dtype represents: 127 for int8,
    the max FINITE fp8 value for float8_e4m3 (240 for ml_dtypes' IEEE-style
    e4m3 — absmax maps onto it exactly, so no leaf element ever rounds to
    inf)."""
    if dtype == "int8":
        return 127.0
    import ml_dtypes
    return float(ml_dtypes.finfo(ml_dtypes.float8_e4m3).max)


def quantize_leaf(key: str, v: np.ndarray,
                  dtype: str = "int8") -> Tuple[np.ndarray, np.ndarray]:
    """Per-output-channel symmetric absmax quantization to int8 or fp8.

    scale = absmax / quant_max(dtype) over the contraction axes (keepdims,
    so dequant is the broadcast `w_q * scale`); int8 rounds to
    [-127, 127], float8_e4m3 rounds to the nearest fp8 value (the mantissa
    rounding IS the quantization — fp8 keeps per-element exponents, so its
    relative error is flat across each channel instead of absolute).
    All-zero channels get scale 1.0 (they quantize and dequantize to 0)."""
    assert dtype in QUANT_DTYPES, dtype
    w = np.asarray(v, dtype=np.float32)
    axes = _contraction_axes(key, w.ndim)
    absmax = np.max(np.abs(w), axis=axes, keepdims=True) if axes else np.abs(w)
    scale = (absmax / quant_max(dtype)).astype(np.float32)
    scale = np.where(scale == 0.0, np.float32(1.0), scale)
    if dtype == "int8":
        q = np.clip(np.round(w / scale), -127, 127).astype(np.int8)
    else:
        import ml_dtypes
        q = (w / scale).astype(ml_dtypes.float8_e4m3)
    return q, scale


def quantize_flat(flat: Dict[str, np.ndarray], dtype: str = "int8") -> Tuple[
        Dict[str, np.ndarray], Dict[str, np.ndarray]]:
    """Quantize every eligible leaf of a flat tree to `dtype`.

    Returns (flat with quantized leaves substituted, {key: float32 scales}).
    Ineligible leaves pass through untouched."""
    out, scales = {}, {}
    for k, v in flat.items():
        if should_quantize(k, v):
            out[k], scales[k] = quantize_leaf(k, v, dtype)
        else:
            out[k] = v
    return out, scales


def quant_manifest(scales_keys, dtype: str = "int8") -> str:
    """The dtype-keyed JSON manifest body for a set of quantized keys."""
    assert dtype in QUANT_DTYPES, dtype
    return json.dumps({"schema": QUANT_SCHEMA_VERSION,
                       "dtypes": {dtype: sorted(scales_keys)}})


def parse_quant_manifest(doc: str) -> Dict[str, str]:
    """{key: quantized dtype} from a manifest JSON document (dtype-keyed on
    disk; inverted here because consumers look leaves up by key)."""
    parsed = json.loads(doc)
    assert parsed.get("schema") == QUANT_SCHEMA_VERSION, (
        f"unknown quant manifest schema {parsed.get('schema')!r} "
        f"(this build reads schema {QUANT_SCHEMA_VERSION})")
    out: Dict[str, str] = {}
    for dtype, keys in parsed.get("dtypes", {}).items():
        assert dtype in QUANT_DTYPES, (
            f"quantized dtype {dtype!r} not supported by this build "
            f"(implemented: {QUANT_DTYPES})")
        for k in keys:
            out[k] = dtype
    return out


def flatten_tree(tree, sep: str = "/") -> Dict[str, np.ndarray]:
    """Flatten a (nested-dict) param tree to {"a/b/c": np.ndarray}.

    The inverse of `unflatten_tree`: consolidate writes with this and
    `InferenceEngine.from_npz` reads with that, so the two sides share one
    key convention by construction."""
    import jax
    out = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = sep.join(
            str(getattr(p, "key", getattr(p, "name", getattr(p, "idx", p))))
            for p in path)
        out[key] = np.asarray(leaf)
    return out


def unflatten_tree(flat: Dict[str, np.ndarray], sep: str = "/") -> dict:
    """Rebuild the nested dict tree from flatten_tree's "/"-joined keys."""
    tree: dict = {}
    for key, leaf in flat.items():
        parts = key.split(sep)
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = leaf
    return tree


def save_npz(out: str, flat: Dict[str, np.ndarray],
             dtype: Optional[str] = None) -> Dict[str, np.ndarray]:
    """Write a flat tree as .npz, optionally casting/quantizing float arrays.

    dtype "bfloat16" halves the export; bf16 has no npz dtype, so those
    arrays are stored as uint16 bit-views plus a key manifest
    (BF16_MANIFEST_KEY) that load_npz uses to restore them exactly.

    dtype "int8" / "float8_e4m3" quantizes every eligible matmul weight
    (should_quantize) per output channel and records the key set under
    QUANT_MANIFEST_KEY with the float32 scales at QUANT_SCALE_PREFIX + key;
    ineligible float leaves stay at their stored dtype, so a quantized
    export of a bf16 tree carries both manifests in one file. fp8 leaves
    have no npz dtype either — they are stored as uint8 bit-views and
    restored by manifest dtype, the same trick as the bf16 uint16 views.
    Casts touch FLOATING leaves only — integer/bool leaves (step counters,
    pre-quantized int8 weights) round-trip exactly under every --dtype."""
    import ml_dtypes
    scales: Dict[str, np.ndarray] = {}
    if dtype in QUANT_DTYPES:
        flat, scales = quantize_flat(flat, dtype)
    elif dtype:
        target = (ml_dtypes.bfloat16 if dtype == "bfloat16"
                  else np.dtype(dtype))
        flat = {k: v.astype(target) if _is_float(v) else v
                for k, v in flat.items()}
    bf16_keys = sorted(k for k, v in flat.items()
                       if v.dtype == ml_dtypes.bfloat16)
    fp8_keys = {k for k, v in flat.items()
                if v.dtype == ml_dtypes.float8_e4m3}
    payload = {k: (v.view(np.uint16) if k in bf16_keys
                   else v.view(np.uint8) if k in fp8_keys else v)
               for k, v in flat.items()}
    if bf16_keys:
        payload[BF16_MANIFEST_KEY] = np.asarray(bf16_keys)
    if scales:
        payload[QUANT_MANIFEST_KEY] = np.asarray(quant_manifest(scales, dtype))
        for k, s in scales.items():
            payload[QUANT_SCALE_PREFIX + k] = s
    np.savez(out, **payload)
    return flat


def load_npz_raw(path: str) -> Tuple[Dict[str, np.ndarray],
                                     Dict[str, np.ndarray],
                                     Dict[str, str]]:
    """Read a save_npz export without dequantizing.

    Returns (flat, scales, manifest): `flat` holds quantized leaves at their
    stored quantized dtype (bf16 and fp8 bit-views restored), `scales` the
    per-key float32 scale arrays, `manifest` {key: quantized dtype} — all
    empty dicts but `flat` for an unquantized file. This is the serving load
    path: InferenceEngine.from_npz device_puts quantized leaves verbatim."""
    import ml_dtypes
    with np.load(path) as data:
        bf16 = (set(str(k) for k in data[BF16_MANIFEST_KEY])
                if BF16_MANIFEST_KEY in data.files else set())
        manifest = (parse_quant_manifest(str(data[QUANT_MANIFEST_KEY]))
                    if QUANT_MANIFEST_KEY in data.files else {})
        flat, scales = {}, {}
        for k in data.files:
            if k in (BF16_MANIFEST_KEY, QUANT_MANIFEST_KEY):
                continue
            if k.startswith(QUANT_SCALE_PREFIX):
                scales[k[len(QUANT_SCALE_PREFIX):]] = data[k]
            elif k in bf16:
                flat[k] = data[k].view(ml_dtypes.bfloat16)
            elif manifest.get(k) == "float8_e4m3":
                flat[k] = data[k].view(ml_dtypes.float8_e4m3)
            else:
                flat[k] = data[k]
        assert set(manifest) == set(scales), (
            f"quant manifest/scale mismatch in {path}: manifest names "
            f"{sorted(set(manifest) ^ set(scales))} without scales (or "
            f"vice versa)")
        return flat, scales, manifest


def load_npz(path: str) -> Dict[str, np.ndarray]:
    """Read a save_npz export back to {key: array}, restoring bf16 views and
    dequantizing int8/fp8 leaves to float32 (key set == the saved tree's;
    generic consumers never see scales). Serving wants the quantized leaves
    verbatim — use load_npz_raw there."""
    flat, scales, manifest = load_npz_raw(path)
    for k in manifest:
        flat[k] = (flat[k].astype(np.float32) * scales[k]).astype(np.float32)
    return flat


def consolidate(ckpt_dir: str, epoch: int, out: str, params_only: bool = True,
                dtype: Optional[str] = None) -> dict:
    import jax
    import orbax.checkpoint as ocp

    from vitax.checkpoint.orbax_io import wait_until_finished
    wait_until_finished()  # same-process async save of this epoch must commit
    path = epoch_ckpt_path(ckpt_dir, epoch)
    # Restore every leaf as a plain numpy array (restore_type=np.ndarray).
    # A targetless restore would instead rebuild the SAVED device mesh from
    # the sharding file — impossible on this host for a checkpoint written
    # by a multi-host run (its device ids don't exist here). Consolidation
    # must work from any single machine regardless of save topology.
    with ocp.PyTreeCheckpointer() as ckptr:
        meta = ckptr.metadata(path)
        restore_args = jax.tree.map(
            lambda _: ocp.RestoreArgs(restore_type=np.ndarray), meta)
        state = ckptr.restore(path, restore_args=restore_args)
    tree = state["params"] if params_only and "params" in state else state
    flat = save_npz(out, flatten_tree(tree), dtype=dtype)
    total = sum(v.size for v in flat.values())
    print(f"consolidated {len(flat)} arrays ({total:,} elements"
          + (f", cast to {dtype}" if dtype else "")
          + f") from {path} -> {out}")
    return flat


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--ckpt_dir", type=str, required=True)
    p.add_argument("--epoch", type=int, required=True)
    p.add_argument("--out", type=str, required=True)
    p.add_argument("--full_state", action="store_false", dest="params_only",
                   help="include optimizer state and step, not just params")
    p.add_argument("--dtype", type=str, default=None,
                   choices=["float32", "bfloat16", "int8", "float8_e4m3"],
                   help="cast float arrays for the export (default: keep "
                        "the stored dtype). bfloat16 halves the file — the "
                        "serving engine computes in bf16 anyway "
                        "(vitax/serve/engine.py from_npz). int8/float8_e4m3 "
                        "quantize every matmul weight per output channel "
                        "(symmetric absmax, float32 scales under the "
                        "__quant__ manifest) for ~4x smaller serve weights; "
                        "LN/bias/router leaves stay f32 (see README "
                        "'Quantized serving')")
    args = p.parse_args(argv)
    consolidate(args.ckpt_dir, args.epoch, args.out, args.params_only,
                dtype=args.dtype)


if __name__ == "__main__":
    main()
