from vitax.checkpoint.orbax_io import (  # noqa: F401
    committed_epochs,
    epoch_ckpt_path,
    is_committed_checkpoint,
    latest_epoch,
    prune_checkpoints,
    restore_read_count,
    restore_state,
    restore_state_with_fallback,
    save_state,
    wait_until_finished,
)
