from vitax.checkpoint.orbax_io import (  # noqa: F401
    epoch_ckpt_path,
    latest_epoch,
    restore_state,
    save_state,
    wait_until_finished,
)
