"""Sharded checkpointing via Orbax.

Capability parity with the reference's per-rank ckpt scheme (reference
utils.py:24-43; SURVEY.md section 3.3), improved the TPU-native way:

- Every host writes only its own parameter/optimizer shards in parallel
  (parity with master_only=False per-rank save, reference run_vit_training.py:299),
  into ONE logical checkpoint directory per epoch — not per-rank files keyed by
  local ordinal (the reference's naming collides on shared filesystems; see
  SURVEY.md section 2.1 'subtle behavior').
- Restore is topology-independent: Orbax reshards on load, so a checkpoint
  written on a v5p-256 restores on a v5p-128 (the reference needs an offline
  consolidation pass to change topology, utils.py:27-29).
- The LR schedule needs no state: it is a pure function of the restored `step`
  (reference saves lr_scheduler.state_dict, utils.py:31).

Saves are ASYNC by default (VERDICT round-1 item 4): `save_state` snapshots
device shards to host memory synchronously (so the caller may immediately
donate/overwrite the state buffers in the next train step) and commits the
write in a background thread — at 10B, the serialize+write no longer stalls
every rank (improving on the reference's synchronous xm.save,
utils.py:24-34). Atomicity is Orbax's tmp-dir+rename commit; `latest_epoch`
additionally validates the commit marker Orbax writes at finalize
(_CHECKPOINT_METADATA / commit_success.txt), so a torn `epoch_<N>/` left by
a hard crash mid-write (or a non-atomic shared store, e.g. GCS fuse) can
never be selected by `--resume_epoch -1`. Call `wait_until_finished()`
(epoch end, exit) or pass `wait=True` (final epoch) to drain.

Failure reaction (PR 7): `save_state` retries transient OSErrors with capped
backoff before surfacing (VITAX_SAVE_RETRIES / VITAX_SAVE_RETRY_BACKOFF_S
override the defaults), and `restore_state_with_fallback` drops — loudly —
to the previous committed epoch when the newest one fails to restore, so an
auto-resume is never wedged by one bad checkpoint. The `ckpt_write` fault
hook (vitax/faults.py) fires once per write attempt to drill both paths.

Single-file consolidation (consolidate_sharded_ckpts parity) lives in
vitax/checkpoint/consolidate.py.
"""

from __future__ import annotations

import atexit
import json
import os
import re
import sys
import time
from typing import Any, List, Optional, Tuple

import jax
import orbax.checkpoint as ocp

from vitax import faults
from vitax.utils.logging import master_print

PyTree = Any

_EPOCH_RE = re.compile(r"^epoch_(\d+)$")

# Files only a *finalized* Orbax checkpoint dir contains: the checkpoint
# metadata written at commit time (orbax >= 0.5), or the explicit commit
# marker orbax drops on filesystems without atomic rename (GCS).
COMMIT_MARKERS = ("_CHECKPOINT_METADATA", "commit_success.txt")

# save_state transient-write retry policy (env-overridable: tests pin the
# retry path with injected OSErrors and a near-zero backoff)
DEFAULT_SAVE_RETRIES = 3
DEFAULT_SAVE_RETRY_BACKOFF_S = 0.5

_CKPTR: Optional[ocp.StandardCheckpointer] = None

# Shared-storage restore counter (the peer-replication acceptance seam):
# every restore_state call — the only path that READS checkpoint state from
# shared storage — bumps it. The kill-and-resume drill asserts a peer-path
# resume leaves it at ZERO (tests/test_snapshot.py).
_RESTORE_READS = 0


def restore_read_count() -> int:
    """How many shared-storage checkpoint restores this process performed."""
    return _RESTORE_READS


def _checkpointer() -> ocp.StandardCheckpointer:
    """One persistent async checkpointer per process (construction is not
    free, and pending background writes must outlive a single save call)."""
    global _CKPTR
    if _CKPTR is None:
        _CKPTR = ocp.StandardCheckpointer()
        atexit.register(close)
    return _CKPTR


def wait_until_finished() -> None:
    """Block until every in-flight async save has committed."""
    if _CKPTR is not None:
        _CKPTR.wait_until_finished()


def close() -> None:
    """Drain pending saves and release the checkpointer."""
    global _CKPTR
    if _CKPTR is not None:
        _CKPTR.close()
        _CKPTR = None


def epoch_ckpt_path(ckpt_dir: str, epoch: int) -> str:
    return os.path.join(os.path.abspath(ckpt_dir), f"epoch_{epoch}")


def _resume_meta_path(ckpt_dir: str, epoch: int) -> str:
    # NEXT to the checkpoint dir, not inside it: Orbax owns the dir's
    # contents, and the name does not match _EPOCH_RE so latest_epoch is
    # unaffected
    return epoch_ckpt_path(ckpt_dir, epoch) + ".resume.json"


def load_resume_step(ckpt_dir: str, epoch: int) -> Optional[int]:
    """Completed steps-in-epoch recorded with a MID-epoch (preemption) save of
    `epoch`, or None when the stored checkpoint is an epoch-boundary one.
    The sampler order is a pure function of (seed, epoch), so this single
    integer pins the exact resume position (vitax/data/loader.py)."""
    path = _resume_meta_path(ckpt_dir, epoch)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            step = json.load(f)["step_in_epoch"]
        return int(step) if step and step > 0 else None
    except (json.JSONDecodeError, KeyError, TypeError, OSError):
        return None  # unreadable sidecar degrades to epoch-granular resume


def load_resume_meta(ckpt_dir: str, epoch: int) -> Optional[dict]:
    """The WHOLE mid-epoch resume sidecar payload for `epoch` —
    {"step_in_epoch": int, "process_count": int, "stream_cursor": {...}?} —
    or None (boundary save, missing, or unreadable). The elastic-resume
    planner (vitax/train/control.py elastic_resume_plan) reads this to
    detect a topology change between the run that wrote the checkpoint and
    the run resuming it; older sidecars without `process_count` degrade to
    "topology unknown" (no rounding), exactly like the other tolerant
    readers here."""
    path = _resume_meta_path(ckpt_dir, epoch)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            payload = json.load(f)
        return payload if isinstance(payload, dict) else None
    except (json.JSONDecodeError, OSError):
        return None  # unreadable sidecar degrades to epoch-granular resume


def load_stream_cursor(ckpt_dir: str, epoch: int) -> Optional[dict]:
    """The streaming-data-plane resume cursor `(epoch, shard_cursor,
    record_offset, shard, ...)` recorded with a MID-epoch save of `epoch`,
    or None (ImageFolder run, boundary save, or unreadable sidecar). The
    resume position itself is re-derived from (seed, epoch, step) — this
    record exists so the resumed run can DETECT a drifted shard set
    (vitax/data/stream/sampler.py check_cursor) instead of silently feeding
    different records."""
    path = _resume_meta_path(ckpt_dir, epoch)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            cursor = json.load(f).get("stream_cursor")
        return cursor if isinstance(cursor, dict) else None
    except (json.JSONDecodeError, OSError):
        return None  # unreadable sidecar degrades to an unverified resume


def is_committed_checkpoint(path: str) -> bool:
    """Did this checkpoint dir finish its commit? A hard crash mid-async-
    write (or a non-atomic shared store) can leave a partial `epoch_N/`
    whose name looks finished; the commit marker is written at finalize, so
    its absence marks the dir torn."""
    return os.path.isdir(path) and any(
        os.path.exists(os.path.join(path, marker))
        for marker in COMMIT_MARKERS)


def committed_epochs(ckpt_dir: str) -> List[int]:
    """Ascending epochs with a COMMITTED checkpoint in ckpt_dir. Torn dirs
    (matching `epoch_<N>` but missing the commit marker) are skipped with a
    warning — they are exactly what a crash mid-write leaves behind, and
    resuming from one restores garbage or asserts."""
    if not os.path.isdir(ckpt_dir):
        return []
    epochs = []
    for name in sorted(os.listdir(ckpt_dir)):
        m = _EPOCH_RE.match(name)
        if not m or name.endswith(".tmp"):
            continue
        if is_committed_checkpoint(os.path.join(ckpt_dir, name)):
            epochs.append(int(m.group(1)))
        else:
            master_print(f"vitax.checkpoint: skipping torn checkpoint "
                         f"{os.path.join(ckpt_dir, name)} (no commit "
                         f"marker — a crash mid-write left it partial)")
    return sorted(epochs)


def latest_epoch(ckpt_dir: str) -> Optional[int]:
    """Highest epoch with a complete, COMMITTED checkpoint in ckpt_dir, or
    None. The commit-marker validation makes `--resume_epoch -1` safe after
    a hard crash mid-async-save."""
    epochs = committed_epochs(ckpt_dir)
    return max(epochs) if epochs else None


def save_state(ckpt_dir: str, epoch: int, state: PyTree,
               wait: bool = False,
               step_in_epoch: Optional[int] = None,
               stream_cursor: Optional[dict] = None,
               keep: int = 0,
               extra_meta: Optional[dict] = None) -> str:
    """Save the train state for `epoch`; all hosts write their shards in
    parallel (reference save_ckpt with master_only=False, utils.py:24-33).

    Returns as soon as the device->host snapshot is taken (the state may then
    be donated to the next step); the write commits in background. wait=True
    blocks until committed (final save / preemption-imminent path).

    step_in_epoch > 0 marks a MID-epoch save (preemption at that many
    completed steps): process 0 records it in a sidecar so resume can
    continue inside the epoch instead of skipping its remainder. An
    epoch-boundary save of the same epoch deletes any stale sidecar (the
    stored state it described has been overwritten). `stream_cursor`
    (streaming data plane, vitax/data/stream/) rides the same sidecar —
    the `(epoch, shard_cursor, record_offset)` record the resumed run
    validates its derived position against (load_stream_cursor).

    Transient OSErrors at the write (a flaky shared filesystem, a full
    scratch volume being reaped) are retried with capped exponential
    backoff before surfacing — losing a 10B run to one EIO is worse than
    waiting a second.

    VITAX_CKPT_SYNC=1 forces wait=True on EVERY save — for fault drills
    and tests where "the save returned" must mean "the checkpoint is
    durable" (an injected crash a few steps after an epoch boundary
    would otherwise race the background commit nondeterministically).

    keep > 0 enables checkpoint GC (--keep_checkpoints): after the save,
    committed epoch dirs beyond the newest `keep` are pruned (process 0
    only; torn/uncommitted dirs are never touched — see prune_checkpoints).

    extra_meta merges additional fields into the mid-epoch resume sidecar
    (e.g. the replication window a zero-stall run was using, so a resumed
    run can see the cadence that produced its peer replicas)."""
    path = epoch_ckpt_path(ckpt_dir, epoch)
    wait = wait or os.environ.get("VITAX_CKPT_SYNC", "") == "1"
    ckptr = _checkpointer()
    retries = int(os.environ.get("VITAX_SAVE_RETRIES", DEFAULT_SAVE_RETRIES))
    backoff_s = float(os.environ.get("VITAX_SAVE_RETRY_BACKOFF_S",
                                     DEFAULT_SAVE_RETRY_BACKOFF_S))
    for attempt in range(max(retries, 1)):
        try:
            faults.fire("ckpt_write")  # one hook per ATTEMPT: `times` > 1
            # in a fault plan exercises exactly this retry loop
            ckptr.save(path, state, force=True)
            break
        except OSError as e:
            if attempt + 1 >= max(retries, 1):
                print(f"vitax.checkpoint: save of {path} failed after "
                      f"{attempt + 1} attempt(s): {type(e).__name__}: {e}",
                      file=sys.stderr, flush=True)
                raise
            delay = backoff_s * (2 ** attempt)
            print(f"vitax.checkpoint: transient save failure for {path} "
                  f"(attempt {attempt + 1}/{retries}: {type(e).__name__}: "
                  f"{e}); retrying in {delay:.2f}s", file=sys.stderr,
                  flush=True)
            time.sleep(delay)
    if wait:
        ckptr.wait_until_finished()
    if jax.process_index() == 0:
        meta = _resume_meta_path(ckpt_dir, epoch)
        if step_in_epoch:
            # process_count records the topology that wrote this mid-epoch
            # state: a resume under a DIFFERENT layout must know (elastic
            # resume re-derives or epoch-rounds; vitax/train/control.py)
            payload = {"step_in_epoch": int(step_in_epoch),
                       "process_count": jax.process_count()}
            if stream_cursor is not None:
                payload["stream_cursor"] = stream_cursor
            if extra_meta:
                payload.update(extra_meta)
            tmp = meta + f".tmp{os.getpid()}"
            with open(tmp, "w") as f:
                f.write(json.dumps(payload))
            os.replace(tmp, meta)  # atomic: never a half-written sidecar
        elif os.path.exists(meta):
            os.remove(meta)
    master_print(f"checkpoint save {'committed' if wait else 'started'}: {path}"
                 + (f" (mid-epoch, {step_in_epoch} steps done)"
                    if step_in_epoch else ""))
    if keep > 0 and jax.process_index() == 0:
        prune_checkpoints(ckpt_dir, keep)
    return path


def prune_checkpoints(ckpt_dir: str, keep: int) -> List[int]:
    """Checkpoint GC (--keep_checkpoints): delete COMMITTED epoch dirs (and
    their resume sidecars) beyond the newest `keep`. Torn/uncommitted dirs
    are never touched — they are crash forensics and committed_epochs
    already refuses to resume from them; deleting one could also race an
    in-flight async commit of that very epoch. keep <= 0 is a no-op (keep
    all). Returns the pruned epochs."""
    if keep <= 0:
        return []
    import shutil
    committed = committed_epochs(ckpt_dir)
    doomed = committed[:-keep] if len(committed) > keep else []
    for ep in doomed:
        shutil.rmtree(epoch_ckpt_path(ckpt_dir, ep), ignore_errors=True)
        try:
            os.remove(_resume_meta_path(ckpt_dir, ep))
        except OSError:
            pass
    if doomed:
        master_print(f"checkpoint GC: pruned committed epoch(s) {doomed} "
                     f"(--keep_checkpoints {keep})")
    return doomed


def restore_state(ckpt_dir: str, epoch: int, abstract_state: PyTree) -> PyTree:
    """Restore into the given abstract state (ShapeDtypeStructs carrying target
    shardings) — resharding across topologies as needed (reference load_ckpt,
    utils.py:37-43, without the same-topology restriction)."""
    global _RESTORE_READS
    wait_until_finished()  # an in-flight save of this epoch must commit first
    path = epoch_ckpt_path(ckpt_dir, epoch)
    assert os.path.exists(path), f"checkpoint not found: {path}"
    _RESTORE_READS += 1  # the peer-restore drill asserts this stays 0
    state = _checkpointer().restore(path, abstract_state)
    master_print(f"resumed from checkpoint {path}")
    return state


def restore_state_with_fallback(ckpt_dir: str, epoch: int,
                                abstract_state: PyTree,
                                ) -> Tuple[PyTree, int]:
    """restore_state, but when the requested (newest) epoch fails to restore
    — corrupted array files behind an intact commit marker, a half-replicated
    shared store — fall back, LOUDLY, to the previous committed epoch rather
    than wedging auto-resume on one bad checkpoint. Returns (state, epoch
    actually restored); raises only when every committed epoch fails."""
    candidates = [ep for ep in committed_epochs(ckpt_dir) if ep <= epoch]
    if epoch not in candidates:
        candidates.append(epoch)  # honor an explicit ask even if unmarked
    last_err: Optional[BaseException] = None
    for ep in sorted(set(candidates), reverse=True):
        try:
            return restore_state(ckpt_dir, ep, abstract_state), ep
        except Exception as e:  # noqa: BLE001 — fall back across ANY restore failure
            last_err = e
            print(f"vitax.checkpoint: RESTORE FAILED for epoch {ep} at "
                  f"{epoch_ckpt_path(ckpt_dir, ep)} ({type(e).__name__}: "
                  f"{e}); falling back to the previous committed epoch",
                  file=sys.stderr, flush=True)
    raise RuntimeError(
        f"no committed epoch <= {epoch} in {ckpt_dir} could be restored"
    ) from last_err
