"""Sharded checkpointing via Orbax.

Capability parity with the reference's per-rank ckpt scheme (reference
utils.py:24-43; SURVEY.md section 3.3), improved the TPU-native way:

- Every host writes only its own parameter/optimizer shards in parallel
  (parity with master_only=False per-rank save, reference run_vit_training.py:299),
  into ONE logical checkpoint directory per epoch — not per-rank files keyed by
  local ordinal (the reference's naming collides on shared filesystems; see
  SURVEY.md section 2.1 'subtle behavior').
- Restore is topology-independent: Orbax reshards on load, so a checkpoint
  written on a v5p-256 restores on a v5p-128 (the reference needs an offline
  consolidation pass to change topology, utils.py:27-29).
- The LR schedule needs no state: it is a pure function of the restored `step`
  (reference saves lr_scheduler.state_dict, utils.py:31).

Single-file consolidation (consolidate_sharded_ckpts parity) lives in
vitax/checkpoint/consolidate.py.
"""

from __future__ import annotations

import os
import re
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp

from vitax.utils.logging import master_print

PyTree = Any

_EPOCH_RE = re.compile(r"^epoch_(\d+)$")


def epoch_ckpt_path(ckpt_dir: str, epoch: int) -> str:
    return os.path.join(os.path.abspath(ckpt_dir), f"epoch_{epoch}")


def latest_epoch(ckpt_dir: str) -> Optional[int]:
    """Highest epoch with a complete checkpoint in ckpt_dir, or None."""
    if not os.path.isdir(ckpt_dir):
        return None
    epochs = []
    for name in os.listdir(ckpt_dir):
        m = _EPOCH_RE.match(name)
        if m and not name.endswith(".tmp"):
            epochs.append(int(m.group(1)))
    return max(epochs) if epochs else None


def save_state(ckpt_dir: str, epoch: int, state: PyTree) -> str:
    """Save the train state for `epoch`; all hosts write their shards in
    parallel (reference save_ckpt with master_only=False, utils.py:24-33)."""
    path = epoch_ckpt_path(ckpt_dir, epoch)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, state, force=True)
    master_print(f"checkpoint saved to {path}")
    return path


def restore_state(ckpt_dir: str, epoch: int, abstract_state: PyTree) -> PyTree:
    """Restore into the given abstract state (ShapeDtypeStructs carrying target
    shardings) — resharding across topologies as needed (reference load_ckpt,
    utils.py:37-43, without the same-topology restriction)."""
    path = epoch_ckpt_path(ckpt_dir, epoch)
    assert os.path.exists(path), f"checkpoint not found: {path}"
    with ocp.StandardCheckpointer() as ckptr:
        state = ckptr.restore(path, abstract_state)
    master_print(f"resumed from checkpoint {path}")
    return state
