"""Sharded checkpointing via Orbax.

Capability parity with the reference's per-rank ckpt scheme (reference
utils.py:24-43; SURVEY.md section 3.3), improved the TPU-native way:

- Every host writes only its own parameter/optimizer shards in parallel
  (parity with master_only=False per-rank save, reference run_vit_training.py:299),
  into ONE logical checkpoint directory per epoch — not per-rank files keyed by
  local ordinal (the reference's naming collides on shared filesystems; see
  SURVEY.md section 2.1 'subtle behavior').
- Restore is topology-independent: Orbax reshards on load, so a checkpoint
  written on a v5p-256 restores on a v5p-128 (the reference needs an offline
  consolidation pass to change topology, utils.py:27-29).
- The LR schedule needs no state: it is a pure function of the restored `step`
  (reference saves lr_scheduler.state_dict, utils.py:31).

Saves are ASYNC by default (VERDICT round-1 item 4): `save_state` snapshots
device shards to host memory synchronously (so the caller may immediately
donate/overwrite the state buffers in the next train step) and commits the
write in a background thread — at 10B, the serialize+write no longer stalls
every rank (improving on the reference's synchronous xm.save,
utils.py:24-34). Atomicity is Orbax's tmp-dir+rename commit; `latest_epoch`
only matches finalized `epoch_<N>` directory names, so a crash mid-write can
never be resumed from. Call `wait_until_finished()` (epoch end, exit) or pass
`wait=True` (final epoch) to drain.

Single-file consolidation (consolidate_sharded_ckpts parity) lives in
vitax/checkpoint/consolidate.py.
"""

from __future__ import annotations

import atexit
import json
import os
import re
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp

from vitax.utils.logging import master_print

PyTree = Any

_EPOCH_RE = re.compile(r"^epoch_(\d+)$")

_CKPTR: Optional[ocp.StandardCheckpointer] = None


def _checkpointer() -> ocp.StandardCheckpointer:
    """One persistent async checkpointer per process (construction is not
    free, and pending background writes must outlive a single save call)."""
    global _CKPTR
    if _CKPTR is None:
        _CKPTR = ocp.StandardCheckpointer()
        atexit.register(close)
    return _CKPTR


def wait_until_finished() -> None:
    """Block until every in-flight async save has committed."""
    if _CKPTR is not None:
        _CKPTR.wait_until_finished()


def close() -> None:
    """Drain pending saves and release the checkpointer."""
    global _CKPTR
    if _CKPTR is not None:
        _CKPTR.close()
        _CKPTR = None


def epoch_ckpt_path(ckpt_dir: str, epoch: int) -> str:
    return os.path.join(os.path.abspath(ckpt_dir), f"epoch_{epoch}")


def _resume_meta_path(ckpt_dir: str, epoch: int) -> str:
    # NEXT to the checkpoint dir, not inside it: Orbax owns the dir's
    # contents, and the name does not match _EPOCH_RE so latest_epoch is
    # unaffected
    return epoch_ckpt_path(ckpt_dir, epoch) + ".resume.json"


def load_resume_step(ckpt_dir: str, epoch: int) -> Optional[int]:
    """Completed steps-in-epoch recorded with a MID-epoch (preemption) save of
    `epoch`, or None when the stored checkpoint is an epoch-boundary one.
    The sampler order is a pure function of (seed, epoch), so this single
    integer pins the exact resume position (vitax/data/loader.py)."""
    path = _resume_meta_path(ckpt_dir, epoch)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            step = json.load(f)["step_in_epoch"]
        return int(step) if step and step > 0 else None
    except (json.JSONDecodeError, KeyError, TypeError, OSError):
        return None  # unreadable sidecar degrades to epoch-granular resume


def latest_epoch(ckpt_dir: str) -> Optional[int]:
    """Highest epoch with a complete checkpoint in ckpt_dir, or None."""
    if not os.path.isdir(ckpt_dir):
        return None
    epochs = []
    for name in os.listdir(ckpt_dir):
        m = _EPOCH_RE.match(name)
        if m and not name.endswith(".tmp"):
            epochs.append(int(m.group(1)))
    return max(epochs) if epochs else None


def save_state(ckpt_dir: str, epoch: int, state: PyTree,
               wait: bool = False,
               step_in_epoch: Optional[int] = None) -> str:
    """Save the train state for `epoch`; all hosts write their shards in
    parallel (reference save_ckpt with master_only=False, utils.py:24-33).

    Returns as soon as the device->host snapshot is taken (the state may then
    be donated to the next step); the write commits in background. wait=True
    blocks until committed (final save / preemption-imminent path).

    step_in_epoch > 0 marks a MID-epoch save (preemption at that many
    completed steps): process 0 records it in a sidecar so resume can
    continue inside the epoch instead of skipping its remainder. An
    epoch-boundary save of the same epoch deletes any stale sidecar (the
    stored state it described has been overwritten)."""
    path = epoch_ckpt_path(ckpt_dir, epoch)
    ckptr = _checkpointer()
    ckptr.save(path, state, force=True)
    if wait:
        ckptr.wait_until_finished()
    if jax.process_index() == 0:
        meta = _resume_meta_path(ckpt_dir, epoch)
        if step_in_epoch:
            tmp = meta + f".tmp{os.getpid()}"
            with open(tmp, "w") as f:
                f.write(json.dumps({"step_in_epoch": int(step_in_epoch)}))
            os.replace(tmp, meta)  # atomic: never a half-written sidecar
        elif os.path.exists(meta):
            os.remove(meta)
    master_print(f"checkpoint save {'committed' if wait else 'started'}: {path}"
                 + (f" (mid-epoch, {step_in_epoch} steps done)"
                    if step_in_epoch else ""))
    return path


def restore_state(ckpt_dir: str, epoch: int, abstract_state: PyTree) -> PyTree:
    """Restore into the given abstract state (ShapeDtypeStructs carrying target
    shardings) — resharding across topologies as needed (reference load_ckpt,
    utils.py:37-43, without the same-topology restriction)."""
    wait_until_finished()  # an in-flight save of this epoch must commit first
    path = epoch_ckpt_path(ckpt_dir, epoch)
    assert os.path.exists(path), f"checkpoint not found: {path}"
    state = _checkpointer().restore(path, abstract_state)
    master_print(f"resumed from checkpoint {path}")
    return state
