// vitax native data-path ops: JPEG decode + resample + crop/flip/normalize.
//
// TPU-native replacement for the reference's torchvision/PIL decode workers
// (reference run_vit_training.py:39-55,65-73: DataLoader worker processes doing
// libjpeg decode + RandomResizedCrop/Resize/CenterCrop via PIL). Here the whole
// per-image pixel path is one C++ call (libjpeg decode -> PIL-parity separable
// bicubic resample -> crop/flip -> output: ImageNet-normalized float32, or raw
// uint8 at 1/4 the buffer size when `normalize` is 0 and the train step
// normalizes on-device), plus a std::thread batch API so one ctypes call fills
// a whole local batch without touching the GIL.
//
// Resampling matches Pillow's ImagingResample algorithm (separable convolution,
// filter support scaled by the downscale factor, uint8 intermediate between the
// horizontal and vertical passes) with float64 coefficient math where Pillow
// uses int16 fixed point — outputs agree within 1 LSB (tests/test_native.py).
// Algorithm from Pillow (python-pillow/Pillow, src/libImaging/Resample.c),
// HPND license; re-derived here, not copied.
//
// Build: g++ -O3 -shared -fPIC -std=c++17 decode.cc -o libvitax_data.so -ljpeg -pthread
// (done automatically by vitax/_native/__init__.py).

#include <cstddef>
#include <cstdio>

#include <jpeglib.h>  // requires <cstddef>/<cstdio> first (uses size_t/FILE)

#include <algorithm>
#include <atomic>
#include <cmath>
#include <csetjmp>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// JPEG decode (libjpeg), with longjmp error recovery so corrupt/unsupported
// files return an error code instead of calling exit().
// ---------------------------------------------------------------------------

struct ErrMgr {
  jpeg_error_mgr pub;
  jmp_buf jb;
};

void err_exit(j_common_ptr cinfo) {
  ErrMgr* e = reinterpret_cast<ErrMgr*>(cinfo->err);
  longjmp(e->jb, 1);
}

void emit_nothing(j_common_ptr, int) {}

bool decode_jpeg_file(const char* path, std::vector<uint8_t>& rgb, int& w, int& h) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return false;
  jpeg_decompress_struct cinfo;
  ErrMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = err_exit;
  jerr.pub.emit_message = emit_nothing;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    std::fclose(f);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_stdio_src(&cinfo, f);
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = JCS_RGB;  // YCbCr/grayscale -> RGB; CMYK errors out
  jpeg_start_decompress(&cinfo);
  if (cinfo.output_components != 3) {
    jpeg_destroy_decompress(&cinfo);
    std::fclose(f);
    return false;
  }
  w = static_cast<int>(cinfo.output_width);
  h = static_cast<int>(cinfo.output_height);
  rgb.resize(static_cast<size_t>(w) * h * 3);
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = rgb.data() + static_cast<size_t>(cinfo.output_scanline) * w * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  std::fclose(f);
  return true;
}

bool read_jpeg_size(const char* path, int& w, int& h) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return false;
  jpeg_decompress_struct cinfo;
  ErrMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = err_exit;
  jerr.pub.emit_message = emit_nothing;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    std::fclose(f);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_stdio_src(&cinfo, f);
  jpeg_read_header(&cinfo, TRUE);
  w = static_cast<int>(cinfo.image_width);
  h = static_cast<int>(cinfo.image_height);
  jpeg_destroy_decompress(&cinfo);
  std::fclose(f);
  return true;
}

// Memory-source decode (jpeg_mem_src): the streaming data plane
// (vitax/data/stream/) hands shard *records* — JPEG bytes already in host
// memory — so the pixel path must not round-trip through the filesystem.
// Identical decode settings to decode_jpeg_file: outputs are bitwise equal
// for the same bytes (tests/test_stream.py pins this).
bool decode_jpeg_mem(const uint8_t* data, size_t len, std::vector<uint8_t>& rgb,
                     int& w, int& h) {
  jpeg_decompress_struct cinfo;
  ErrMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = err_exit;
  jerr.pub.emit_message = emit_nothing;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<unsigned char*>(data),
               static_cast<unsigned long>(len));
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  if (cinfo.output_components != 3) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  w = static_cast<int>(cinfo.output_width);
  h = static_cast<int>(cinfo.output_height);
  rgb.resize(static_cast<size_t>(w) * h * 3);
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = rgb.data() + static_cast<size_t>(cinfo.output_scanline) * w * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

bool read_jpeg_size_mem(const uint8_t* data, size_t len, int& w, int& h) {
  jpeg_decompress_struct cinfo;
  ErrMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = err_exit;
  jerr.pub.emit_message = emit_nothing;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<unsigned char*>(data),
               static_cast<unsigned long>(len));
  jpeg_read_header(&cinfo, TRUE);
  w = static_cast<int>(cinfo.image_width);
  h = static_cast<int>(cinfo.image_height);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

// ---------------------------------------------------------------------------
// PIL-parity separable resample (bicubic, Keys a=-0.5, support 2, antialias).
// ---------------------------------------------------------------------------

double bicubic_filter(double x) {
  const double a = -0.5;
  x = std::fabs(x);
  if (x < 1.0) return ((a + 2.0) * x - (a + 3.0)) * x * x + 1.0;
  if (x < 2.0) return ((((x - 5.0) * x + 8.0) * x - 4.0)) * a;
  return 0.0;
}

// Pillow precompute_coeffs: per output pixel, the [xmin, xmin+xmax) source
// window and normalized filter weights; support widens by the downscale factor.
int precompute_coeffs(int in_size, double in0, double in1, int out_size,
                      std::vector<int>& bounds, std::vector<double>& kk) {
  double scale = (in1 - in0) / out_size;
  double filterscale = scale < 1.0 ? 1.0 : scale;
  double support = 2.0 * filterscale;
  int ksize = static_cast<int>(std::ceil(support)) * 2 + 1;
  kk.assign(static_cast<size_t>(out_size) * ksize, 0.0);
  bounds.assign(static_cast<size_t>(out_size) * 2, 0);
  double ss = 1.0 / filterscale;
  for (int xx = 0; xx < out_size; xx++) {
    double center = in0 + (xx + 0.5) * scale;
    int xmin = static_cast<int>(center - support + 0.5);
    if (xmin < 0) xmin = 0;
    int xmax = static_cast<int>(center + support + 0.5);
    if (xmax > in_size) xmax = in_size;
    xmax -= xmin;
    double* k = &kk[static_cast<size_t>(xx) * ksize];
    double ww = 0.0;
    for (int x = 0; x < xmax; x++) {
      double wgt = bicubic_filter((x + xmin - center + 0.5) * ss);
      k[x] = wgt;
      ww += wgt;
    }
    if (ww != 0.0) {
      for (int x = 0; x < xmax; x++) k[x] /= ww;
    }
    bounds[xx * 2 + 0] = xmin;
    bounds[xx * 2 + 1] = xmax;
  }
  return ksize;
}

inline uint8_t clip8(double v) {
  long r = std::lround(v);
  if (r < 0) return 0;
  if (r > 255) return 255;
  return static_cast<uint8_t>(r);
}

// Resample src (w, h, RGB8) restricted to box [bx0,bx1)x[by0,by1) into
// (ow, oh). Two passes with a uint8 intermediate, exactly like Pillow.
void resample(const uint8_t* src, int w, int h, double bx0, double by0,
              double bx1, double by1, int ow, int oh, std::vector<uint8_t>& dst) {
  std::vector<int> bounds_h, bounds_v;
  std::vector<double> kk_h, kk_v;
  int ksize_h = precompute_coeffs(w, bx0, bx1, ow, bounds_h, kk_h);
  int ksize_v = precompute_coeffs(h, by0, by1, oh, bounds_v, kk_v);

  // horizontal pass over only the rows the vertical pass will read
  int ybox0 = bounds_v[0];
  int ybox1 = bounds_v[(oh - 1) * 2] + bounds_v[(oh - 1) * 2 + 1];
  std::vector<uint8_t> tmp(static_cast<size_t>(ybox1 - ybox0) * ow * 3);
  for (int y = ybox0; y < ybox1; y++) {
    const uint8_t* row = src + static_cast<size_t>(y) * w * 3;
    uint8_t* orow = tmp.data() + static_cast<size_t>(y - ybox0) * ow * 3;
    for (int xx = 0; xx < ow; xx++) {
      int xmin = bounds_h[xx * 2], xmax = bounds_h[xx * 2 + 1];
      const double* k = &kk_h[static_cast<size_t>(xx) * ksize_h];
      double s0 = 0.0, s1 = 0.0, s2 = 0.0;
      const uint8_t* p = row + static_cast<size_t>(xmin) * 3;
      for (int x = 0; x < xmax; x++, p += 3) {
        s0 += p[0] * k[x];
        s1 += p[1] * k[x];
        s2 += p[2] * k[x];
      }
      orow[xx * 3 + 0] = clip8(s0);
      orow[xx * 3 + 1] = clip8(s1);
      orow[xx * 3 + 2] = clip8(s2);
    }
  }

  // vertical pass
  dst.resize(static_cast<size_t>(oh) * ow * 3);
  for (int yy = 0; yy < oh; yy++) {
    int ymin = bounds_v[yy * 2] - ybox0, ymax = bounds_v[yy * 2 + 1];
    const double* k = &kk_v[static_cast<size_t>(yy) * ksize_v];
    uint8_t* orow = dst.data() + static_cast<size_t>(yy) * ow * 3;
    for (int xx = 0; xx < ow; xx++) {
      double s0 = 0.0, s1 = 0.0, s2 = 0.0;
      const uint8_t* p = tmp.data() + (static_cast<size_t>(ymin) * ow + xx) * 3;
      for (int y = 0; y < ymax; y++, p += static_cast<size_t>(ow) * 3) {
        s0 += p[0] * k[y];
        s1 += p[1] * k[y];
        s2 += p[2] * k[y];
      }
      orow[xx * 3 + 0] = clip8(s0);
      orow[xx * 3 + 1] = clip8(s1);
      orow[xx * 3 + 2] = clip8(s2);
    }
  }
}

// ---------------------------------------------------------------------------
// Pipelines (reference run_vit_training.py:39-55 semantics, after the random
// parameters have been drawn by the Python side).
// ---------------------------------------------------------------------------

const float kMean[3] = {0.485f, 0.456f, 0.406f};
const float kStd[3] = {0.229f, 0.224f, 0.225f};

// Write (size, size, 3) normalized float32, optionally h-flipped.
void normalize_out(const std::vector<uint8_t>& img, int size, int flip, float* out) {
  for (int y = 0; y < size; y++) {
    const uint8_t* row = img.data() + static_cast<size_t>(y) * size * 3;
    float* orow = out + static_cast<size_t>(y) * size * 3;
    for (int x = 0; x < size; x++) {
      int sx = flip ? (size - 1 - x) : x;
      const uint8_t* p = row + static_cast<size_t>(sx) * 3;
      float* o = orow + static_cast<size_t>(x) * 3;
      o[0] = (p[0] * (1.0f / 255.0f) - kMean[0]) / kStd[0];
      o[1] = (p[1] * (1.0f / 255.0f) - kMean[1]) / kStd[1];
      o[2] = (p[2] * (1.0f / 255.0f) - kMean[2]) / kStd[2];
    }
  }
}

// Write raw (size, size, 3) uint8, optionally h-flipped — the device-side
// normalization path: the train step divides/normalizes on the TPU, making
// the host->device transfer 4x smaller than float32.
void raw_out(const std::vector<uint8_t>& img, int size, int flip, uint8_t* out) {
  for (int y = 0; y < size; y++) {
    const uint8_t* row = img.data() + static_cast<size_t>(y) * size * 3;
    uint8_t* orow = out + static_cast<size_t>(y) * size * 3;
    if (!flip) {
      std::memcpy(orow, row, static_cast<size_t>(size) * 3);
      continue;
    }
    for (int x = 0; x < size; x++) {
      const uint8_t* p = row + static_cast<size_t>(size - 1 - x) * 3;
      orow[x * 3 + 0] = p[0];
      orow[x * 3 + 1] = p[1];
      orow[x * 3 + 2] = p[2];
    }
  }
}

// mode 0 (train): resize the (left, top, cw, ch) box to (out_size, out_size).
// mode 1 (val): resize shorter side to resize_to, center crop out_size
//               (zero-padding if smaller — transforms.center_crop parity).
// On success `pixels` holds (out_size, out_size, 3) uint8, pre-flip.
bool process_decoded(const std::vector<uint8_t>& rgb, int w, int h, int mode,
                     int left, int top, int cw, int ch, int out_size,
                     int resize_to, std::vector<uint8_t>& pixels) {
  if (mode == 0) {
    if (cw <= 0 || ch <= 0 || left < 0 || top < 0 || left + cw > w || top + ch > h)
      return false;
    resample(rgb.data(), w, h, left, top, left + cw, top + ch, out_size, out_size,
             pixels);
    return true;
  }
  // val: resize shorter side (transforms.resize_shorter parity)
  // std::rint = round-half-to-even under the default FP mode, matching
  // Python round() in transforms.resize_shorter for exact-.5 scales
  int new_w, new_h;
  if (w <= h) {
    new_w = resize_to;
    new_h = std::max(1L, std::lrint(static_cast<double>(resize_to) * h / w));
  } else {
    new_h = resize_to;
    new_w = std::max(1L, std::lrint(static_cast<double>(resize_to) * w / h));
  }
  std::vector<uint8_t> resized;
  resample(rgb.data(), w, h, 0.0, 0.0, w, h, new_w, new_h, resized);
  // center crop with zero pad
  pixels.assign(static_cast<size_t>(out_size) * out_size * 3, 0);
  int cl = (new_w - out_size) / 2, ct = (new_h - out_size) / 2;
  // crop window intersected with the image; destination offset when padding
  int x0 = std::max(cl, 0), y0 = std::max(ct, 0);
  int x1 = std::min(cl + out_size, new_w), y1 = std::min(ct + out_size, new_h);
  for (int y = y0; y < y1; y++) {
    std::memcpy(pixels.data() + (static_cast<size_t>(y - ct) * out_size + (x0 - cl)) * 3,
                resized.data() + (static_cast<size_t>(y) * new_w + x0) * 3,
                static_cast<size_t>(x1 - x0) * 3);
  }
  return true;
}

}  // namespace

extern "C" {

// Returns 0 on success.
int vitax_jpeg_size(const char* path, int* w, int* h) {
  return read_jpeg_size(path, *w, *h) ? 0 : 1;
}

// Decode + process one file into out[out_size*out_size*3]: float32 normalized
// when normalize != 0, else raw uint8. Returns 0 on success.
int vitax_process_file(const char* path, int mode, int left, int top, int cw,
                       int ch, int flip, int out_size, int resize_to,
                       int normalize, void* out) {
  std::vector<uint8_t> rgb;
  int w, h;
  if (!decode_jpeg_file(path, rgb, w, h)) return 1;
  std::vector<uint8_t> pixels;
  if (!process_decoded(rgb, w, h, mode, left, top, cw, ch, out_size, resize_to,
                       pixels))
    return 1;
  if (normalize)
    normalize_out(pixels, out_size, flip, static_cast<float*>(out));
  else
    raw_out(pixels, out_size, flip, static_cast<uint8_t*>(out));
  return 0;
}

// In-memory single record: decode + process JPEG bytes (a shard record or a
// /predict request body) exactly like vitax_process_file does a file.
// Returns 0 on success.
int vitax_process_mem(const uint8_t* data, int len, int mode, int left,
                      int top, int cw, int ch, int flip, int out_size,
                      int resize_to, int normalize, void* out) {
  std::vector<uint8_t> rgb;
  int w, h;
  if (!decode_jpeg_mem(data, static_cast<size_t>(len), rgb, w, h)) return 1;
  std::vector<uint8_t> pixels;
  if (!process_decoded(rgb, w, h, mode, left, top, cw, ch, out_size, resize_to,
                       pixels))
    return 1;
  if (normalize)
    normalize_out(pixels, out_size, flip, static_cast<float*>(out));
  else
    raw_out(pixels, out_size, flip, static_cast<uint8_t*>(out));
  return 0;
}

int vitax_jpeg_size_mem(const uint8_t* data, int len, int* w, int* h) {
  return read_jpeg_size_mem(data, static_cast<size_t>(len), *w, *h) ? 0 : 1;
}

// Batch: params is n x 6 int32 rows {mode, left, top, cw, ch, flip}; out is
// (n, out_size, out_size, 3) — float32 when normalize != 0, else uint8; fail
// is n uint8 flags (1 = this item failed and its slot is untouched — caller
// falls back per item). Work is spread over n_threads std::threads (no GIL
// involvement). Returns #failures.
int vitax_process_batch(const char** paths, int n, const int32_t* params,
                        int out_size, int resize_to, int normalize, void* out,
                        uint8_t* fail, int n_threads) {
  std::atomic<int> next(0), failures(0);
  size_t item = static_cast<size_t>(out_size) * out_size * 3;
  auto worker = [&]() {
    for (;;) {
      int i = next.fetch_add(1);
      if (i >= n) return;
      const int32_t* p = params + static_cast<size_t>(i) * 6;
      void* o = normalize
          ? static_cast<void*>(static_cast<float*>(out) + item * i)
          : static_cast<void*>(static_cast<uint8_t*>(out) + item * i);
      int ok = vitax_process_file(paths[i], p[0], p[1], p[2], p[3], p[4], p[5],
                                  out_size, resize_to, normalize, o);
      fail[i] = static_cast<uint8_t>(ok != 0);
      if (ok != 0) failures.fetch_add(1);
    }
  };
  int nt = std::max(1, std::min(n_threads, n));
  std::vector<std::thread> threads;
  threads.reserve(nt);
  for (int t = 0; t < nt; t++) threads.emplace_back(worker);
  for (auto& t : threads) t.join();
  return failures.load();
}

// Batch over in-memory records (the streaming data plane's hot path): one
// ctypes call decodes + transforms a whole local batch of shard records on a
// std::thread pool — no per-record Python, no GIL, no filesystem.
int vitax_process_batch_mem(const uint8_t** datas, const int32_t* lens, int n,
                            const int32_t* params, int out_size, int resize_to,
                            int normalize, void* out, uint8_t* fail,
                            int n_threads) {
  std::atomic<int> next(0), failures(0);
  size_t item = static_cast<size_t>(out_size) * out_size * 3;
  auto worker = [&]() {
    for (;;) {
      int i = next.fetch_add(1);
      if (i >= n) return;
      const int32_t* p = params + static_cast<size_t>(i) * 6;
      void* o = normalize
          ? static_cast<void*>(static_cast<float*>(out) + item * i)
          : static_cast<void*>(static_cast<uint8_t*>(out) + item * i);
      int ok = vitax_process_mem(datas[i], lens[i], p[0], p[1], p[2], p[3],
                                 p[4], p[5], out_size, resize_to, normalize, o);
      fail[i] = static_cast<uint8_t>(ok != 0);
      if (ok != 0) failures.fetch_add(1);
    }
  };
  int nt = std::max(1, std::min(n_threads, n));
  std::vector<std::thread> threads;
  threads.reserve(nt);
  for (int t = 0; t < nt; t++) threads.emplace_back(worker);
  for (auto& t : threads) t.join();
  return failures.load();
}

}  // extern "C"
