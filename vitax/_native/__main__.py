"""Explicit build entry: `python -m vitax._native` compiles the data-path
library ahead of time (it otherwise builds lazily on first use). Exit 0 on
success, 1 if the toolchain/libjpeg is unavailable."""

import sys

from vitax import _native

if __name__ == "__main__":
    lib = _native.load()
    if lib is None:
        print("native library unavailable (g++ or libjpeg missing)", file=sys.stderr)
        sys.exit(1)
    print(f"native library ready: {_native._SO}")
