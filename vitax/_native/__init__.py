"""Build + load the native (C++) data-path library.

The .so is compiled from decode.cc on first use (g++ -O3, links libjpeg) and
cached next to the source; a stale .so (older than the source) is rebuilt.
Everything degrades gracefully: if the toolchain or libjpeg is missing,
`load()` returns None and callers fall back to the PIL path
(vitax/data/transforms.py).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "decode.cc")
_SO = os.path.join(_DIR, "libvitax_data.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_failed = False


def _build() -> None:
    tmp = _SO + f".tmp{os.getpid()}"
    # -march=x86-64-v2, not native: the cache can live on a filesystem shared
    # by heterogeneous workers, and a newer-ISA host's build would SIGILL the
    # older hosts (v2 = SSE4.2/POPCNT, safe on any TPU-VM fleet; non-x86
    # falls back to the compiler default)
    march = ["-march=x86-64-v2"] if os.uname().machine in ("x86_64", "amd64") else []
    base = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
            _SRC, "-o", tmp, "-ljpeg", "-pthread"]
    try:
        subprocess.run(base[:2] + march + base[2:], check=True, capture_output=True)
    except subprocess.CalledProcessError:
        if not march:
            raise
        # GCC < 11 doesn't know x86-64-v2; plain x86-64 is still ISA-safe
        subprocess.run(base, check=True, capture_output=True)
    os.replace(tmp, _SO)  # atomic: concurrent builders race benignly


def _prototype(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.vitax_jpeg_size.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int)]
    lib.vitax_jpeg_size.restype = ctypes.c_int
    lib.vitax_process_file.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_void_p]
    lib.vitax_process_file.restype = ctypes.c_int
    lib.vitax_process_batch.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), ctypes.c_int,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8),
        ctypes.c_int]
    lib.vitax_process_batch.restype = ctypes.c_int
    # memory-source entry points (streaming shard records, serve request
    # bodies). A stale .so built before they existed degrades gracefully:
    # vitax/data/native.py checks has_mem_api() and falls back to PIL.
    if hasattr(lib, "vitax_process_mem"):
        lib.vitax_process_mem.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_void_p]
        lib.vitax_process_mem.restype = ctypes.c_int
        lib.vitax_jpeg_size_mem.argtypes = [
            ctypes.c_char_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int)]
        lib.vitax_jpeg_size_mem.restype = ctypes.c_int
        lib.vitax_process_batch_mem.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int, ctypes.POINTER(ctypes.c_int32), ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int]
        lib.vitax_process_batch_mem.restype = ctypes.c_int
    return lib


def load() -> Optional[ctypes.CDLL]:
    """The loaded library, building it if needed; None if unavailable."""
    global _lib, _failed
    if _lib is not None or _failed:
        return _lib
    with _lock:
        if _lib is not None or _failed:
            return _lib
        try:
            if (not os.path.exists(_SO)
                    or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
                _log("vitax native data path: compiling decode.cc (one-time, "
                     "a few seconds; pre-build with `python -m vitax._native`)")
                _build()
            _lib = _prototype(ctypes.CDLL(_SO))
        except Exception as e:
            _log(f"vitax native data path unavailable ({type(e).__name__}); "
                 "falling back to the slower PIL pipeline")
            _failed = True
    return _lib


def _log(msg: str) -> None:
    # NOT master_print: that queries jax.process_index(), which would trigger
    # (and on a dead transport, hang in) backend init from the data path.
    # The env var is authoritative when set; otherwise every process logs the
    # one-time build line, which is acceptable.
    if os.environ.get("JAX_PROCESS_ID", "0") == "0":
        print(msg, flush=True)


def available() -> bool:
    return load() is not None
