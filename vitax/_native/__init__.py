"""Build + load the native (C++) data-path library.

The .so is compiled from decode.cc on first use (g++ -O3, links libjpeg) and
cached next to the source; a stale .so (older than the source) is rebuilt.
Everything degrades gracefully: if the toolchain or libjpeg is missing,
`load()` returns None and callers fall back to the PIL path
(vitax/data/transforms.py).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "decode.cc")
_SO = os.path.join(_DIR, "libvitax_data.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_failed = False


def _build() -> None:
    tmp = _SO + f".tmp{os.getpid()}"
    cmd = [
        "g++", "-O3", "-march=native", "-shared", "-fPIC", "-std=c++17",
        _SRC, "-o", tmp, "-ljpeg", "-pthread",
    ]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(tmp, _SO)  # atomic: concurrent builders race benignly


def _prototype(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.vitax_jpeg_size.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int)]
    lib.vitax_jpeg_size.restype = ctypes.c_int
    lib.vitax_process_file.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_void_p]
    lib.vitax_process_file.restype = ctypes.c_int
    lib.vitax_process_batch.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), ctypes.c_int,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8),
        ctypes.c_int]
    lib.vitax_process_batch.restype = ctypes.c_int
    return lib


def load() -> Optional[ctypes.CDLL]:
    """The loaded library, building it if needed; None if unavailable."""
    global _lib, _failed
    if _lib is not None or _failed:
        return _lib
    with _lock:
        if _lib is not None or _failed:
            return _lib
        try:
            if (not os.path.exists(_SO)
                    or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
                _build()
            _lib = _prototype(ctypes.CDLL(_SO))
        except Exception:
            _failed = True
    return _lib


def available() -> bool:
    return load() is not None
