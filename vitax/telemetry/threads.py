"""Background-thread crash visibility + bounded shutdown joins.

vitax runs ~a dozen named background threads (batcher, watchdog, fleet
health, loader producers, heartbeats, snapshot writer, peer receiver).
By default an uncaught exception in any of them prints a traceback and
the thread dies — the process keeps running minus one vital organ, which
at pod scale reads as "the log stopped" hours later. Two primitives fix
the two halves of that failure mode:

- `install_thread_excepthook(recorder, rank)` routes every uncaught
  background-thread exception through one `threading.excepthook`: a
  rank-tagged traceback to stderr plus a `kind:"thread_crash"` JSONL
  event when a Recorder is attached (surfaced as the `thread_crashes`
  counter in tools/metrics_report.py --json). SystemExit keeps its
  stdlib meaning (threads may exit deliberately).

- `join_or_warn(thread, timeout)` bounds a shutdown join: a wedged
  worker gets `timeout` seconds, then a loud leaked-thread warning on
  stderr instead of blocking process exit forever. Used by
  SnapshotPipeline.close() and PeerReplicator.stop().

Both are host-side and jax-free; safe to import from anywhere.
"""

from __future__ import annotations

import sys
import threading
import traceback
from typing import Optional

_state_lock = threading.Lock()
_crash_count = 0
_installed = False
_recorder = None
_rank = 0


def install_thread_excepthook(recorder=None, rank: int = 0) -> None:
    """Install (idempotently) the crash hook; rebinds recorder/rank when
    called again — the train loop installs early with recorder=None, then
    re-installs once the Recorder exists."""
    global _installed
    with _state_lock:
        globals()["_recorder"] = recorder
        globals()["_rank"] = int(rank)
        already = _installed
        _installed = True
    if not already:
        threading.excepthook = _excepthook


def thread_crash_count() -> int:
    """Uncaught background-thread exceptions seen since install (tests and
    shutdown paths assert this stays 0 on healthy runs)."""
    with _state_lock:
        return _crash_count


def _excepthook(args) -> None:
    global _crash_count
    if args.exc_type is SystemExit:
        return  # deliberate thread exit — same semantics as the default hook
    with _state_lock:
        _crash_count += 1
        recorder, rank = _recorder, _rank
    name = args.thread.name if args.thread is not None else "unknown"
    tb = "".join(traceback.format_exception(
        args.exc_type, args.exc_value, args.exc_traceback))
    print(f"[vitax.threads rank {rank}] uncaught exception in background "
          f"thread `{name}`:\n{tb}", file=sys.stderr, flush=True)
    if recorder is not None:
        try:  # JSONL sinks flush per record — the event survives a dying run
            recorder.event(
                "thread_crash", rank=rank, thread=name,
                error=f"{args.exc_type.__name__}: {args.exc_value}")
        except Exception as e:  # noqa: BLE001 — a broken sink must not recurse
            print(f"[vitax.threads rank {rank}] thread_crash event sink "
                  f"failed: {type(e).__name__}: {e}",
                  file=sys.stderr, flush=True)


def join_or_warn(thread: Optional[threading.Thread], timeout: float,
                 what: Optional[str] = None, rank: int = 0) -> bool:
    """Join `thread` for at most `timeout` seconds. Returns True when the
    thread is gone; on timeout prints a loud leaked-thread warning and
    returns False — shutdown paths must keep going, not hang."""
    if thread is None or not thread.is_alive():
        return True
    thread.join(timeout=timeout)
    if thread.is_alive():
        name = what or thread.name
        print(f"[vitax.threads rank {rank}] thread `{name}` still alive "
              f"{timeout:.0f}s after shutdown was requested — leaking it "
              "rather than blocking process exit (inspect with the "
              "watchdog's all-thread stack dump)", file=sys.stderr,
              flush=True)
        return False
    return True
