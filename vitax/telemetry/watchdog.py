"""Hang watchdog: detect a stalled training loop, dump the evidence, and —
when asked — escalate so a supervisor can restart the job.

Large jobs die quietly: a wedged collective, a deadlocked host thread or a
starved input queue all look like "the log stopped". The watchdog is a
daemon thread the train loop pets once per step; if `timeout_s` passes
without a pet it dumps — WITHOUT killing the job —

  - the Python stacks of every live thread (where is the loop actually
    stuck: `q.get`? a device fetch? a checkpoint write?), and
  - the live device memory stats (an OOM-thrashing device and a dead
    interconnect hang differently),

rank-tagged to stderr on every host, plus a structured `kind="hang"` JSONL
event through the Recorder where one is attached (rank 0). It fires at most
once per stall: after a dump it stays quiet until the next pet proves the
loop moved again (MegaScale-style hang detection, Jiang et al. 2024).

Escalation (--hang_action checkpoint_exit): after the dump the watchdog sets
a STICKY escalation flag and emits a `kind="hang_escalation"` event. The
train loop polls the flag at the step boundary — the same flag-then-poll
design as vitax/train/preempt.py, because the watchdog thread must never
touch device state — takes an emergency committed checkpoint, and exits with
EXIT_HANG (42) for the supervisor (vitax/supervise.py) to restart. If the
loop never reaches a boundary (the hang is real and hard) the watchdog
itself `os._exit`s with the same code once `hard_deadline_s` more seconds
pass, so a wedged device cannot pin the process forever; the loop's
`acknowledge_escalation()` re-arms that deadline to protect the emergency
save in progress. With the default --hang_action dump the job is left
running, exactly as before.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from typing import Callable, Optional

# The escalation exit-code contract: a supervisor treats this as "the child
# asked to be restarted after a hang" (any committed emergency checkpoint is
# picked up by --resume_epoch -1). Distinct from crash codes and from 0.
EXIT_HANG = 42

HANG_ACTIONS = ("dump", "checkpoint_exit")


def dump_all_stacks() -> str:
    """Python stacks of every live thread, tagged with thread names."""
    names = {t.ident: t.name for t in threading.enumerate()}
    parts = []
    for ident, frame in sys._current_frames().items():
        name = names.get(ident, "unknown")
        stack = "".join(traceback.format_stack(frame))
        parts.append(f"--- thread {name} (ident {ident}) ---\n{stack}")
    return "\n".join(parts)


class Watchdog:
    """Heartbeat monitor. `start()` it, `pet()` it every step, `stop()` it.

    `on_fire(payload: dict)` runs in the watchdog thread on each dump (the
    loop wires it to Recorder.event("hang", ...)); `fire_count` counts dumps
    over the watchdog's lifetime (tests assert it stays 0 on healthy runs).

    With `action="checkpoint_exit"`, the first dump of a stall also requests
    escalation: `escalation_requested()` turns (stickily) True for the loop
    to poll, `on_escalate(payload)` runs once, and a hard deadline of
    `hard_deadline_s` (default 2 x timeout_s) starts ticking — if neither
    `acknowledge_escalation()` nor `stop()` arrives in time, the watchdog
    hard-exits the process with EXIT_HANG (`hard_exit` is injectable so
    tests never die for real).
    """

    def __init__(self, timeout_s: float,
                 on_fire: Optional[Callable[[dict], None]] = None,
                 rank: int = 0, poll_s: Optional[float] = None,
                 action: str = "dump",
                 hard_deadline_s: Optional[float] = None,
                 on_escalate: Optional[Callable[[dict], None]] = None,
                 on_hard_exit: Optional[Callable[[dict], None]] = None,
                 hard_exit: Callable[[int], None] = os._exit):
        assert timeout_s > 0, timeout_s
        assert action in HANG_ACTIONS, action
        self.timeout_s = float(timeout_s)
        self.on_fire = on_fire
        self.rank = rank
        self.action = action
        self.hard_deadline_s = (float(hard_deadline_s) if hard_deadline_s
                                else 2.0 * self.timeout_s)
        self.on_escalate = on_escalate
        # last-words hook, run right before the hard-deadline os._exit: the
        # train loop wires it to a flushed kind:"hang_hard_exit" telemetry
        # event + the control plane's fault publication, so peers learn the
        # cause instead of just losing a heartbeat. Assignable after
        # construction (the control plane is built later than the watchdog).
        self.on_hard_exit = on_hard_exit
        self._hard_exit = hard_exit
        # poll often enough to notice promptly, rarely enough to cost nothing
        self.poll_s = poll_s if poll_s else min(max(timeout_s / 4.0, 0.05), 5.0)
        self.fire_count = 0
        # guards the pet/deadline words shared between the loop thread (pet,
        # request/acknowledge escalation, arm_exit_deadline) and _run: float
        # stores are atomic under the GIL, but the dump-once logic needs
        # _last_pet and _fired_since_pet to move together (VTX200)
        self._lock = threading.Lock()
        self._last_pet = time.monotonic()
        self._fired_since_pet = False
        self._escalated = threading.Event()
        self._hard_deadline_at: Optional[float] = None  # monotonic
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def running(self) -> bool:
        """Has start() been called? The train loop arms the watchdog at the
        FIRST dispatch return — i.e. after XLA compilation — so the hang
        window never spans compile time and --hang_timeout_s can be far
        smaller than a 10B-scale compile (minutes). Before that, a stall is
        "still compiling", not a hang."""
        return self._thread is not None

    def start(self) -> "Watchdog":
        with self._lock:
            self._last_pet = time.monotonic()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="vitax-watchdog")
        self._thread.start()
        return self

    def pet(self) -> None:
        """The loop made progress; re-arm the dump (NOT the escalation: once
        requested, the loop must checkpoint and exit — a step that limps
        through after a real hang is not a healthy run)."""
        with self._lock:
            self._last_pet = time.monotonic()
            self._fired_since_pet = False

    def escalation_requested(self) -> bool:
        """Sticky: True once a stall under action="checkpoint_exit" dumped."""
        return self._escalated.is_set()

    def request_escalation(self, reason: str = "external") -> None:
        """Escalate from OUTSIDE the stall detector (the control plane's
        peer-loss path, vitax/train/control.py): arm the hard deadline and
        raise the sticky flag exactly like a hang-dump escalation, minus the
        dump — the caller already knows the cause. Idempotent: a watchdog
        that escalated on its own keeps its earlier deadline."""
        if self._escalated.is_set():
            return
        # same ordering contract as _escalate: deadline armed BEFORE the flag
        with self._lock:
            self._hard_deadline_at = time.monotonic() + self.hard_deadline_s
        self._escalated.set()
        if self.on_escalate is not None:
            try:
                self.on_escalate({"reason": reason,
                                  "timeout_s": self.timeout_s,
                                  "exit_code": EXIT_HANG,
                                  "hard_deadline_s": self.hard_deadline_s})
            except Exception as e:  # noqa: BLE001
                print(f"[vitax.watchdog rank {self.rank}] on_escalate sink "
                      f"failed: {type(e).__name__}: {e}",
                      file=sys.stderr, flush=True)

    def acknowledge_escalation(self) -> None:
        """The loop saw the flag and is taking the emergency checkpoint:
        push the hard-exit deadline out by another hard_deadline_s so the
        save itself runs under the same bounded protection."""
        with self._lock:
            self._hard_deadline_at = time.monotonic() + self.hard_deadline_s

    def arm_exit_deadline(self) -> None:
        """Bound a blocking exit-path collective (the coordinated preemption
        barrier in train/loop.py): arm the hard-exit deadline WITHOUT
        requesting escalation — works under any --hang_action. A peer that
        died mid-save would otherwise wedge this host in the barrier
        forever; with the deadline armed the watchdog hard-exits EXIT_HANG
        and the supervisor restarts from the checkpoint this host just
        committed. A clean barrier return is followed by stop(), which
        halts the watchdog thread long before the deadline can fire."""
        with self._lock:
            self._hard_deadline_at = time.monotonic() + self.hard_deadline_s

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.poll_s + 1.0)

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            now = time.monotonic()
            with self._lock:
                stalled = now - self._last_pet
                fire = (stalled >= self.timeout_s
                        and not self._fired_since_pet)
                if fire:
                    self._fired_since_pet = True  # once per stall, not poll
                hard = (self._hard_deadline_at is not None
                        and now >= self._hard_deadline_at)
            # dump and exit OUTSIDE the lock: both run user sinks that may
            # call pet()/acknowledge_escalation() back into us
            if fire:
                self._fire(stalled)
            if hard:
                self._hard_exit_now()

    def _hard_exit_now(self) -> None:
        print(f"[vitax.watchdog rank {self.rank}] escalation deadline "
              f"({self.hard_deadline_s:.1f}s) passed without the loop "
              f"reaching a step boundary — hard-exiting with code "
              f"{EXIT_HANG} for the supervisor", file=sys.stderr, flush=True)
        if self.on_hard_exit is not None:
            try:  # JSONL sinks flush per record: the event survives os._exit
                self.on_hard_exit({"rank": self.rank,
                                   "exit_code": EXIT_HANG,
                                   "hard_deadline_s": self.hard_deadline_s})
            except Exception as e:  # noqa: BLE001 — last words must not block the exit
                print(f"[vitax.watchdog rank {self.rank}] on_hard_exit sink "
                      f"failed: {type(e).__name__}: {e}",
                      file=sys.stderr, flush=True)
        with self._lock:
            self._hard_deadline_at = None  # a fake test exit returns; disarm
        self._hard_exit(EXIT_HANG)

    def _fire(self, stalled_s: float) -> None:
        self.fire_count += 1
        from vitax.telemetry.record import memory_stats_bytes
        try:
            mem = memory_stats_bytes()
        except Exception as e:  # noqa: BLE001 — a dead backend must not mute the dump
            mem = {"error": f"{type(e).__name__}: {e}"}
        stacks = dump_all_stacks()
        escalating = (self.action == "checkpoint_exit"
                      and not self._escalated.is_set())
        verdict = (f"escalating: emergency checkpoint + exit {EXIT_HANG} at "
                   f"the next step boundary (hard deadline "
                   f"{self.hard_deadline_s:.1f}s)" if escalating
                   else "job left running")
        print(f"[vitax.watchdog rank {self.rank}] no step progress for "
              f"{stalled_s:.1f}s (timeout {self.timeout_s:.1f}s); dumping "
              f"all-thread stacks + device memory ({verdict})\n"
              f"{stacks}\n[vitax.watchdog rank {self.rank}] memory: {mem}",
              file=sys.stderr, flush=True)
        if self.on_fire is not None:
            try:
                self.on_fire({"stalled_s": round(stalled_s, 3),
                              "timeout_s": self.timeout_s,
                              "stacks": stacks, **mem})
            except Exception as e:  # noqa: BLE001
                print(f"[vitax.watchdog rank {self.rank}] on_fire sink "
                      f"failed: {type(e).__name__}: {e}",
                      file=sys.stderr, flush=True)
        if escalating:
            self._escalate(stalled_s)

    def _escalate(self, stalled_s: float) -> None:
        # order matters: arm the deadline BEFORE raising the flag, so a loop
        # that polls immediately can only ever see a flag whose deadline is
        # already running (acknowledge then safely re-arms it)
        with self._lock:
            self._hard_deadline_at = time.monotonic() + self.hard_deadline_s
        self._escalated.set()
        if self.on_escalate is not None:
            try:  # JSONL sinks flush per record: the event survives the exit
                self.on_escalate({"stalled_s": round(stalled_s, 3),
                                  "timeout_s": self.timeout_s,
                                  "exit_code": EXIT_HANG,
                                  "hard_deadline_s": self.hard_deadline_s})
            except Exception as e:  # noqa: BLE001
                print(f"[vitax.watchdog rank {self.rank}] on_escalate sink "
                      f"failed: {type(e).__name__}: {e}",
                      file=sys.stderr, flush=True)
