"""Hang watchdog: detect a stalled training loop and dump the evidence.

Large jobs die quietly: a wedged collective, a deadlocked host thread or a
starved input queue all look like "the log stopped". The watchdog is a
daemon thread the train loop pets once per step; if `timeout_s` passes
without a pet it dumps — WITHOUT killing the job —

  - the Python stacks of every live thread (where is the loop actually
    stuck: `q.get`? a device fetch? a checkpoint write?), and
  - the live device memory stats (an OOM-thrashing device and a dead
    interconnect hang differently),

rank-tagged to stderr on every host, plus a structured `kind="hang"` JSONL
event through the Recorder where one is attached (rank 0). It fires at most
once per stall: after a dump it stays quiet until the next pet proves the
loop moved again (MegaScale-style hang detection, Jiang et al. 2024 — the
job is left alive for the operator or an external supervisor to decide).
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from typing import Callable, Optional


def dump_all_stacks() -> str:
    """Python stacks of every live thread, tagged with thread names."""
    names = {t.ident: t.name for t in threading.enumerate()}
    parts = []
    for ident, frame in sys._current_frames().items():
        name = names.get(ident, "unknown")
        stack = "".join(traceback.format_stack(frame))
        parts.append(f"--- thread {name} (ident {ident}) ---\n{stack}")
    return "\n".join(parts)


class Watchdog:
    """Heartbeat monitor. `start()` it, `pet()` it every step, `stop()` it.

    `on_fire(payload: dict)` runs in the watchdog thread on each dump (the
    loop wires it to Recorder.event("hang", ...)); `fire_count` counts dumps
    over the watchdog's lifetime (tests assert it stays 0 on healthy runs).
    """

    def __init__(self, timeout_s: float,
                 on_fire: Optional[Callable[[dict], None]] = None,
                 rank: int = 0, poll_s: Optional[float] = None):
        assert timeout_s > 0, timeout_s
        self.timeout_s = float(timeout_s)
        self.on_fire = on_fire
        self.rank = rank
        # poll often enough to notice promptly, rarely enough to cost nothing
        self.poll_s = poll_s if poll_s else min(max(timeout_s / 4.0, 0.05), 5.0)
        self.fire_count = 0
        self._last_pet = time.monotonic()
        self._fired_since_pet = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "Watchdog":
        self._last_pet = time.monotonic()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="vitax-watchdog")
        self._thread.start()
        return self

    def pet(self) -> None:
        """The loop made progress; re-arm."""
        self._last_pet = time.monotonic()
        self._fired_since_pet = False

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.poll_s + 1.0)

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            stalled = time.monotonic() - self._last_pet
            if stalled >= self.timeout_s and not self._fired_since_pet:
                self._fired_since_pet = True  # once per stall, not per poll
                self._fire(stalled)

    def _fire(self, stalled_s: float) -> None:
        self.fire_count += 1
        from vitax.telemetry.record import memory_stats_bytes
        try:
            mem = memory_stats_bytes()
        except Exception as e:  # noqa: BLE001 — a dead backend must not mute the dump
            mem = {"error": f"{type(e).__name__}: {e}"}
        stacks = dump_all_stacks()
        print(f"[vitax.watchdog rank {self.rank}] no step progress for "
              f"{stalled_s:.1f}s (timeout {self.timeout_s:.1f}s); dumping "
              f"all-thread stacks + device memory (job left running)\n"
              f"{stacks}\n[vitax.watchdog rank {self.rank}] memory: {mem}",
              file=sys.stderr, flush=True)
        if self.on_fire is not None:
            try:
                self.on_fire({"stalled_s": round(stalled_s, 3),
                              "timeout_s": self.timeout_s,
                              "stacks": stacks, **mem})
            except Exception as e:  # noqa: BLE001
                print(f"[vitax.watchdog rank {self.rank}] on_fire sink "
                      f"failed: {type(e).__name__}: {e}",
                      file=sys.stderr, flush=True)
