"""vitax.telemetry — structured observability for training runs.

Subsystem map:
  flops      analytic model-FLOPs accounting + TPU peak table -> MFU
  sinks      JSONL event log (always-on) + optional TensorBoard mirror
  record     Recorder: versioned per-step records fanned out to sinks
  schema     validators for the perf-data files CI folds into a trajectory
             (bench payloads, BENCH_r*.json, autotune trial JSONL)
  watchdog   heartbeat hang detector: all-thread stack + memory dumps
  threads    thread-crash excepthook (kind:"thread_crash" events) and
             bounded shutdown joins with leaked-thread warnings

Wired through the training stack by vitax/train/loop.py (Recorder lifecycle,
per-log-step records, watchdog pets), vitax/data/loader.py (host batch-wait
accounting) and vitax/config.py (--metrics_dir, --tensorboard,
--peak_tflops, --hang_timeout_s). Everything is host-side: telemetry on or
off, the compiled step program is identical.
"""

from vitax.telemetry.flops import (  # noqa: F401
    PEAK_TFLOPS, detect_peak_tflops, mfu, model_flops_per_image,
    model_flops_per_step)
from vitax.telemetry.record import (  # noqa: F401
    REQUIRED_STEP_KEYS, SCHEMA_VERSION, Recorder, build_recorder)
from vitax.telemetry.schema import (  # noqa: F401
    validate_autotune_trial, validate_bench_file, validate_bench_payload,
    validate_trials_file)
from vitax.telemetry.sinks import (  # noqa: F401
    JsonlSink, TensorBoardSink, make_tensorboard_sink)
from vitax.telemetry.threads import (  # noqa: F401
    install_thread_excepthook, join_or_warn, thread_crash_count)
from vitax.telemetry.watchdog import Watchdog, dump_all_stacks  # noqa: F401
