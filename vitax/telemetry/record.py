"""Recorder: structured per-step run records, fanned out to pluggable sinks.

One `record_step` per log step turns the loop's host-side measurements into a
versioned, machine-readable record (schema 1):

    schema, time, step, epoch, step_in_epoch, loss, lr, grad_norm,
    sec_per_iter, images_per_sec, tokens_per_sec, data_wait_s, ckpt_stall_s,
    opt_update_s, mfu, mem_used_bytes, mem_peak_bytes[, mem_limit_bytes]

MFU comes from the analytic FLOPs model (telemetry/flops.py) over the
measured sec/iter — no device work, no tracing. `event()` appends
non-step records (watchdog hang dumps, run metadata) to the same JSONL
stream, tagged with `kind`.

Everything here is host-side by construction: building a Recorder, or not,
cannot change the compiled step program or add device->host syncs
(tests/test_telemetry.py pins that with a lowered-program equality check).
"""

from __future__ import annotations

import os
import sys
import time
from typing import Optional

from vitax.telemetry.flops import (
    detect_peak_tflops, mfu, model_flops_per_step)

SCHEMA_VERSION = 1

# acceptance contract of a step record: tools/metrics_report.py and the
# tier-1 round-trip test key off this exact set
REQUIRED_STEP_KEYS = (
    "schema", "step", "loss", "sec_per_iter", "data_wait_s", "mfu",
    "mem_used_bytes",
)


class Recorder:
    """Fan structured records out to sinks; owns the run's MFU constants."""

    def __init__(self, cfg, sinks, n_devices: int, device_kind: str,
                 rank: int = 0):
        self.cfg = cfg
        self.sinks = list(sinks)
        self.n_devices = n_devices
        self.device_kind = device_kind
        self.rank = rank
        self.peak_tflops = detect_peak_tflops(
            device_kind, getattr(cfg, "peak_tflops", 0.0))
        self.flops_per_step = model_flops_per_step(cfg)
        self.tokens_per_step = cfg.batch_size * cfg.num_patches

    def _write(self, record: dict) -> None:
        for sink in self.sinks:
            try:
                sink.write(record)
            except Exception as e:  # noqa: BLE001 — telemetry must not kill training
                print(f"vitax.telemetry: sink {type(sink).__name__} failed "
                      f"({type(e).__name__}: {e})", file=sys.stderr, flush=True)

    def record_step(self, *, step: int, epoch: int, step_in_epoch: int,
                    loss: float, lr: float, sec_per_iter: float,
                    data_wait_s: float, grad_norm: Optional[float] = None,
                    ckpt_stall_s: float = 0.0, opt_update_s: float = 0.0,
                    ) -> dict:
        """One record per log step. `sec_per_iter` / `data_wait_s` /
        `ckpt_stall_s` are the per-step averages since the previous record;
        `step` is the global optimizer-step count (monotonically increasing
        across epochs). `ckpt_stall_s` is the zero-stall snapshot pipeline's
        staging time charged to the loop thread (vitax/checkpoint/
        snapshot.py) — the acceptance pin keeps it ~0 on non-final saves.
        `opt_update_s` is the fenced wall time of the optimizer-phase probe
        (vitax/train/step.py make_opt_probe), measured at log steps only —
        the fused-optimizer win as a number, not an assertion."""
        record = {
            "schema": SCHEMA_VERSION,
            "time": time.time(),
            "step": int(step),
            "epoch": int(epoch),
            "step_in_epoch": int(step_in_epoch),
            "loss": float(loss),
            "lr": float(lr),
            "sec_per_iter": float(sec_per_iter),
            "images_per_sec": (self.cfg.batch_size / sec_per_iter
                               if sec_per_iter > 0 else 0.0),
            "tokens_per_sec": (self.tokens_per_step / sec_per_iter
                               if sec_per_iter > 0 else 0.0),
            "data_wait_s": float(data_wait_s),
            "ckpt_stall_s": float(ckpt_stall_s),
            "opt_update_s": float(opt_update_s),
            "mfu": mfu(self.cfg, sec_per_iter, self.n_devices,
                       self.peak_tflops),
        }
        if grad_norm is not None:
            record["grad_norm"] = float(grad_norm)
        record.update(memory_stats_bytes())
        self._write(record)
        return record

    def event(self, kind: str, **payload) -> dict:
        """Non-step record (watchdog dump, run metadata), JSONL-tagged with
        `kind`; the TensorBoard sink ignores these by design."""
        record = {"schema": SCHEMA_VERSION, "time": time.time(),
                  "kind": kind, "rank": self.rank, **payload}
        self._write(record)
        return record

    def close(self) -> None:
        for sink in self.sinks:
            try:
                sink.close()
            except Exception:  # noqa: BLE001 # vtx: ignore[VTX106] a failing sink must not break the others' close
                pass


def memory_stats_bytes() -> dict:
    """Schema-keyed HBM stats (vitax/utils/logging.py memory_stats_dict,
    renamed to the record's mem_*_bytes fields). mem_used_bytes is always
    present — 0 when the backend exposes no stats (CPU) — because the record
    contract promises the key; peak/limit appear only when reported."""
    from vitax.utils.logging import memory_stats_dict
    stats = memory_stats_dict()
    out = {"mem_used_bytes": int(stats.get("bytes_in_use", 0))}
    if stats.get("peak_bytes_in_use"):
        out["mem_peak_bytes"] = int(stats["peak_bytes_in_use"])
    if stats.get("bytes_limit"):
        out["mem_limit_bytes"] = int(stats["bytes_limit"])
    return out


def build_recorder(cfg, n_devices: int, device_kind: str,
                   rank: int = 0) -> Optional[Recorder]:
    """Recorder for this run, or None when telemetry is off.

    None when --metrics_dir is unset, on non-zero ranks (process 0 owns the
    global step records; the watchdog stays per-rank via stderr), or — fail
    soft, never crash a run over its observability — when metrics_dir cannot
    be created or written."""
    metrics_dir = getattr(cfg, "metrics_dir", "") or ""
    if not metrics_dir or rank != 0:
        return None
    from vitax.telemetry.sinks import JsonlSink
    try:
        os.makedirs(metrics_dir, exist_ok=True)
        sinks = [JsonlSink(os.path.join(metrics_dir, "metrics.jsonl"))]
    except OSError as e:
        print(f"vitax.telemetry: --metrics_dir {metrics_dir!r} is not "
              f"writable ({e}); telemetry disabled for this run",
              file=sys.stderr, flush=True)
        return None
    if getattr(cfg, "tensorboard", False):
        from vitax.telemetry.sinks import make_tensorboard_sink
        tb = make_tensorboard_sink(os.path.join(metrics_dir, "tb"))
        if tb is not None:
            sinks.append(tb)
    return Recorder(cfg, sinks, n_devices, device_kind, rank=rank)
