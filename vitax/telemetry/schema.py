"""Schema validation for the perf-data files CI folds into a trajectory:
bench result payloads (BENCH_r*.json / bench.jsonl) and autotune trial
JSONL (kind:"autotune_trial").

Validators return a list of error strings (empty = valid) instead of
raising, so tools/perf_gate.py --validate and tools/lint.sh can report every
problem in one pass. The contracts guarded here:

  - bench payload: the ONE JSON line bench.py prints — metric/value/unit/
    vs_baseline always present; a measured (non-error) payload must carry
    the full resolved `knobs` object (KNOB_PAYLOAD_KEYS) so the trajectory
    can tell whether two numbers are comparable. Historical payloads
    (BENCH_r02 and earlier) predate the knobs object; absence is legal,
    a *malformed* knobs object is not.
  - autotune trial: schema 1, monotone trial ids within a file, phase and
    pruned_by drawn from closed vocabularies, knobs complete.
"""

from __future__ import annotations

import json
from typing import List, Optional

from vitax.tune.knobs import KNOB_PAYLOAD_KEYS

TRIAL_PHASES = ("analytic", "compile", "measure")
PRUNED_BY_VALUES = (None, "invalid", "cost_rank", "hbm", "hbm_estimate",
                    "compile_error", "halving", "run_error")

_KNOB_TYPES = {
    "batch_per_chip": int,
    "remat_policy": str,
    "scan_blocks": bool,
    "scan_unroll": int,
    "remat_window": int,
    "grad_ckpt": bool,
    "use_flash_attention": bool,
    "grad_accum_steps": int,
    "param_gather_dtype": (str, type(None)),
    "grad_reduce_dtype": str,
    "gather_overlap": str,
    "fused_optimizer": str,
}

_NUM = (int, float)


def _typecheck(errs: List[str], where: str, obj: dict, key: str, types,
               required: bool = True) -> None:
    if key not in obj:
        if required:
            errs.append(f"{where}: missing required key {key!r}")
        return
    val = obj[key]
    # bool is an int subclass; an int-typed knob must not accept True
    if types is int and isinstance(val, bool):
        errs.append(f"{where}: {key!r} must be int, got bool")
        return
    if not isinstance(val, types):
        tname = getattr(types, "__name__", str(types))
        errs.append(f"{where}: {key!r} must be {tname}, "
                    f"got {type(val).__name__}")


def validate_knobs(knobs, where: str = "knobs",
                   require_all: bool = True) -> List[str]:
    """The resolved-knob payload (KNOB_PAYLOAD_KEYS, vitax/tune/knobs.py)."""
    errs: List[str] = []
    if not isinstance(knobs, dict):
        return [f"{where}: knobs must be an object, "
                f"got {type(knobs).__name__}"]
    for key in KNOB_PAYLOAD_KEYS:
        _typecheck(errs, where, knobs, key, _KNOB_TYPES[key],
                   required=require_all)
    return errs


def validate_bench_payload(payload, where: str = "bench") -> List[str]:
    """The bench.py single-JSON-line contract (and BENCH_r*.json "parsed")."""
    errs: List[str] = []
    if not isinstance(payload, dict):
        return [f"{where}: payload must be an object, "
                f"got {type(payload).__name__}"]
    _typecheck(errs, where, payload, "metric", str)
    _typecheck(errs, where, payload, "value", _NUM)
    _typecheck(errs, where, payload, "unit", str)
    if "vs_baseline" not in payload:
        errs.append(f"{where}: missing required key 'vs_baseline'")
    elif payload["vs_baseline"] is not None and not isinstance(
            payload["vs_baseline"], _NUM):
        errs.append(f"{where}: 'vs_baseline' must be number or null")
    if isinstance(payload.get("value"), _NUM) and payload["value"] < 0:
        errs.append(f"{where}: 'value' must be >= 0")
    _typecheck(errs, where, payload, "error", str, required=False)
    if "knobs" in payload:
        errs.extend(validate_knobs(payload["knobs"], f"{where}.knobs",
                                   require_all=False))
    return errs


def validate_bench_round(obj, where: str = "BENCH") -> List[str]:
    """One BENCH_rNN.json trajectory entry (driver wrapper + parsed line)."""
    errs: List[str] = []
    if not isinstance(obj, dict):
        return [f"{where}: must be an object, got {type(obj).__name__}"]
    _typecheck(errs, where, obj, "n", int)
    _typecheck(errs, where, obj, "cmd", str)
    _typecheck(errs, where, obj, "rc", int)
    parsed = obj.get("parsed")
    if parsed is not None:
        errs.extend(validate_bench_payload(parsed, f"{where}.parsed"))
    return errs


def validate_autotune_trial(rec, where: str = "trial") -> List[str]:
    """One kind:"autotune_trial" record (vitax/tune/driver.py TrialLog)."""
    errs: List[str] = []
    if not isinstance(rec, dict):
        return [f"{where}: must be an object, got {type(rec).__name__}"]
    if rec.get("schema") != 1:
        errs.append(f"{where}: schema must be 1, got {rec.get('schema')!r}")
    if rec.get("kind") != "autotune_trial":
        errs.append(f"{where}: kind must be 'autotune_trial', "
                    f"got {rec.get('kind')!r}")
    _typecheck(errs, where, rec, "trial_id", int)
    if isinstance(rec.get("trial_id"), int) and rec["trial_id"] < 0:
        errs.append(f"{where}: trial_id must be >= 0")
    _typecheck(errs, where, rec, "time", _NUM)
    _typecheck(errs, where, rec, "model_preset", str)
    _typecheck(errs, where, rec, "topology", str)
    if rec.get("phase") not in TRIAL_PHASES:
        errs.append(f"{where}: phase must be one of {TRIAL_PHASES}, "
                    f"got {rec.get('phase')!r}")
    if "pruned_by" not in rec:
        errs.append(f"{where}: missing required key 'pruned_by'")
    elif rec["pruned_by"] not in PRUNED_BY_VALUES:
        errs.append(f"{where}: pruned_by {rec['pruned_by']!r} not in "
                    f"{PRUNED_BY_VALUES}")
    errs.extend(validate_knobs(rec.get("knobs"), f"{where}.knobs"))
    for key in ("compile_s", "step_time_s", "images_per_sec_chip", "mfu"):
        _typecheck(errs, where, rec, key, _NUM, required=False)
    for key in ("rank", "round", "steps"):
        _typecheck(errs, where, rec, key, int, required=False)
    for key in ("cost", "compile", "mem"):
        _typecheck(errs, where, rec, key, dict, required=False)
    return errs


def validate_trials_file(path: str,
                         max_errors: int = 50) -> List[str]:
    """Validate an autotune trial JSONL file: every line parses, every
    record passes validate_autotune_trial, trial ids strictly increase."""
    errs: List[str] = []
    last_id: Optional[int] = None
    with open(path, encoding="utf-8") as f:
        for lineno, line in enumerate(f, 1):
            if not line.strip():
                continue
            where = f"{path}:{lineno}"
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                errs.append(f"{where}: invalid JSON ({e})")
                continue
            errs.extend(validate_autotune_trial(rec, where))
            tid = rec.get("trial_id")
            if isinstance(tid, int) and not isinstance(tid, bool):
                if last_id is not None and tid <= last_id:
                    errs.append(f"{where}: trial_id {tid} not monotone "
                                f"(previous {last_id})")
                last_id = tid
            if len(errs) >= max_errors:
                errs.append(f"{path}: stopping after {max_errors} errors")
                break
    return errs


def validate_bench_file(path: str) -> List[str]:
    """Validate one BENCH_rNN.json trajectory file."""
    try:
        with open(path, encoding="utf-8") as f:
            obj = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return [f"{path}: unreadable ({e})"]
    return validate_bench_round(obj, path)
