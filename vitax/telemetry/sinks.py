"""Metric sinks: where structured step records go.

A sink is anything with `write(record: dict)` and `close()`. The Recorder
fans every record out to all of its sinks:

- `JsonlSink`       — the always-on machine-readable run log: one JSON object
                      per line, flushed per record so a hang or crash never
                      loses the committed history (the watchdog's dump must
                      survive the job it diagnosed).
- `TensorBoardSink` — optional scalar mirror via tensorboard's no-TF Writer;
                      built through `make_tensorboard_sink`, which degrades
                      to None (with one stderr warning) when the package is
                      absent — telemetry must never add a hard dependency.
"""

from __future__ import annotations

import json
import sys
import threading
from typing import Optional


class JsonlSink:
    """Append-only JSONL event log. Thread-safe: the watchdog thread writes
    hang events while the train thread writes step records."""

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "a", encoding="utf-8")
        self._lock = threading.Lock()

    def write(self, record: dict) -> None:
        line = json.dumps(record, sort_keys=True, default=str)
        with self._lock:
            self._f.write(line + "\n")
            self._f.flush()  # per-record: partial runs must stay readable

    def close(self) -> None:
        with self._lock:
            try:
                self._f.close()
            except OSError:
                pass


class TensorBoardSink:
    """Mirror numeric step-record fields as TB scalars (train/<key>)."""

    # bookkeeping fields that are not scalars worth plotting
    _SKIP = frozenset({"schema", "step", "time", "kind", "rank"})

    def __init__(self, logdir: str):
        from tensorboard.summary import Writer  # no-TF writer (TB >= 2.5)
        self._writer = Writer(logdir)

    def write(self, record: dict) -> None:
        step = record.get("step")
        if step is None or record.get("kind"):  # events are JSONL-only
            return
        for key, val in record.items():
            if key in self._SKIP or isinstance(val, bool):
                continue
            if isinstance(val, (int, float)):
                self._writer.add_scalar(f"train/{key}", float(val), int(step))
        self._writer.flush()

    def close(self) -> None:
        try:
            self._writer.close()
        except Exception:  # noqa: BLE001 # vtx: ignore[VTX106] close must never raise at interpreter exit
            pass


def make_tensorboard_sink(logdir: str) -> Optional[TensorBoardSink]:
    """TensorBoardSink, or None (one warning) when tensorboard is missing or
    refuses the logdir — the JSONL sink is the durable record either way."""
    try:
        return TensorBoardSink(logdir)
    except Exception as e:  # noqa: BLE001 — optional dep, degrade to no-op
        print(f"vitax.telemetry: tensorboard sink disabled "
              f"({type(e).__name__}: {e})", file=sys.stderr, flush=True)
        return None
