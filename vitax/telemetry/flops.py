"""Analytic model-FLOPs accounting -> MFU.

Model FLOPs utilization (MFU, PaLM appendix B convention: Chowdhery et al.
2022) is useful FLOPs per second divided by the chips' peak FLOPs — the
headline efficiency number every perf PR is judged against. "Useful" means
the matmul FLOPs of ONE forward+backward over the batch: remat recompute,
failed experiments and padding are not useful work, so they are NOT counted
(true MFU, not hardware FLOPs utilization).

The FLOPs model is closed-form from `Config` — no tracing, no device work:
patchify conv, per-block qkv/proj + attention einsums + MLP (dense or MoE
top-k experts + router), classifier head, x3 for fwd+bwd (the standard 6ND
convention). Grad accumulation and pipeline microbatching reshape WHERE the
batch's samples flow, not how many matmul FLOPs the optimizer step performs,
so per-step FLOPs are `per_image x batch_size` for every (K, pp_microbatches)
setting — the model is accumulation/pipeline aware by construction.

Shared by bench.py, tools/profile_step.py and the training-loop Recorder so
every MFU the repo reports is the same number.
"""

from __future__ import annotations

# bf16 peak TFLOP/s per chip by TPU generation (public figures). "cpu" keeps
# CPU smoke runs' MFU finite and self-consistent rather than meaningless.
PEAK_TFLOPS = {
    "v4": 275.0,
    "v5 lite": 197.0, "v5e": 197.0,
    "v5p": 459.0,
    "v6e": 918.0, "v6 lite": 918.0,
    "cpu": 1.0,
}

DEFAULT_PEAK_TFLOPS = 197.0  # conservative fallback for unknown device kinds


def detect_peak_tflops(device_kind: str, override: float = 0.0) -> float:
    """Per-chip peak TFLOP/s for a PJRT device_kind string; `override` > 0
    (--peak_tflops) wins unconditionally — the escape hatch for new hardware
    the table has not met."""
    if override and override > 0:
        return float(override)
    kind = (device_kind or "").lower()
    for key, val in PEAK_TFLOPS.items():
        if key in kind:
            return val
    return DEFAULT_PEAK_TFLOPS


def model_flops_per_image(cfg) -> float:
    """Useful matmul FLOPs per image, fwd+bwd (3x forward).

    Dense blocks count qkv/proj/fc1/fc2; MoE blocks count the router matmul
    plus top_k expert MLPs per token (capacity-dropped tokens still occupy
    their expert slot in the einsum impl, but dropped work is not useful —
    top_k per token is the honest number). The dense path is term-for-term
    the historical bench.py accounting, so measured baselines stay
    comparable."""
    d, L = cfg.embed_dim, cfg.num_blocks
    n = cfg.num_patches
    h = cfg.mlp_hidden_dim
    attn_per_token = 2 * (3 * d * d + d * d)                   # qkv, proj
    attn_block = 2 * 2 * n * n * d                             # QK^T and AV
    if getattr(cfg, "moe_experts", 0) > 0:
        k = getattr(cfg, "moe_top_k", 1)
        mlp_per_token = (k * 2 * (d * h + h * d)               # top-k experts
                         + 2 * d * cfg.moe_experts)            # router logits
    else:
        mlp_per_token = 2 * (d * h + h * d)                    # fc1, fc2
    fwd = L * ((attn_per_token + mlp_per_token) * n + attn_block)
    fwd += 2 * n * (3 * cfg.patch_size ** 2) * d               # patchify conv
    fwd += 2 * d * cfg.num_classes                             # head
    return 3.0 * fwd


def model_flops_per_step(cfg) -> float:
    """Useful FLOPs of one optimizer step = per-image x global batch.
    Invariant under --grad_accum_steps and --pp_microbatches (see module
    docstring)."""
    return model_flops_per_image(cfg) * cfg.batch_size


def mfu(cfg, sec_per_iter: float, n_devices: int,
        peak_tflops_per_chip: float) -> float:
    """MFU in [0, 1]: achieved useful FLOP/s over aggregate peak FLOP/s."""
    if sec_per_iter <= 0 or n_devices <= 0 or peak_tflops_per_chip <= 0:
        return 0.0
    achieved = model_flops_per_step(cfg) / sec_per_iter
    return achieved / (peak_tflops_per_chip * 1e12 * n_devices)
