"""Training orchestration: the reference's train() / eval_on_val() / run_logging()
(reference run_vit_training.py:216-318; SURVEY.md sections 3.1-3.4), TPU-native.

One process per host drives all local devices; the hot loop dispatches one
compiled train_step per iteration. Device->host syncs happen only at log steps
(the role of the reference's xm.add_step_closure throttling, run_vit_training.py:289):
JAX's async dispatch returns futures, so we hold the metrics of the most recent
step and fetch them when logging.
"""

from __future__ import annotations

import dataclasses
import math
import pprint
import time
from typing import Optional

import jax
import jax.numpy as jnp

from vitax import distributed, faults, platform
from vitax.checkpoint import (restore_state, restore_state_with_fallback,
                              save_state)
from vitax.config import Config
from vitax.data import build_datasets
from vitax.models import build_model, count_params
from vitax.parallel.mesh import BATCH_AXES, build_mesh
from vitax.train.control import ArbiterReporter, ControlPlane
from vitax.programs.builder import Geometry, build_program
from vitax.programs.registry import get_scenario
from vitax.train.state import TrainState, make_train_state
from vitax.telemetry import (Watchdog, build_recorder,
                             install_thread_excepthook)
from vitax.telemetry.watchdog import EXIT_HANG
from vitax.utils.logging import master_print, memory_summary
from vitax.utils.metrics import SmoothedValue

# Multi-host failure-signal agreement (SIGTERM preemption, watchdog
# escalation, fault/peer-loss bits) is the control plane's job now:
# vitax/train/control.py folds them into one packed word agreed across hosts
# every --control_sync_steps steps (plus each epoch boundary) — the same
# single tiny collective the preemption-only flag sync used to cost.


def _sharded_param_count(state: TrainState) -> int:
    """Per-device (sharded) parameter count — the reference prints this as
    'per-TPU (sharded) parameter num' (run_vit_training.py:234)."""
    total = 0
    for leaf in jax.tree.leaves(state.params):
        shard = leaf.addressable_shards[0]
        # host-side: shapes are static python tuples; jnp.prod here would
        # dispatch (and sync on) one tiny device program per parameter leaf
        total += math.prod(shard.data.shape)
    return total


def train(cfg: Config) -> TrainState:
    distributed.maybe_initialize()
    if cfg.debug_nans:
        jax.config.update("jax_debug_nans", True)
    if cfg.compile_cache_dir:
        # Persistent XLA compilation cache: restarts (launcher --restart,
        # preemption resume, --resume_epoch) skip the recompile of the step
        # program — minutes at 10B scale, more with --scan_unroll > 1. Safe
        # across processes (cache keys include topology + program hash).
        # An empty flag means "no opinion": any JAX_COMPILATION_CACHE_DIR /
        # prior jax.config setting is left untouched (so is the persistence
        # threshold, JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS).
        jax.config.update("jax_compilation_cache_dir", cfg.compile_cache_dir)

    master_print(f"\n=== cfg ===\n{pprint.pformat(cfg)}\n")
    # deterministic fault injection (--fault_plan / VITAX_FAULT_PLAN): armed
    # before any hook site can fire, re-armed identically on every
    # (supervised) restart; a no-plan run pays one `is None` check per hook
    fault_plan = faults.install_from_config(cfg)
    if fault_plan is not None:
        master_print(f"fault injection ARMED (drill): {fault_plan.describe()}")
    mesh = build_mesh(cfg)
    master_print(f"mesh: {dict(mesh.shape)} over {jax.device_count()} devices "
                 f"({jax.process_count()} host(s))")
    attention_impl = _select_attention(cfg, mesh)

    # --- datasets (reference :223-225) ---
    train_ds, train_loader, _, val_loader = build_datasets(cfg, mesh)
    distributed.barrier("loaded dataset")
    master_print(f"\n=== dataset ===\n{pprint.pformat(train_ds)}\n")

    # --- model + optimizer, born sharded (reference :228-242) ---
    auto_resume = cfg.resume_epoch < 0
    # zero-stall snapshot pipeline + peer replication (vitax/checkpoint/
    # snapshot.py, peer.py): built BEFORE resume so the peer store can be
    # negotiated as the restore source — restoring a lost host's shard from
    # its surviving ring buddy reads nothing from shared storage
    snap_pipe = replicator = peer_plan = None
    orbax_found = 0
    deferred_events = []  # (kind, payload) raised before the recorder exists
    if cfg.zero_stall_ckpt or cfg.replicate_steps > 0:
        from vitax.checkpoint.snapshot import SnapshotPipeline
        snap_pipe = SnapshotPipeline()
        master_print("zero-stall checkpointing: staging on the loop thread, "
                     "serialize + write on a background worker")
    if cfg.replicate_steps > 0:
        from vitax.checkpoint import peer as peer_mod
        from vitax.train.control import coordination_client
        store = peer_mod.PeerStore(peer_mod.resolve_peer_dir(cfg))
        replicator = peer_mod.PeerReplicator(
            store, process_index=jax.process_index(),
            process_count=jax.process_count(), client=coordination_client())
        replicator.start_receiver()
        master_print(f"peer replication: every {cfg.replicate_steps} steps "
                     f"-> buddy host {replicator.buddy} (local store "
                     f"{store.root}, guarding host {replicator.guard})")
    if auto_resume:  # auto-resume: latest COMMITTED checkpoint, if any
        from vitax.checkpoint.orbax_io import latest_epoch
        # process 0 picks, everyone adopts: a non-atomic shared-store view
        # (e.g. GCS fuse) must not let hosts disagree on the resume epoch;
        # latest_epoch validates the Orbax commit marker, so a torn dir a
        # crash left mid-write is never selected
        found = distributed.broadcast_from_process0(latest_epoch(cfg.ckpt_dir) or 0)
        cfg = dataclasses.replace(cfg, resume_epoch=found)
        master_print(f"auto-resume: {'epoch ' + str(found) if found else 'no checkpoint found, fresh start'}")
        orbax_found = found
        if replicator is not None:
            # restore-from-peers preferred: the newest complete peer version
            # that beats the Orbax frontier wins (agreed by ALL hosts via
            # the BIT_PEER_RESTORE fold; survivors serve the lost host's
            # shard over the KV seam during the negotiation)
            from vitax.checkpoint.orbax_io import load_resume_step
            frontier = ((0, 0) if not found else peer_mod.progress_key(
                found, load_resume_step(cfg.ckpt_dir, found) or 0))
            peer_plan = peer_mod.negotiate_restore(
                replicator.store, process_index=jax.process_index(),
                process_count=jax.process_count(),
                client=coordination_client(), orbax_frontier=frontier,
                on_event=lambda kind, payload:
                    deferred_events.append((kind, payload)))
            if peer_plan is not None:
                cfg = dataclasses.replace(cfg, resume_epoch=peer_plan.epoch)
                master_print(
                    f"peer restore agreed: version {list(peer_plan.version)}"
                    f" is at least as fresh as the Orbax frontier "
                    f"{list(frontier)} — restoring from peer shards, not "
                    f"shared storage")
    # step-granular resume: a mid-epoch (preemption) checkpoint carries the
    # completed step count in a sidecar — continue INSIDE that epoch instead
    # of skipping its remainder (improves on the reference's epoch-granular
    # --resume_epoch contract, run_vit_training.py:246-248). Elastic: a
    # checkpoint written under a DIFFERENT process count resumes too
    # (_elastic_resume — Orbax reshards the state; the step either carries
    # over exactly or is epoch-rounded when a stream cursor pins topology)
    resume_step = 0
    topology_change = None  # (from, to) process counts when they differ
    resume_rounded = False  # cursor invalidated -> re-enter the SAME epoch
    if peer_plan is not None:
        # the peer meta is sidecar-shaped: the same elastic planner decides
        # the re-entry step (every host reads identical agreed-version meta)
        from vitax.train.control import elastic_resume_plan
        plan = elastic_resume_plan(peer_plan.meta, jax.process_count())
        resume_step = plan.resume_step
        topology_change = ((plan.from_processes, jax.process_count())
                           if plan.topology_changed else None)
        resume_rounded = plan.epoch_rounded
    elif cfg.resume_epoch > 0:
        resume_step, topology_change, resume_rounded = _elastic_resume(
            cfg, cfg.resume_epoch)
    model = build_model(cfg, attention_impl=attention_impl,
                        token_sharding=_token_sharding(cfg, mesh),
                        moe_dispatch_sharding=_moe_dispatch_sharding(cfg, mesh))
    # re-derived from the LIVE loader each run (not a checkpointed value):
    # an elastic restart under a different topology gets the cadence its
    # CURRENT shard assignment supports (the stream sampler's steps_per_epoch
    # depends on process count; the index-sampled loaders' does not)
    steps_per_epoch = (cfg.steps_per_epoch
                       or getattr(train_loader, "steps_per_epoch", 0)
                       or (len(train_ds) // cfg.batch_size))
    max_iteration = steps_per_epoch * cfg.num_epochs
    # the scenario registry (vitax/programs/registry.py) owns the optimizer
    # assembly: --task train/distill get the reference AdamW chain verbatim,
    # finetune appends the masked backbone-lr scale, probe masks the
    # backbone frozen with head-only moments
    scenario = get_scenario(cfg.task)
    tx, schedule = scenario.make_optimizer(cfg, max_iteration)
    # On resume, build only the ABSTRACT state (no device materialization — the
    # checkpoint supplies the values; reference :246-248) and restore into it.
    state, state_specs, _ = make_train_state(
        cfg, model, tx, mesh, jax.random.key(cfg.seed),
        materialize=cfg.resume_epoch <= 0)
    restore_info = None  # {"path": "peer"|"orbax", "epoch": N} for telemetry
    from vitax.checkpoint.orbax_io import restore_read_count
    reads_before_restore = restore_read_count()  # delta = THIS run's reads
    if cfg.resume_epoch > 0:
        if peer_plan is not None:
            # peer shards first; a checksum/coverage failure falls back
            # LOUDLY to the last committed Orbax epoch (restore_info tells
            # us which path actually won)
            state, restore_info = peer_mod.restore_state_preferring_peers(
                replicator.store, peer_plan, cfg.ckpt_dir, orbax_found,
                state, on_event=lambda kind, payload:
                    deferred_events.append((kind, payload)))
            if restore_info["path"] == "orbax":
                if restore_info["epoch"] != cfg.resume_epoch:
                    cfg = dataclasses.replace(
                        cfg, resume_epoch=restore_info["epoch"])
                resume_step, topology_change, resume_rounded = (
                    _elastic_resume(cfg, cfg.resume_epoch))
        elif auto_resume:
            # an auto-resume must survive one bad checkpoint: fall back to
            # the previous committed epoch (loudly) instead of wedging
            state, restored = restore_state_with_fallback(
                cfg.ckpt_dir, cfg.resume_epoch, state)
            if restored != cfg.resume_epoch:
                cfg = dataclasses.replace(cfg, resume_epoch=restored)
                resume_step, topology_change, resume_rounded = (
                    _elastic_resume(cfg, restored))
            restore_info = {"path": "orbax", "epoch": cfg.resume_epoch}
        else:  # an explicit --resume_epoch N must mean N — fail hard
            state = restore_state(cfg.ckpt_dir, cfg.resume_epoch, state)
            restore_info = {"path": "orbax", "epoch": cfg.resume_epoch}
    if cfg.init_npz and cfg.resume_epoch <= 0:
        # finetune/probe warm start: overwrite the fresh sharded init from
        # the consolidated export (head re-init per --reinit_head / shape);
        # an Orbax resume above takes precedence — the checkpoint already
        # embodies the warm-started run
        from vitax.programs.workloads import warm_start_from_npz
        state, ft_info = warm_start_from_npz(cfg, state, mesh)
        deferred_events.append(("finetune", ft_info))
    distributed.barrier("loaded model")
    master_print(f"\n=== model ===\n{model}\n")
    master_print(f"global parameter num: {count_params(state.params)}")
    master_print(f"per-device (sharded) parameter num: {_sharded_param_count(state)}")
    from vitax.train.state import ADAMW_HPARAMS
    master_print(  # optimizer dump at startup (reference run_vit_training.py:242)
        f"\n=== optimizer ===\nAdamW(lr=warmup_cosine(base={cfg.lr}, "
        f"warmup={cfg.warmup_steps}, max_iteration={max_iteration}), "
        f"betas=({ADAMW_HPARAMS['b1']}, {ADAMW_HPARAMS['b2']}), "
        f"eps={ADAMW_HPARAMS['eps']}, weight_decay={cfg.weight_decay}, "
        f"clip_grad_norm={cfg.clip_grad_norm})\n")
    distributed.barrier("loaded optimizer")

    if cfg.grad_accum_steps > 1:
        # step-count/logging semantics are UNCHANGED: the scan over K
        # microbatches lives inside the compiled step, so each loader batch
        # is still exactly one optimizer step / one log line / one lr tick.
        master_print(
            f"grad accumulation: {cfg.grad_accum_steps} microbatches of "
            f"{cfg.batch_size // cfg.grad_accum_steps} inside the jitted "
            f"step (one optimizer step per loader batch)")
    # one build_program(task, geometry) entry for every jitted program the
    # loop runs (vitax/programs/builder.py). The geometry wraps the loop's
    # LIVE objects (non-owned), so the built programs are the exact
    # constructors' outputs — the lowered bytes are pinned identical to the
    # former direct make_train_step/make_eval_step calls
    # (tests/test_programs.py).
    geom = Geometry(cfg=cfg, mesh=mesh, model=model, tx=tx,
                    schedule=schedule, state_specs=state_specs)
    train_step = build_program(scenario.step_program, geom)
    eval_step = build_program("eval", geom)

    smoothed_loss = SmoothedValue(window_size=5)
    smoothed_time = SmoothedValue(window_size=5)
    from vitax.train import preempt
    preempt.install()  # SIGTERM -> committed save -> clean exit

    # --- telemetry (vitax/telemetry/): all host-side — the compiled step
    # program and its dispatch cadence are identical with telemetry off ---
    recorder = build_recorder(cfg, jax.device_count(),
                              platform.device_kind(),
                              rank=jax.process_index())
    # uncaught exceptions in ANY background thread (loader producers,
    # watchdog, heartbeats, snapshot writer, peer receiver) become
    # rank-tagged stderr tracebacks + kind:"thread_crash" events instead
    # of silent thread deaths (recorder=None still tags stderr)
    install_thread_excepthook(recorder, rank=jax.process_index())
    # opt_update_s probe: a separate non-donating compile of the optimizer
    # phase (vitax/train/step.py make_opt_probe), run at log steps only — the
    # train step's program and the non-log-step cadence are untouched. The
    # first probe call warms the compile; timing starts at the second.
    # Built from cfg.metrics_dir (rank-uniform argv), NOT from the recorder:
    # the recorder lives on rank 0 only, but the probe is a global-mesh
    # program — every process must execute it at the same log steps or
    # rank 0 blocks forever in a collective its peers never enter.
    opt_probe = (build_program("opt_probe", geom)
                 if (getattr(cfg, "metrics_dir", "") or "") else None)
    opt_probe_warm = [False]

    def _time_opt_update(cur_state) -> float:
        if not opt_probe_warm[0]:
            jax.block_until_ready(opt_probe(cur_state))
            opt_probe_warm[0] = True
        t0 = time.perf_counter()
        jax.block_until_ready(opt_probe(cur_state))
        return time.perf_counter() - t0

    if recorder is not None:
        master_print(f"telemetry: JSONL step records -> {cfg.metrics_dir} "
                     f"(MFU vs {recorder.peak_tflops:.0f} TF/s/chip peak"
                     + (", tensorboard mirror on" if cfg.tensorboard else "")
                     + ")")
        recorder.event("run_start", device_kind=recorder.device_kind,
                       n_devices=recorder.n_devices,
                       peak_tflops=recorder.peak_tflops,
                       flops_per_step=recorder.flops_per_step,
                       batch_size=cfg.batch_size)
        if fault_plan is not None:  # fired faults become kind:"fault" events
            faults.set_reporter(
                lambda payload: recorder.event("fault", **payload))
        for kind, payload in deferred_events:
            recorder.event(kind, **payload)  # pre-recorder restore events
        if restore_info is not None:
            # which restore path actually won, plus the shared-storage read
            # counter — the peer-restore drill asserts path=="peer" with
            # orbax_reads == 0 (zero checkpoint state read from storage)
            recorder.event("restore", path=restore_info["path"],
                           epoch=int(restore_info["epoch"]),
                           resume_step=int(resume_step),
                           orbax_reads=restore_read_count()
                           - reads_before_restore)
    if replicator is not None and recorder is not None:
        replicator.on_event = (lambda kind, payload:
                               recorder.event(kind, **payload))
    watchdog = None
    if cfg.hang_timeout_s > 0:
        on_fire = ((lambda payload: recorder.event("hang", **payload))
                   if recorder is not None else None)
        on_escalate = ((lambda payload: recorder.event("hang_escalation",
                                                       **payload))
                       if recorder is not None else None)
        # built here, ARMED at the first dispatch return (see the step loop):
        # the first step blocks on XLA compilation — minutes at 10B scale —
        # and a watchdog ticking through it would escalate on a healthy run
        watchdog = Watchdog(cfg.hang_timeout_s, on_fire=on_fire,
                            rank=jax.process_index(),
                            action=cfg.hang_action,
                            on_escalate=on_escalate)
        master_print(
            f"watchdog: stack+memory dump after {cfg.hang_timeout_s:.0f}s "
            f"without a completed step (armed after the compile step)"
            + (f", then emergency checkpoint + exit {EXIT_HANG}"
               if cfg.hang_action == "checkpoint_exit" else ""))

    # --- coordinated failure control plane (vitax/train/control.py): every
    # host-local signal — preemption, escalation, fault, peer loss — folds
    # into one packed word agreed across hosts, so all hosts take the same
    # action at the same step. Host-side only, like telemetry and faults:
    # the compiled step program is identical with the plane on or off. ---
    control = ControlPlane(
        sync_steps=cfg.control_sync_steps, watchdog=watchdog,
        on_event=((lambda payload: recorder.event("control", **payload))
                  if recorder is not None else None))
    if watchdog is not None:
        # last words for the hard-deadline exit: a flushed telemetry event
        # plus the fault bit published through the coordination service, so
        # peers learn the cause instead of just losing a heartbeat
        def _hard_exit_last_words(payload, _recorder=recorder,
                                  _control=control):
            if _recorder is not None:
                _recorder.event("hang_hard_exit", **payload)
            _control.publish_fault("hang_hard_exit")
        watchdog.on_hard_exit = _hard_exit_last_words
    if topology_change is not None and recorder is not None:
        # the RESUME action, not the observation: the supervisor already
        # records `topology_change` when it spots the mismatch — this one
        # says the loop actually re-derived its schedule under the new
        # process count (distinct events, or the report double-counts)
        recorder.event("control", event="elastic_resume",
                       from_processes=topology_change[0],
                       to_processes=topology_change[1],
                       epoch=cfg.resume_epoch, resume_step=resume_step,
                       epoch_rounded=resume_rounded)
    if cfg.peer_heartbeat_s > 0:
        grace_s = cfg.peer_grace_s or 10.0 * cfg.peer_heartbeat_s
        if control.start_liveness(cfg.peer_heartbeat_s, grace_s):
            master_print(
                f"peer liveness: heartbeats every {cfg.peer_heartbeat_s:g}s "
                f"through the coordination service; a peer silent for "
                f"{grace_s:g}s is declared lost and survivors exit "
                f"{EXIT_HANG} within the deadline instead of blocking in "
                f"collectives")

    arbiter_reporter = None
    if cfg.arbiter_url and jax.process_index() == 0:
        # chip-arbiter heartbeat (vitax/arbiter/): rank 0 posts the latest
        # committed step so borrow policy sees live progress. Host-side
        # thread only — the compiled step program is unchanged.
        arbiter_reporter = ArbiterReporter(
            cfg.arbiter_url, process_count=jax.process_count())
        arbiter_reporter.start()
        master_print(f"arbiter telemetry: posting step heartbeats to "
                     f"{cfg.arbiter_url}")

    control.warmup()  # compile the agreement fold outside any hang deadline
    distributed.barrier("training begins")
    master_print("training begins (the first few iterations are very slow due to compilation)")

    prof = {"on": False}  # shared so the finally can close a mid-flight trace
    try:
        state = _run_epochs(
            cfg, state, train_step, train_loader, val_loader, eval_step,
            schedule, smoothed_loss, smoothed_time, prof,
            resume_step=resume_step, resume_rounded=resume_rounded,
            recorder=recorder, watchdog=watchdog, control=control,
            snap_pipe=snap_pipe, replicator=replicator,
            opt_timer=_time_opt_update if opt_probe is not None else None,
            arbiter_reporter=arbiter_reporter)
    except Exception as e:  # noqa: BLE001 — classify, then exit coordinated or re-raise
        # A dead peer shows up two ways: ICI collectives BLOCK on it (the
        # liveness deadline timer bounds that), host-plane transports like
        # Gloo surface it as a runtime ERROR instead. Ask the liveness
        # monitor which this is: a lost-peer verdict means the error is the
        # death itself — exit EXIT_HANG like every other coordinated
        # escalation (the last committed checkpoint stands; no joint save is
        # possible over a dead peer). Peers all beating == a genuine bug:
        # re-raise it untouched.
        lost = control.peer_loss_suspected()
        if lost is None:
            raise
        import sys as _sys
        print(f"vitax.control: runtime error after losing peer {lost} "
              f"({type(e).__name__}: {e}); exiting {EXIT_HANG} for the "
              f"supervisor to restart from the last committed checkpoint",
              file=_sys.stderr, flush=True)
        raise SystemExit(EXIT_HANG) from e
    finally:
        if prof["on"]:
            jax.profiler.stop_trace()
            master_print(f"profile trace written to {cfg.profile_dir}")
        control.stop()  # liveness threads + any armed peer-loss exit timer
        if arbiter_reporter is not None:
            arbiter_reporter.stop()  # flushes the last committed step
        if watchdog is not None:
            watchdog.stop()  # before the loaders: their drain must not fire it
        train_loader.close()
        val_loader.close()
        if replicator is not None:
            # receiver thread + one final guard-shard pull: an elastic
            # shrink resumes from the survivor's LOCAL store, which must
            # hold the buddy's preemption-save shard before this exit
            replicator.stop()
        if snap_pipe is not None:
            snap_pipe.close()  # drain queued persist/replicate jobs
        from vitax.checkpoint.orbax_io import wait_until_finished
        wait_until_finished()  # drain any in-flight async save before exit
        if recorder is not None:
            recorder.close()
        faults.uninstall()  # fault plans are per-run, like the recorder
        preempt.uninstall()  # restore normal SIGTERM for post-training work

    master_print("training completed")
    return state


def _stream_cursor(loader, epoch: int, next_step: int):
    """The streaming data plane's resume cursor after `next_step` consumed
    batches, or None for loaders without one (ImageFolder/fake). Rides the
    mid-epoch checkpoint sidecar so the resumed run can validate its derived
    position against the shard set that produced the checkpoint."""
    fn = getattr(loader, "cursor_for_step", None)
    return fn(epoch, next_step) if fn is not None else None


def _verify_stream_resume(cfg, train_loader, resume_step: int) -> None:
    """Mid-epoch stream resume: check the sidecar cursor against the position
    this run derives from (seed, epoch, step). The derivation is the source
    of truth — the stored cursor exists to FAIL LOUDLY when the shard set,
    seed, or topology changed underneath the checkpoint (silently feeding
    different records is the failure mode). Process 0 only: the sidecar holds
    process 0's cursor, and a drifted shard manifest is global anyway."""
    if not resume_step or not hasattr(train_loader, "check_cursor"):
        return
    if jax.process_index() != 0:
        return
    from vitax.checkpoint.orbax_io import load_stream_cursor
    cursor = load_stream_cursor(cfg.ckpt_dir, cfg.resume_epoch)
    if cursor is not None:
        train_loader.check_cursor(cursor, resume_step)
        master_print(f"stream resume cursor verified: epoch "
                     f"{cursor.get('epoch')}, shard_cursor "
                     f"{cursor.get('shard_cursor')} "
                     f"({cursor.get('shard')}), record_offset "
                     f"{cursor.get('record_offset')}")


def _elastic_resume(cfg, epoch: int):
    """Resume plan for `epoch` under the CURRENT topology: (resume_step,
    (from, to) process counts when they differ else None, epoch_rounded).
    `epoch_rounded` True means the mid-epoch progress was dropped — the loop
    must RE-ENTER `epoch` from step 0, not treat the save as an epoch
    boundary (which would skip the epoch's remaining records). Process 0
    reads the sidecar and plans (vitax/train/control.py elastic_resume_plan);
    every process adopts its verdict — the same broadcast discipline as the
    auto-resume epoch pick, so a non-atomic shared store can never let hosts
    disagree on where the epoch re-enters."""
    from vitax.checkpoint.orbax_io import load_resume_meta
    from vitax.train.control import elastic_resume_plan
    step = prev = rounded = 0
    if jax.process_index() == 0:
        plan = elastic_resume_plan(load_resume_meta(cfg.ckpt_dir, epoch),
                                   jax.process_count())
        step = plan.resume_step
        rounded = int(plan.epoch_rounded)
        if plan.topology_changed:
            prev = plan.from_processes
            master_print(
                f"elastic resume: checkpoint epoch {epoch} was written by "
                f"{plan.from_processes} process(es), this run has "
                f"{jax.process_count()}"
                + (f" — stream cursor invalidated by the topology change; "
                   f"epoch-rounding the resume (re-running "
                   f"{plan.skipped_steps} mid-epoch steps)"
                   if plan.epoch_rounded else
                   " — rank-interleaved sampling keeps the step-granular "
                   "resume exact"))
    step = distributed.broadcast_from_process0(step)
    prev = distributed.broadcast_from_process0(prev)
    rounded = bool(distributed.broadcast_from_process0(rounded))
    return step, ((prev, jax.process_count()) if prev else None), rounded


def _save_ckpt(cfg, state, epoch, *, wait, step_in_epoch=None,
               stream_cursor=None, snap_pipe=None, replicator=None):
    """Route a checkpoint save through the zero-stall pipeline when one is
    active — ALL saves must: Orbax's async checkpointer is a per-process
    singleton, and a direct save from the loop thread would race the
    pipeline's worker. wait=True keeps its meaning (drain before return —
    final/emergency semantics). Saves under an active replication window
    record the window in the resume sidecar and refresh the peer store."""
    extra = ({"replicate_steps": cfg.replicate_steps}
             if cfg.replicate_steps > 0 else None)
    if snap_pipe is not None:
        snap_pipe.submit(state, epoch=epoch, step_in_epoch=step_in_epoch or 0,
                         stream_cursor=stream_cursor, persist_to=cfg.ckpt_dir,
                         keep=cfg.keep_checkpoints, extra_meta=extra,
                         replicator=replicator, wait=wait)
    else:
        save_state(cfg.ckpt_dir, epoch, state, wait=wait,
                   step_in_epoch=step_in_epoch, stream_cursor=stream_cursor,
                   keep=cfg.keep_checkpoints, extra_meta=extra)


def _run_epochs(cfg, state, train_step, train_loader, val_loader, eval_step,
                schedule, smoothed_loss, smoothed_time, prof,
                resume_step: int = 0, resume_rounded: bool = False,
                recorder=None, watchdog=None, control=None,
                snap_pipe=None, replicator=None, opt_timer=None,
                arbiter_reporter=None):
    if control is None:  # direct callers (tests): a local, collective-free plane
        control = ControlPlane(sync_steps=cfg.control_sync_steps,
                               watchdog=watchdog)
    data_rng = jax.random.key(cfg.seed + 1)
    total_steps = 0
    steps_since_record = 0  # averaging window for the per-record data wait
    # profiler window (historical default: steps 3..7 — start after 2
    # completed steps so the compile step stays out of the trace)
    prof_start = cfg.profile_start_step
    prof_stop = cfg.profile_start_step + cfg.profile_num_steps
    # resume_step > 0: the resume checkpoint was a mid-epoch preemption save —
    # re-enter THAT epoch at the recorded step (the sampler order is a pure
    # function of (seed, epoch), so the data stream continues exactly where
    # the preempted run left off). resume_rounded: the save was ALSO
    # mid-epoch, but a topology change invalidated its stream cursor — the
    # planner dropped the step, so re-enter the SAME epoch from step 0
    # (treating it as an epoch boundary would silently skip the epoch's
    # remaining records, the opposite of the rounding contract).
    reenter = bool(resume_step) or resume_rounded
    start_epoch = cfg.resume_epoch + (0 if reenter else 1)
    if resume_step:
        master_print(f"step-granular resume: re-entering epoch {start_epoch} "
                     f"at step {resume_step + 1}")
        _verify_stream_resume(cfg, train_loader, resume_step)
    elif resume_rounded:
        master_print(f"epoch-rounded resume: re-running epoch {start_epoch} "
                     f"from step 1 (mid-epoch stream cursor invalidated by "
                     f"the topology change)")
    for epoch in range(max(start_epoch, 1), cfg.num_epochs + 1):
        master_print(f"starting epoch {epoch}")
        time_epoch_b = time_step_b = time.time()
        metrics = None
        start_step = resume_step if epoch == start_epoch else 0
        for step, batch in enumerate(
                train_loader.epoch(epoch, start_step=start_step),
                start=start_step):
            if cfg.steps_per_epoch and step >= cfg.steps_per_epoch:
                break
            if cfg.profile_dir and total_steps == prof_start and not prof["on"]:
                jax.profiler.start_trace(cfg.profile_dir)
                prof["on"] = True
            state, metrics = train_step(state, batch, data_rng)
            total_steps += 1
            # fault drill point (no-op without a plan): fires BEFORE the pet
            # so an injected hang starves the watchdog exactly like a real
            # wedged step; index = the global step count, so plans are
            # deterministic across restarts of the same config
            faults.fire("step", index=total_steps)
            steps_since_record += 1
            if watchdog is not None:
                # pet on dispatch, not completion: the loop is alive; a wedged
                # DEVICE stalls the next log step's fence, which stops pets
                # within log_step_interval dispatches (async dispatch depth).
                # The FIRST step arms the watchdog instead — after its results
                # MATERIALIZE, not at dispatch return: the first execution
                # covers XLA compile and, multi-host, collective-transport
                # bring-up + peer compile skew. None of that is a hang, and
                # --hang_timeout_s stays independent of all of it.
                if watchdog.running:
                    watchdog.pet()
                else:
                    jax.device_get(metrics["loss"])  # fence: bring-up done
                    watchdog.start()
            if prof["on"] and total_steps == prof_stop:
                jax.device_get(metrics["loss"])  # fence (block_until_ready is
                # a no-op on some PJRT transports, e.g. the axon tunnel)
                jax.profiler.stop_trace()
                prof["on"] = False
                master_print(f"profile trace written to {cfg.profile_dir}")

            # first step of THIS RUN (fresh start, epoch-granular resume, or
            # mid-epoch resume alike): always log it — it carries the compile
            is_first_iter = total_steps == 1
            will_log = is_first_iter or (step + 1) % cfg.log_step_interval == 0
            host_loss = None
            if will_log:
                # fence before reading the clock: train_step returns at
                # dispatch, so an unfenced delta times the async enqueue,
                # not device execution — the logged sec/iter would converge
                # to dispatch latency while the devices fall arbitrarily
                # far behind. Fetched ONCE here and passed through as a host
                # value (_run_logging and the telemetry record reuse it);
                # non-log steps stay fence-free so the pipeline keeps its
                # device/host overlap.
                host_loss = float(jax.device_get(metrics["loss"]))
            t_new = time.time()
            smoothed_time.update(t_new - time_step_b, batch_size=1)
            time_step_b = t_new
            if will_log:
                lr = float(schedule(int(jax.device_get(metrics["lr_step"]))))
                _run_logging(cfg, epoch, step, host_loss, lr, smoothed_loss,
                             smoothed_time)
                # fenced re-run of the optimizer phase in isolation (probe
                # program, not the train step) — the cost rides a log step
                # that just fenced anyway. Runs on EVERY rank (the probe is
                # a global-mesh program; its collectives must line up), even
                # though only rank 0 records the number.
                opt_update_s = (opt_timer(state)
                                if opt_timer is not None else 0.0)
                if recorder is not None:
                    # all inputs are already host values; the one extra
                    # device->host fetch (grad_norm) rides a log step that
                    # just fenced — non-log steps stay untouched
                    recorder.record_step(
                        step=total_steps, epoch=epoch, step_in_epoch=step + 1,
                        loss=host_loss, lr=lr,
                        sec_per_iter=smoothed_time.avg,
                        data_wait_s=(train_loader.consume_wait_s()
                                     / max(steps_since_record, 1)),
                        ckpt_stall_s=((snap_pipe.consume_stall_s()
                                       / max(steps_since_record, 1))
                                      if snap_pipe is not None else 0.0),
                        opt_update_s=opt_update_s,
                        grad_norm=float(jax.device_get(metrics["grad_norm"])))
                    if "kl" in metrics:
                        # distill step (vitax/programs/workloads.py): the
                        # extra metrics ride the log-step fence the record
                        # above just paid
                        recorder.event(
                            "distill", step=total_steps, epoch=epoch,
                            kl=float(jax.device_get(metrics["kl"])),
                            ce=float(jax.device_get(metrics["ce"])),
                            teacher_top1=float(
                                jax.device_get(metrics["teacher_top1"])),
                            student_top1=float(
                                jax.device_get(metrics["student_top1"])),
                            alpha=cfg.distill_alpha, temp=cfg.distill_temp)
                steps_since_record = 0
            if arbiter_reporter is not None:
                # a lock + three assignments; the reporter thread posts
                arbiter_reporter.update(total_steps, epoch)
            if (replicator is not None and snap_pipe is not None
                    and (step + 1) % cfg.replicate_steps == 0):
                # replication window: stage this host's shard (the only part
                # on the loop thread — charged to ckpt_stall_s) and mirror
                # it to the ring buddy from the pipeline worker
                snap_pipe.submit(
                    state, epoch=epoch, step_in_epoch=step + 1,
                    stream_cursor=_stream_cursor(train_loader, epoch,
                                                 step + 1),
                    replicator=replicator)
            # step-boundary control poll (vitax/train/control.py): folds the
            # watchdog's escalation flag, the SIGTERM flag, and fault/peer
            # bits into one word — agreed across hosts on the sync cadence,
            # free local reads single-host — so every host reacts to the
            # SAME verdict at the SAME step
            sig = control.poll(step_in_epoch=step, epoch=epoch)
            if sig.emergency:
                # agreed hang escalation / fault / peer-loss verdict: save a
                # jointly committed mid-epoch checkpoint and exit EXIT_HANG
                # on ALL hosts for the supervisor to restart. Reaching this
                # agreement proves every process is alive, so the joint
                # save's collectives line up; the acknowledge re-arms the
                # watchdog's hard deadline so a save wedged on a truly dead
                # device is still bounded.
                if watchdog is not None:
                    watchdog.acknowledge_escalation()
                master_print(f"watchdog escalation: saving emergency "
                             f"checkpoint at epoch {epoch} (step {step + 1}) "
                             f"and exiting with code {EXIT_HANG} "
                             f"(agreed signals: {sig.describe()})")
                jax.device_get(metrics["loss"])  # fence: step must be done
                _save_ckpt(cfg, state, epoch, wait=True,
                           step_in_epoch=step + 1,
                           stream_cursor=_stream_cursor(train_loader, epoch,
                                                        step + 1),
                           snap_pipe=snap_pipe, replicator=replicator)
                control.arm_exit_deadline()  # bound the barrier: a peer
                # dead mid-drain must not wedge survivors forever
                distributed.barrier("coordinated emergency exit")
                raise SystemExit(EXIT_HANG)
            if sig.preempt:
                # commit a synchronous save of the live mid-epoch state under
                # this epoch's name (with the completed step count in the
                # resume sidecar), drain, and leave. Auto-resume
                # (--resume_epoch -1) restarts INSIDE this epoch at the next
                # step — no data is skipped or repeated.
                master_print(f"SIGTERM received: saving preemption checkpoint "
                             f"at epoch {epoch} (step {step + 1}) and exiting")
                jax.device_get(metrics["loss"])  # fence: step must be done
                _save_ckpt(cfg, state, epoch, wait=True,
                           step_in_epoch=step + 1,
                           stream_cursor=_stream_cursor(train_loader, epoch,
                                                        step + 1),
                           snap_pipe=snap_pipe, replicator=replicator)
                # bounded: a peer that died mid-save must not wedge this
                # host in the barrier forever — the plane prefers the
                # watchdog's hard deadline when one runs and otherwise arms
                # its own DEFAULT_EXIT_DEADLINE_S timer, so the barrier is
                # bounded under EVERY config (the PR 10 gap, closed)
                control.arm_exit_deadline()
                distributed.barrier("coordinated preemption exit")
                return state
            if cfg.max_steps and total_steps >= cfg.max_steps:
                break

        if metrics is not None:
            jax.device_get(metrics["loss"])  # fence: honest epoch wall time
        master_print(f"epoch {epoch} done ({time.time() - time_epoch_b:.2f} sec)")

        # epoch boundary: always sync — epochs shorter than the in-loop
        # cadence still get an agreed verdict here (every host reaches the
        # boundary at the same point)
        sig = control.poll(step_in_epoch=None, epoch=epoch)
        if sig.emergency:
            if watchdog is not None:
                watchdog.acknowledge_escalation()
            master_print(f"watchdog escalation: saving emergency checkpoint "
                         f"after epoch {epoch} and exiting with code "
                         f"{EXIT_HANG} (agreed signals: {sig.describe()})")
            _save_ckpt(cfg, state, epoch, wait=True,
                       snap_pipe=snap_pipe, replicator=replicator)
            control.arm_exit_deadline()  # bound the barrier (see above)
            distributed.barrier("coordinated emergency exit")
            raise SystemExit(EXIT_HANG)
        if sig.preempt:
            master_print(f"SIGTERM received: saving preemption checkpoint "
                         f"after epoch {epoch} and exiting")
            _save_ckpt(cfg, state, epoch, wait=True,
                       snap_pipe=snap_pipe, replicator=replicator)
            control.arm_exit_deadline()  # bound the barrier (see above)
            distributed.barrier("coordinated preemption exit")
            return state

        if epoch % cfg.ckpt_epoch_interval == 0 or epoch == cfg.num_epochs:
            # async: the device->host snapshot happens before return, the write
            # commits in background while the next epoch trains; the final save
            # waits so training never exits with an uncommitted checkpoint.
            # Under --zero_stall_ckpt even the snapshot leaves the loop thread
            # after a staged memcpy (vitax/checkpoint/snapshot.py).
            _save_ckpt(cfg, state, epoch, wait=epoch == cfg.num_epochs,
                       snap_pipe=snap_pipe, replicator=replicator)
        if epoch % cfg.test_epoch_interval == 0 or epoch == cfg.num_epochs:
            top1, top5, _, _ = eval_on_val(cfg, val_loader, eval_step, state,
                                           recorder=recorder, epoch=epoch)
            master_print(f"accuracy on val: {top1:.4f} (top-5 {top5:.4f})")
        if cfg.max_steps and total_steps >= cfg.max_steps:
            break

    return state


def _token_sharding(cfg: Config, mesh):
    """(B, N, D) activation sharding: batch over (dp, fsdp), tokens over sp.
    Anchors GSPMD propagation; None on single-device meshes."""
    if mesh.size == 1:
        return None
    from jax.sharding import NamedSharding, PartitionSpec as P
    sp = mesh.shape.get("sp", 1)
    token_axis = "sp" if (sp > 1 and cfg.num_patches % sp == 0) else None
    return NamedSharding(mesh, P(BATCH_AXES, token_axis, None))


def _moe_dispatch_sharding(cfg: Config, mesh):
    """(E, B, C, D) dispatched-tensor sharding for the MoE einsums: experts
    over "ep", batch over the data axes. The explicit anchor makes GSPMD
    lower dispatch/combine to all-to-alls instead of the partitioner's
    involuntary full rematerialization. None when dense or single-device."""
    if cfg.moe_experts == 0 or mesh.size == 1:
        return None
    from jax.sharding import NamedSharding, PartitionSpec as P
    ep = mesh.shape.get("ep", 1)
    return NamedSharding(
        mesh, P("ep" if ep > 1 else None, ("dp", "fsdp"), None, None))


def _select_attention(cfg: Config, mesh):
    """Pick the attention core (vitax.ops.attention.make_attention_impl):
    ring attention under sp, whole-N or streaming Pallas kernel on TPU,
    dense jnp elsewhere."""
    from vitax.ops.attention import make_attention_impl
    impl = make_attention_impl(cfg, mesh)
    master_print("attention core: "
                 + getattr(impl, "vitax_name", "dense jnp"))
    return impl


def _run_logging(cfg, epoch, step, loss, lr, smoothed_loss, smoothed_time):
    """Throttled step log (reference run_logging, run_vit_training.py:203-213).

    The loss is already the global-batch mean — the reference's
    mesh_reduce(sum)/world_size (:205-206) is compiled into the step. The
    caller fetched it (and resolved lr) once at the log-step fence and passes
    the host values through — no second device->host sync here."""
    smoothed_loss.update(loss, batch_size=1)
    mem = f", {memory_summary()}" if cfg.log_memory else ""
    master_print(
        f"epoch {epoch} step {step + 1}, lr: {lr:.4f}, "
        f"loss: {smoothed_loss.avg:.4f}, "
        f"sec/iter: {smoothed_time.avg:.4f}{mem}"
    )


def eval_on_val(cfg: Config, val_loader, eval_step, state: TrainState,
                recorder=None, epoch: int = 0):
    """Top-1 + top-5 accuracy over the val split (reference eval_on_val,
    run_vit_training.py:306-318, extended with the top-5 metric the serving
    stack reports). drop_last semantics preserved: the remainder of the
    split is ignored, exactly like the reference (:77,:83).

    With a Recorder (--metrics_dir), emits one kind:"eval" telemetry event
    (epoch, top1, top5, n) per eval pass — tools/metrics_report.py surfaces
    the latest one. Returns (top1, top5, n_correct, total)."""
    correct = None
    total = 0
    for step, batch in enumerate(val_loader.epoch(0)):
        if cfg.eval_max_batches and step >= cfg.eval_max_batches:
            break
        c = eval_step(state, batch)
        correct = c if correct is None else jax.tree.map(
            lambda a, b: a + b, correct, c)
        total += cfg.batch_size
    counts = (jax.device_get(correct) if correct is not None
              else {"correct": 0, "correct_top5": 0})
    n_correct = int(counts["correct"])
    n_top5 = int(counts["correct_top5"])
    top1 = n_correct / total if total else 0.0
    top5 = n_top5 / total if total else 0.0
    if recorder is not None:
        recorder.event("eval", epoch=int(epoch), top1=top1, top5=top5,
                       n=total)
    return top1, top5, n_correct, total
