"""Training orchestration: the reference's train() / eval_on_val() / run_logging()
(reference run_vit_training.py:216-318; SURVEY.md sections 3.1-3.4), TPU-native.

One process per host drives all local devices; the hot loop dispatches one
compiled train_step per iteration. Device->host syncs happen only at log steps
(the role of the reference's xm.add_step_closure throttling, run_vit_training.py:289):
JAX's async dispatch returns futures, so we hold the metrics of the most recent
step and fetch them when logging.
"""

from __future__ import annotations

import dataclasses
import math
import pprint
import time
from typing import Optional

import jax
import jax.numpy as jnp

from vitax import distributed, faults, platform
from vitax.checkpoint import (restore_state, restore_state_with_fallback,
                              save_state)
from vitax.config import Config
from vitax.data import build_datasets
from vitax.models import build_model, count_params
from vitax.parallel.mesh import BATCH_AXES, build_mesh
from vitax.train.state import TrainState, build_optimizer, make_train_state
from vitax.train.step import make_eval_step, make_train_step
from vitax.telemetry import Watchdog, build_recorder
from vitax.telemetry.watchdog import EXIT_HANG
from vitax.utils.logging import master_print, memory_summary
from vitax.utils.metrics import SmoothedValue

# Multi-host preemption-flag sync cadence (steps). Bounds the extra exposure
# after SIGTERM to min(10 steps, rest of the epoch) of wall time — the epoch
# boundary always syncs too. Hosts must use the SAME constant (the flag sync
# is a collective).
PREEMPT_SYNC_STEPS = 10


def _sharded_param_count(state: TrainState) -> int:
    """Per-device (sharded) parameter count — the reference prints this as
    'per-TPU (sharded) parameter num' (run_vit_training.py:234)."""
    total = 0
    for leaf in jax.tree.leaves(state.params):
        shard = leaf.addressable_shards[0]
        # host-side: shapes are static python tuples; jnp.prod here would
        # dispatch (and sync on) one tiny device program per parameter leaf
        total += math.prod(shard.data.shape)
    return total


def train(cfg: Config) -> TrainState:
    distributed.maybe_initialize()
    if cfg.debug_nans:
        jax.config.update("jax_debug_nans", True)
    if cfg.compile_cache_dir:
        # Persistent XLA compilation cache: restarts (launcher --restart,
        # preemption resume, --resume_epoch) skip the recompile of the step
        # program — minutes at 10B scale, more with --scan_unroll > 1. Safe
        # across processes (cache keys include topology + program hash).
        # An empty flag means "no opinion": any JAX_COMPILATION_CACHE_DIR /
        # prior jax.config setting is left untouched (so is the persistence
        # threshold, JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS).
        jax.config.update("jax_compilation_cache_dir", cfg.compile_cache_dir)

    master_print(f"\n=== cfg ===\n{pprint.pformat(cfg)}\n")
    # deterministic fault injection (--fault_plan / VITAX_FAULT_PLAN): armed
    # before any hook site can fire, re-armed identically on every
    # (supervised) restart; a no-plan run pays one `is None` check per hook
    fault_plan = faults.install_from_config(cfg)
    if fault_plan is not None:
        master_print(f"fault injection ARMED (drill): {fault_plan.describe()}")
    mesh = build_mesh(cfg)
    master_print(f"mesh: {dict(mesh.shape)} over {jax.device_count()} devices "
                 f"({jax.process_count()} host(s))")
    attention_impl = _select_attention(cfg, mesh)

    # --- datasets (reference :223-225) ---
    train_ds, train_loader, _, val_loader = build_datasets(cfg, mesh)
    distributed.barrier("loaded dataset")
    master_print(f"\n=== dataset ===\n{pprint.pformat(train_ds)}\n")

    # --- model + optimizer, born sharded (reference :228-242) ---
    auto_resume = cfg.resume_epoch < 0
    if auto_resume:  # auto-resume: latest COMMITTED checkpoint, if any
        from vitax.checkpoint.orbax_io import latest_epoch
        # process 0 picks, everyone adopts: a non-atomic shared-store view
        # (e.g. GCS fuse) must not let hosts disagree on the resume epoch;
        # latest_epoch validates the Orbax commit marker, so a torn dir a
        # crash left mid-write is never selected
        found = distributed.broadcast_from_process0(latest_epoch(cfg.ckpt_dir) or 0)
        cfg = dataclasses.replace(cfg, resume_epoch=found)
        master_print(f"auto-resume: {'epoch ' + str(found) if found else 'no checkpoint found, fresh start'}")
    # step-granular resume: a mid-epoch (preemption) checkpoint carries the
    # completed step count in a sidecar — continue INSIDE that epoch instead
    # of skipping its remainder (improves on the reference's epoch-granular
    # --resume_epoch contract, run_vit_training.py:246-248)
    resume_step = 0
    if cfg.resume_epoch > 0:
        from vitax.checkpoint.orbax_io import load_resume_step
        resume_step = distributed.broadcast_from_process0(
            load_resume_step(cfg.ckpt_dir, cfg.resume_epoch) or 0)
    model = build_model(cfg, attention_impl=attention_impl,
                        token_sharding=_token_sharding(cfg, mesh),
                        moe_dispatch_sharding=_moe_dispatch_sharding(cfg, mesh))
    steps_per_epoch = cfg.steps_per_epoch or (len(train_ds) // cfg.batch_size)
    max_iteration = steps_per_epoch * cfg.num_epochs
    tx, schedule = build_optimizer(cfg, max_iteration)
    # On resume, build only the ABSTRACT state (no device materialization — the
    # checkpoint supplies the values; reference :246-248) and restore into it.
    state, state_specs, _ = make_train_state(
        cfg, model, tx, mesh, jax.random.key(cfg.seed),
        materialize=cfg.resume_epoch <= 0)
    if cfg.resume_epoch > 0:
        if auto_resume:
            # an auto-resume must survive one bad checkpoint: fall back to
            # the previous committed epoch (loudly) instead of wedging
            state, restored = restore_state_with_fallback(
                cfg.ckpt_dir, cfg.resume_epoch, state)
            if restored != cfg.resume_epoch:
                cfg = dataclasses.replace(cfg, resume_epoch=restored)
                from vitax.checkpoint.orbax_io import load_resume_step
                resume_step = distributed.broadcast_from_process0(
                    load_resume_step(cfg.ckpt_dir, restored) or 0)
        else:  # an explicit --resume_epoch N must mean N — fail hard
            state = restore_state(cfg.ckpt_dir, cfg.resume_epoch, state)
    distributed.barrier("loaded model")
    master_print(f"\n=== model ===\n{model}\n")
    master_print(f"global parameter num: {count_params(state.params)}")
    master_print(f"per-device (sharded) parameter num: {_sharded_param_count(state)}")
    from vitax.train.state import ADAMW_HPARAMS
    master_print(  # optimizer dump at startup (reference run_vit_training.py:242)
        f"\n=== optimizer ===\nAdamW(lr=warmup_cosine(base={cfg.lr}, "
        f"warmup={cfg.warmup_steps}, max_iteration={max_iteration}), "
        f"betas=({ADAMW_HPARAMS['b1']}, {ADAMW_HPARAMS['b2']}), "
        f"eps={ADAMW_HPARAMS['eps']}, weight_decay={cfg.weight_decay}, "
        f"clip_grad_norm={cfg.clip_grad_norm})\n")
    distributed.barrier("loaded optimizer")

    if cfg.grad_accum_steps > 1:
        # step-count/logging semantics are UNCHANGED: the scan over K
        # microbatches lives inside the compiled step, so each loader batch
        # is still exactly one optimizer step / one log line / one lr tick.
        master_print(
            f"grad accumulation: {cfg.grad_accum_steps} microbatches of "
            f"{cfg.batch_size // cfg.grad_accum_steps} inside the jitted "
            f"step (one optimizer step per loader batch)")
    train_step = make_train_step(cfg, model, tx, mesh, state_specs)
    eval_step = make_eval_step(cfg, model, mesh, state_specs)

    smoothed_loss = SmoothedValue(window_size=5)
    smoothed_time = SmoothedValue(window_size=5)
    from vitax.train import preempt
    preempt.install()  # SIGTERM -> committed save -> clean exit

    # --- telemetry (vitax/telemetry/): all host-side — the compiled step
    # program and its dispatch cadence are identical with telemetry off ---
    recorder = build_recorder(cfg, jax.device_count(),
                              platform.device_kind(),
                              rank=jax.process_index())
    if recorder is not None:
        master_print(f"telemetry: JSONL step records -> {cfg.metrics_dir} "
                     f"(MFU vs {recorder.peak_tflops:.0f} TF/s/chip peak"
                     + (", tensorboard mirror on" if cfg.tensorboard else "")
                     + ")")
        recorder.event("run_start", device_kind=recorder.device_kind,
                       n_devices=recorder.n_devices,
                       peak_tflops=recorder.peak_tflops,
                       flops_per_step=recorder.flops_per_step,
                       batch_size=cfg.batch_size)
        if fault_plan is not None:  # fired faults become kind:"fault" events
            faults.set_reporter(
                lambda payload: recorder.event("fault", **payload))
    watchdog = None
    if cfg.hang_timeout_s > 0:
        on_fire = ((lambda payload: recorder.event("hang", **payload))
                   if recorder is not None else None)
        on_escalate = ((lambda payload: recorder.event("hang_escalation",
                                                       **payload))
                       if recorder is not None else None)
        # built here, ARMED at the first dispatch return (see the step loop):
        # the first step blocks on XLA compilation — minutes at 10B scale —
        # and a watchdog ticking through it would escalate on a healthy run
        watchdog = Watchdog(cfg.hang_timeout_s, on_fire=on_fire,
                            rank=jax.process_index(),
                            action=cfg.hang_action,
                            on_escalate=on_escalate)
        master_print(
            f"watchdog: stack+memory dump after {cfg.hang_timeout_s:.0f}s "
            f"without a completed step (armed after the compile step)"
            + (f", then emergency checkpoint + exit {EXIT_HANG}"
               if cfg.hang_action == "checkpoint_exit" else ""))

    distributed.barrier("training begins")
    master_print("training begins (the first few iterations are very slow due to compilation)")

    prof = {"on": False}  # shared so the finally can close a mid-flight trace
    try:
        state = _run_epochs(
            cfg, state, train_step, train_loader, val_loader, eval_step,
            schedule, smoothed_loss, smoothed_time, prof,
            resume_step=resume_step, recorder=recorder, watchdog=watchdog)
    finally:
        if prof["on"]:
            jax.profiler.stop_trace()
            master_print(f"profile trace written to {cfg.profile_dir}")
        if watchdog is not None:
            watchdog.stop()  # before the loaders: their drain must not fire it
        train_loader.close()
        val_loader.close()
        from vitax.checkpoint.orbax_io import wait_until_finished
        wait_until_finished()  # drain any in-flight async save before exit
        if recorder is not None:
            recorder.close()
        faults.uninstall()  # fault plans are per-run, like the recorder
        preempt.uninstall()  # restore normal SIGTERM for post-training work

    master_print("training completed")
    return state


def _stream_cursor(loader, epoch: int, next_step: int):
    """The streaming data plane's resume cursor after `next_step` consumed
    batches, or None for loaders without one (ImageFolder/fake). Rides the
    mid-epoch checkpoint sidecar so the resumed run can validate its derived
    position against the shard set that produced the checkpoint."""
    fn = getattr(loader, "cursor_for_step", None)
    return fn(epoch, next_step) if fn is not None else None


def _verify_stream_resume(cfg, train_loader, resume_step: int) -> None:
    """Mid-epoch stream resume: check the sidecar cursor against the position
    this run derives from (seed, epoch, step). The derivation is the source
    of truth — the stored cursor exists to FAIL LOUDLY when the shard set,
    seed, or topology changed underneath the checkpoint (silently feeding
    different records is the failure mode). Process 0 only: the sidecar holds
    process 0's cursor, and a drifted shard manifest is global anyway."""
    if not resume_step or not hasattr(train_loader, "check_cursor"):
        return
    if jax.process_index() != 0:
        return
    from vitax.checkpoint.orbax_io import load_stream_cursor
    cursor = load_stream_cursor(cfg.ckpt_dir, cfg.resume_epoch)
    if cursor is not None:
        train_loader.check_cursor(cursor, resume_step)
        master_print(f"stream resume cursor verified: epoch "
                     f"{cursor.get('epoch')}, shard_cursor "
                     f"{cursor.get('shard_cursor')} "
                     f"({cursor.get('shard')}), record_offset "
                     f"{cursor.get('record_offset')}")


def _preempt_agreed(step_in_epoch) -> bool:
    """Did SIGTERM arrive, as agreed by ALL hosts? Single-host: the local flag
    (free, checked every step). Multi-host: the flag sync is a collective, so
    every host must call it at the same points — every PREEMPT_SYNC_STEPS
    steps in the step loop, and unconditionally at each epoch boundary
    (step_in_epoch=None) so epochs shorter than the cadence are still covered.
    Without agreement, one host entering the save while others keep stepping
    would interleave mismatched collectives and deadlock the pod."""
    from vitax.train import preempt
    if jax.process_count() == 1:
        return preempt.requested()
    on_cadence = (step_in_epoch is None
                  or (step_in_epoch + 1) % PREEMPT_SYNC_STEPS == 0)
    if not on_cadence:
        return False
    return distributed.any_across_processes(preempt.requested())


def _run_epochs(cfg, state, train_step, train_loader, val_loader, eval_step,
                schedule, smoothed_loss, smoothed_time, prof,
                resume_step: int = 0, recorder=None, watchdog=None):
    data_rng = jax.random.key(cfg.seed + 1)
    total_steps = 0
    steps_since_record = 0  # averaging window for the per-record data wait
    # profiler window (historical default: steps 3..7 — start after 2
    # completed steps so the compile step stays out of the trace)
    prof_start = cfg.profile_start_step
    prof_stop = cfg.profile_start_step + cfg.profile_num_steps
    # resume_step > 0: the resume checkpoint was a mid-epoch preemption save —
    # re-enter THAT epoch at the recorded step (the sampler order is a pure
    # function of (seed, epoch), so the data stream continues exactly where
    # the preempted run left off)
    start_epoch = cfg.resume_epoch + (0 if resume_step else 1)
    if resume_step:
        master_print(f"step-granular resume: re-entering epoch {start_epoch} "
                     f"at step {resume_step + 1}")
        _verify_stream_resume(cfg, train_loader, resume_step)
    for epoch in range(max(start_epoch, 1), cfg.num_epochs + 1):
        master_print(f"starting epoch {epoch}")
        time_epoch_b = time_step_b = time.time()
        metrics = None
        start_step = resume_step if epoch == start_epoch else 0
        for step, batch in enumerate(
                train_loader.epoch(epoch, start_step=start_step),
                start=start_step):
            if cfg.steps_per_epoch and step >= cfg.steps_per_epoch:
                break
            if cfg.profile_dir and total_steps == prof_start and not prof["on"]:
                jax.profiler.start_trace(cfg.profile_dir)
                prof["on"] = True
            state, metrics = train_step(state, batch, data_rng)
            total_steps += 1
            # fault drill point (no-op without a plan): fires BEFORE the pet
            # so an injected hang starves the watchdog exactly like a real
            # wedged step; index = the global step count, so plans are
            # deterministic across restarts of the same config
            faults.fire("step", index=total_steps)
            steps_since_record += 1
            if watchdog is not None:
                # pet on dispatch, not completion: the loop is alive; a wedged
                # DEVICE stalls the next log step's fence, which stops pets
                # within log_step_interval dispatches (async dispatch depth).
                # The FIRST dispatch return starts the watchdog instead: it
                # includes the XLA compile, which must not count as a stall
                # (--hang_timeout_s stays independent of compile time).
                if watchdog.running:
                    watchdog.pet()
                else:
                    watchdog.start()
            if prof["on"] and total_steps == prof_stop:
                jax.device_get(metrics["loss"])  # fence (block_until_ready is
                # a no-op on some PJRT transports, e.g. the axon tunnel)
                jax.profiler.stop_trace()
                prof["on"] = False
                master_print(f"profile trace written to {cfg.profile_dir}")

            # first step of THIS RUN (fresh start, epoch-granular resume, or
            # mid-epoch resume alike): always log it — it carries the compile
            is_first_iter = total_steps == 1
            will_log = is_first_iter or (step + 1) % cfg.log_step_interval == 0
            host_loss = None
            if will_log:
                # fence before reading the clock: train_step returns at
                # dispatch, so an unfenced delta times the async enqueue,
                # not device execution — the logged sec/iter would converge
                # to dispatch latency while the devices fall arbitrarily
                # far behind. Fetched ONCE here and passed through as a host
                # value (_run_logging and the telemetry record reuse it);
                # non-log steps stay fence-free so the pipeline keeps its
                # device/host overlap.
                host_loss = float(jax.device_get(metrics["loss"]))
            t_new = time.time()
            smoothed_time.update(t_new - time_step_b, batch_size=1)
            time_step_b = t_new
            if will_log:
                lr = float(schedule(int(jax.device_get(metrics["lr_step"]))))
                _run_logging(cfg, epoch, step, host_loss, lr, smoothed_loss,
                             smoothed_time)
                if recorder is not None:
                    # all inputs are already host values; the one extra
                    # device->host fetch (grad_norm) rides a log step that
                    # just fenced — non-log steps stay untouched
                    recorder.record_step(
                        step=total_steps, epoch=epoch, step_in_epoch=step + 1,
                        loss=host_loss, lr=lr,
                        sec_per_iter=smoothed_time.avg,
                        data_wait_s=(train_loader.consume_wait_s()
                                     / max(steps_since_record, 1)),
                        grad_norm=float(jax.device_get(metrics["grad_norm"])))
                steps_since_record = 0
            if watchdog is not None and watchdog.escalation_requested():
                # --hang_action checkpoint_exit: the watchdog flagged a hang
                # (flag-then-poll like preempt.py — its thread must never
                # touch device state); save a committed mid-epoch checkpoint
                # and exit EXIT_HANG for the supervisor to restart. The
                # acknowledge re-arms the watchdog's hard deadline so a save
                # wedged on a truly dead device is still bounded.
                watchdog.acknowledge_escalation()
                master_print(f"watchdog escalation: saving emergency "
                             f"checkpoint at epoch {epoch} (step {step + 1}) "
                             f"and exiting with code {EXIT_HANG}")
                jax.device_get(metrics["loss"])  # fence: step must be done
                save_state(cfg.ckpt_dir, epoch, state, wait=True,
                           step_in_epoch=step + 1,
                           stream_cursor=_stream_cursor(train_loader, epoch,
                                                        step + 1))
                raise SystemExit(EXIT_HANG)
            if _preempt_agreed(step_in_epoch=step):
                # commit a synchronous save of the live mid-epoch state under
                # this epoch's name (with the completed step count in the
                # resume sidecar), drain, and leave. Auto-resume
                # (--resume_epoch -1) restarts INSIDE this epoch at the next
                # step — no data is skipped or repeated.
                master_print(f"SIGTERM received: saving preemption checkpoint "
                             f"at epoch {epoch} (step {step + 1}) and exiting")
                jax.device_get(metrics["loss"])  # fence: step must be done
                save_state(cfg.ckpt_dir, epoch, state, wait=True,
                           step_in_epoch=step + 1,
                           stream_cursor=_stream_cursor(train_loader, epoch,
                                                        step + 1))
                return state
            if cfg.max_steps and total_steps >= cfg.max_steps:
                break

        if metrics is not None:
            jax.device_get(metrics["loss"])  # fence: honest epoch wall time
        master_print(f"epoch {epoch} done ({time.time() - time_epoch_b:.2f} sec)")

        if _preempt_agreed(step_in_epoch=None):  # epoch boundary: always sync
            # epochs shorter than the in-loop cadence still get a preemption
            # save here (every host reaches the boundary at the same point)
            master_print(f"SIGTERM received: saving preemption checkpoint "
                         f"after epoch {epoch} and exiting")
            save_state(cfg.ckpt_dir, epoch, state, wait=True)
            return state

        if epoch % cfg.ckpt_epoch_interval == 0 or epoch == cfg.num_epochs:
            # async: the device->host snapshot happens before return, the write
            # commits in background while the next epoch trains; the final save
            # waits so training never exits with an uncommitted checkpoint
            save_state(cfg.ckpt_dir, epoch, state, wait=epoch == cfg.num_epochs)
        if epoch % cfg.test_epoch_interval == 0 or epoch == cfg.num_epochs:
            top1, top5, _, _ = eval_on_val(cfg, val_loader, eval_step, state,
                                           recorder=recorder, epoch=epoch)
            master_print(f"accuracy on val: {top1:.4f} (top-5 {top5:.4f})")
        if cfg.max_steps and total_steps >= cfg.max_steps:
            break

    return state


def _token_sharding(cfg: Config, mesh):
    """(B, N, D) activation sharding: batch over (dp, fsdp), tokens over sp.
    Anchors GSPMD propagation; None on single-device meshes."""
    if mesh.size == 1:
        return None
    from jax.sharding import NamedSharding, PartitionSpec as P
    sp = mesh.shape.get("sp", 1)
    token_axis = "sp" if (sp > 1 and cfg.num_patches % sp == 0) else None
    return NamedSharding(mesh, P(BATCH_AXES, token_axis, None))


def _moe_dispatch_sharding(cfg: Config, mesh):
    """(E, B, C, D) dispatched-tensor sharding for the MoE einsums: experts
    over "ep", batch over the data axes. The explicit anchor makes GSPMD
    lower dispatch/combine to all-to-alls instead of the partitioner's
    involuntary full rematerialization. None when dense or single-device."""
    if cfg.moe_experts == 0 or mesh.size == 1:
        return None
    from jax.sharding import NamedSharding, PartitionSpec as P
    ep = mesh.shape.get("ep", 1)
    return NamedSharding(
        mesh, P("ep" if ep > 1 else None, ("dp", "fsdp"), None, None))


def _select_attention(cfg: Config, mesh):
    """Pick the attention core (vitax.ops.attention.make_attention_impl):
    ring attention under sp, whole-N or streaming Pallas kernel on TPU,
    dense jnp elsewhere."""
    from vitax.ops.attention import make_attention_impl
    impl = make_attention_impl(cfg, mesh)
    master_print("attention core: "
                 + getattr(impl, "vitax_name", "dense jnp"))
    return impl


def _run_logging(cfg, epoch, step, loss, lr, smoothed_loss, smoothed_time):
    """Throttled step log (reference run_logging, run_vit_training.py:203-213).

    The loss is already the global-batch mean — the reference's
    mesh_reduce(sum)/world_size (:205-206) is compiled into the step. The
    caller fetched it (and resolved lr) once at the log-step fence and passes
    the host values through — no second device->host sync here."""
    smoothed_loss.update(loss, batch_size=1)
    mem = f", {memory_summary()}" if cfg.log_memory else ""
    master_print(
        f"epoch {epoch} step {step + 1}, lr: {lr:.4f}, "
        f"loss: {smoothed_loss.avg:.4f}, "
        f"sec/iter: {smoothed_time.avg:.4f}{mem}"
    )


def eval_on_val(cfg: Config, val_loader, eval_step, state: TrainState,
                recorder=None, epoch: int = 0):
    """Top-1 + top-5 accuracy over the val split (reference eval_on_val,
    run_vit_training.py:306-318, extended with the top-5 metric the serving
    stack reports). drop_last semantics preserved: the remainder of the
    split is ignored, exactly like the reference (:77,:83).

    With a Recorder (--metrics_dir), emits one kind:"eval" telemetry event
    (epoch, top1, top5, n) per eval pass — tools/metrics_report.py surfaces
    the latest one. Returns (top1, top5, n_correct, total)."""
    correct = None
    total = 0
    for step, batch in enumerate(val_loader.epoch(0)):
        if cfg.eval_max_batches and step >= cfg.eval_max_batches:
            break
        c = eval_step(state, batch)
        correct = c if correct is None else jax.tree.map(
            lambda a, b: a + b, correct, c)
        total += cfg.batch_size
    counts = (jax.device_get(correct) if correct is not None
              else {"correct": 0, "correct_top5": 0})
    n_correct = int(counts["correct"])
    n_top5 = int(counts["correct_top5"])
    top1 = n_correct / total if total else 0.0
    top5 = n_top5 / total if total else 0.0
    if recorder is not None:
        recorder.event("eval", epoch=int(epoch), top1=top1, top5=top5,
                       n=total)
    return top1, top5, n_correct, total
