"""LR schedule: linear warmup then half-cosine decay to 0.

Exact parity with the reference's LambdaLR multiplier (reference utils.py:11-21):
  step < warmup:  ratio = step / warmup          (so lr == 0 at step 0)
  else:           where = (step - warmup) / (max - warmup)
                  ratio = 0.5 * (1 + cos(pi * where))

Implemented as a pure step -> lr function (optax-style schedule), evaluated inside
the jitted train step — no host-side scheduler object to keep in sync.
"""

from __future__ import annotations

import jax.numpy as jnp


def warmup_cosine_schedule(base_lr: float, warmup_iteration: int, max_iteration: int):
    """Returns schedule(step) -> lr. Matches reference utils.py:12-19 including
    lr == 0 at step 0 and cosine reaching 0 at max_iteration; with
    warmup_iteration == 0 the warmup branch is never taken (pure cosine from
    step 0), exactly like the reference's `step < warmup` test."""
    warmup = int(warmup_iteration)

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        warm_ratio = step / max(warmup, 1)  # divisor unused when warmup == 0
        denom = max(max_iteration - warmup, 1)
        where = (step - warmup) / denom
        cos_ratio = 0.5 * (1.0 + jnp.cos(jnp.pi * where))
        ratio = jnp.where(step < warmup, warm_ratio, cos_ratio)
        return base_lr * ratio

    return schedule
