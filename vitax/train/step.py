"""Jitted train/eval steps.

The reference's hot loop (run_vit_training.py:259-291, SURVEY.md section 3.2) —
forward, CE loss, backward, FSDP collectives, grad clip, AdamW update, LR step —
is ONE compiled XLA program here. GSPMD inserts the per-layer all-gathers and
grad reduce-scatters from the parameter shardings; the loss mean over the
globally-sharded batch compiles to the cross-replica reduction the reference
performs by hand (xm.mesh_reduce, run_vit_training.py:205-206).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from vitax.config import Config
from vitax.ops.fused_optimizer import fused_clip_adamw, fused_optimizer_active
from vitax.parallel.mesh import BATCH_AXES, Mesh, batch_pspec
from vitax.parallel.sharding import (
    gather_over_fsdp, gather_overlap_active, make_comm_precision, shardings_of)
from vitax.train.state import ADAMW_HPARAMS, TrainState

PyTree = Any


def _needs_dropout(cfg: Config) -> bool:
    return (cfg.pos_dropout > 0) or (cfg.att_dropout > 0) or (cfg.mlp_dropout > 0)


def _make_logits_anchor(mesh: Mesh):
    """Anchor (B, C) logits batch-sharded: under 3-axis-batch meshes (dp x
    fsdp x ep) the CE softmax backward and the eval argmax iota otherwise
    land on mixed layouts the partitioner reaches only by involuntary full
    rematerialization (same family as the activation anchors in
    vitax/models/vit.py). Identity on single-device meshes."""
    if mesh.size == 1:
        return lambda logits: logits
    sharding = NamedSharding(mesh, P(batch_pspec()[0], None))
    return lambda logits: jax.lax.with_sharding_constraint(logits, sharding)


def _select_by_name(cols, name: str):
    """Leaves of the 'intermediates' collection whose path contains `name` —
    sown values are selected BY NAME so any future sow (e.g. a debug metric)
    cannot silently join the training objective (ADVICE r3)."""
    return [leaf for path, leaf in jax.tree_util.tree_leaves_with_path(cols)
            if any(getattr(k, "key", None) == name for k in path)]


def aux_from_frac_prob(fracs, probs, cfg: Config):
    """Switch load-balance loss from the sown per-block (E,) ingredients:
    mean over blocks of E * sum_e(frac_e * prob_e). Works on stacked
    (L, ..., E) leaves (the scan path) and per-block lists (unrolled /
    pipeline paths) alike — the leading axes all reduce into the sum, and
    the division by num_blocks restores the per-block mean."""
    assert fracs and len(fracs) == len(probs), (len(fracs), len(probs))
    total = sum(jnp.sum(f * p) for f, p in zip(fracs, probs))
    return cfg.moe_experts * total / cfg.num_blocks


def _forward_fn(cfg: Config, model, mesh: Mesh, state_specs=None):
    """Unified forward: (params, images, det=True, rng=None, with_aux=False)
    -> logits, or (logits, moe_aux) when with_aux.

    model.apply, or the GPipe pipeline over the "pp" mesh axis when
    --pp_size > 1 (vitax/parallel/pipeline.py — same param tree, different
    block application; dropout keys and the MoE aux ingredients are threaded
    through the pipeline body). The block-param specs (P("pp", ...) +
    optional "fsdp" dims) come from the state spec tree so the pipeline's
    just-in-time ZeRO-3 gathers match the actual layout."""
    if getattr(cfg, "pp_size", 1) > 1 and mesh.shape.get("pp", 1) > 1:
        from vitax.parallel.pipeline import make_pp_forward
        block_specs = None
        if state_specs is not None:
            block_specs = state_specs.params["params"]["blocks"]
        return make_pp_forward(cfg, model, mesh, block_specs=block_specs)
    if gather_overlap_active(cfg, mesh):
        # double-buffered ZeRO-3 gather schedule: the scan carry prefetches
        # the next group's gathered params so the collective overlaps the
        # current group's compute (subsumes the windowed path — groups are
        # --remat_window blocks when the window is active, else one block)
        from vitax.models.vit import make_overlap_forward
        assert state_specs is not None, (
            "gather_overlap needs the state spec tree for the stacked "
            "block-param layout")
        return make_overlap_forward(
            cfg, model, mesh, state_specs.params["params"]["blocks"])
    if getattr(cfg, "remat_window", 0) > 1:
        # group-remat functional scan (the wgrad dus-stacking experiment;
        # same param tree, different checkpoint placement)
        from vitax.models.vit import make_windowed_forward
        return make_windowed_forward(cfg, model)

    def forward(params, images, det=True, rng=None, with_aux=False):
        rngs = {"dropout": rng} if (rng is not None and not det) else None
        if not with_aux:
            return model.apply(params, images, det, rngs=rngs)
        logits, cols = model.apply(params, images, det, rngs=rngs,
                                   mutable=["intermediates"])
        fracs = _select_by_name(cols, "moe_frac_tokens")
        probs = _select_by_name(cols, "moe_mean_prob")
        if with_aux == "raw":
            # uncombined per-block ingredients, for callers that average
            # them across grad-accum microbatches BEFORE the product
            return logits, (tuple(fracs), tuple(probs))
        return logits, aux_from_frac_prob(fracs, probs, cfg)

    return forward


def prepare_images(images: jax.Array) -> jax.Array:
    """Device-side ToTensor+Normalize for uint8 batches (the host pipeline's
    reference transforms, run_vit_training.py:44-45/:53-54, moved inside the
    compiled step so batches cross host->device as uint8 — 4x less transfer).
    Float inputs (fake data, --host_normalize, bench tensors) pass through."""
    if images.dtype != jnp.uint8:
        return images
    from vitax.data.transforms import IMAGENET_MEAN, IMAGENET_STD
    mean = jnp.asarray(IMAGENET_MEAN, jnp.float32)
    std = jnp.asarray(IMAGENET_STD, jnp.float32)
    return (images.astype(jnp.float32) / 255.0 - mean) / std


def _microbatch_split(batch: PyTree, k_steps: int, mesh: Mesh) -> PyTree:
    """Reshape every (B, ...) leaf to (K, B/K, ...) with a STRIDED sample
    assignment: reshape to (B/K, K, ...) then swap the leading axes, so
    microbatch k holds samples {k, k + K, k + 2K, ...}. Under the batch
    sharding, element (j, k) = sample j*K + k stays inside the owning
    device's contiguous [d*B/D, (d+1)*B/D) range — the split costs no
    cross-device data movement. CE and the MoE router/aux ingredients are
    per-sample, so WHICH samples share a microbatch cannot change the
    summed gradient."""
    def split(x):
        xs = x.reshape(x.shape[0] // k_steps, k_steps, *x.shape[1:])
        xs = xs.swapaxes(0, 1)
        if mesh.size > 1:
            spec = P(None, batch_pspec()[0], *(None,) * (x.ndim - 1))
            xs = jax.lax.with_sharding_constraint(
                xs, NamedSharding(mesh, spec))
        return xs
    return jax.tree.map(split, batch)


def _make_update_fn(cfg: Config, tx, mesh: Mesh, state_specs, schedule):
    """The optimizer phase: update(grads, opt_state, params) ->
    (new_params, new_opt_state, grad_norm). Shared by the train step and the
    opt_update_s telemetry probe (make_opt_probe).

    ONE global-norm reduction per step feeds both the clip and the grad_norm
    metric (the old step re-reduced the tree optax's clip_by_global_norm had
    already walked). The clip applies optax's exact formula off that shared
    norm, so the value chain is bit-identical to the chained transform.

    With the fused optimizer active (vitax/ops/fused_optimizer.py), clip +
    AdamW + weight decay + param step run as one Pallas pass per leaf group,
    in place, shard-local under the FSDP specs."""
    fused = fused_optimizer_active(cfg)
    if fused and schedule is None:
        raise ValueError(
            "fused optimizer is active but no lr schedule was provided — "
            "pass build_optimizer's second return value as schedule=")

    def update(grads, opt_state, params):
        grad_norm = optax.global_norm(grads)
        if fused:
            new_params, new_opt_state = fused_clip_adamw(
                grads, opt_state, params,
                grad_norm=grad_norm,
                schedule=schedule,
                clip_norm=cfg.clip_grad_norm,
                weight_decay=cfg.weight_decay,
                mesh=mesh if mesh.size > 1 else None,
                param_specs=state_specs.params,
                **ADAMW_HPARAMS)
            return new_params, new_opt_state, grad_norm
        if cfg.clip_grad_norm > 0:
            # optax.clip_by_global_norm's update_fn, verbatim, off the
            # shared reduction
            trigger = jnp.squeeze(grad_norm < cfg.clip_grad_norm)
            grads = jax.tree.map(
                lambda t: jax.lax.select(
                    trigger, t,
                    (t / grad_norm.astype(t.dtype)) * cfg.clip_grad_norm),
                grads)
        updates, new_opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_opt_state, grad_norm

    return update


def make_opt_probe(
    cfg: Config,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    state_specs: PyTree,
    schedule=None,
):
    """Jitted optimizer-phase probe for the opt_update_s telemetry:
    (state) -> (new_params, new_opt_state, grad_norm) over all-zero grads at
    the state shardings — the same update program the train step runs, timed
    in isolation. A SEPARATE, non-donating compile: the train step's program
    is untouched (tests/test_telemetry.py pins its identity), the probe's
    outputs are discarded, and the loop invokes it at log steps only."""
    state_shardings = shardings_of(mesh, state_specs)
    update_fn = _make_update_fn(cfg, tx, mesh, state_specs, schedule)

    def probe(state: TrainState):
        grads = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32),
                             state.params)
        if mesh.size > 1:
            grads = jax.lax.with_sharding_constraint(
                grads, shardings_of(mesh, state_specs.params))
        return update_fn(grads, state.opt_state, state.params)

    return jax.jit(
        probe,
        in_shardings=(state_shardings,),
        out_shardings=(state_shardings.params, state_shardings.opt_state,
                       None),
    )


def make_train_step(
    cfg: Config,
    model,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    state_specs: PyTree,
    donate: bool = True,
    schedule=None,
) -> Callable[[TrainState, Dict[str, jax.Array], jax.Array], Tuple[TrainState, Dict[str, jax.Array]]]:
    """Build the jitted train step: (state, batch, rng) -> (state, metrics).

    - `donate` on state: params/opt-state buffers are reused in place.
      `donate=False` exists for the program-invariant verifier only
      (vitax/analysis/rules.py donation-honored rule compiles it as the
      deliberately-broken negative arm); production callers always donate.
    - `schedule` is build_optimizer's second return value (the pure lr
      schedule). Required when the fused optimizer is active — the fused
      path evaluates it directly instead of optax's scale_by_schedule; the
      optax path ignores it.
    - ZeRO-2 mode (`--no_reshard_after_forward`): params are constrained to a
      fully-gathered (over "fsdp") layout at the top of the step, so the
      all-gather happens once and the gathered weights stay live through
      backward; grads and optimizer state remain sharded.
    - `--grad_accum_steps K > 1`: a lax.scan over K microbatches of B/K
      accumulates fp32 grads inside this same compiled program — one clip +
      AdamW update (and one loss/grad_norm metric) per loader batch, peak
      activations ~ one microbatch. The ZeRO-2 gather above happens ONCE
      (scan-invariant) and is reused by all K microbatches. K == 1 traces
      the exact pre-accumulation program (no scan wrapper, no extra rng
      fold) — the compiled step is unchanged.
    - Comm precision (`--param_gather_dtype` / `--grad_reduce_dtype`,
      vitax/parallel/sharding.py cast_to_compute): when active, the f32
      master tree is downcast to bf16 while still sharded, so every FSDP
      param collective moves bf16 bytes. The cast sits INSIDE autodiff for
      the value_and_grad paths (its convert-vjp upcasts cotangents to f32
      and pins the grad-reduction dtype); the ZeRO-2 step-top gather and the
      1f1b hand-assembled backward cast outside autodiff and upcast grads
      explicitly via `finalize_grads`. With the policy off (or
      --param_gather_dtype float32) the traced program is bit-for-bit the
      pre-policy one.
    """
    state_shardings = shardings_of(mesh, state_specs)
    batch_sharding = NamedSharding(mesh, batch_pspec())
    rng_sharding = NamedSharding(mesh, P())
    dropout = _needs_dropout(cfg)
    forward = _forward_fn(cfg, model, mesh, state_specs)
    comm = make_comm_precision(cfg, mesh, state_specs.params)
    update_fn = _make_update_fn(cfg, tx, mesh, state_specs, schedule)

    moe = cfg.moe_experts > 0
    anchor_logits = _make_logits_anchor(mesh)

    def loss_fn(params, batch, rng):
        if comm is not None:
            # idempotent: leaves the ZeRO-2 path pre-cast (already bf16)
            # untouched; elsewhere the convert-vjp rides the backward
            params = comm.cast(params)
        images = prepare_images(batch["image"])
        det = not dropout
        r = rng if dropout else None
        if moe:
            # the per-block MoE load-balance ingredients ride the
            # "intermediates" collection (vitax/models/moe.py); weighted
            # into the objective (Switch Transformer)
            logits, aux = forward(params, images, det, rng=r, with_aux=True)
        else:
            logits = forward(params, images, det, rng=r)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            anchor_logits(logits), batch["label"]).mean()
        if moe:
            loss = loss + cfg.moe_aux_weight * aux
        return loss

    zero2 = not cfg.reshard_after_forward and not cfg.run_without_fsdp
    gathered_shardings = (
        shardings_of(mesh, gather_over_fsdp(state_specs.params)) if zero2 else None)

    use_1f1b = (getattr(cfg, "pp_schedule", "gpipe") == "1f1b"
                and cfg.pp_size > 1 and mesh.shape.get("pp", 1) > 1)
    if use_1f1b:
        # the interleaved schedule computes the loss INSIDE the pipelined
        # region (per microbatch, at the last stage) and hand-assembles the
        # grads — it replaces value_and_grad wholesale
        from vitax.parallel.pipeline_1f1b import make_1f1b_value_and_grad
        vag_1f1b = make_1f1b_value_and_grad(cfg, model, mesh, state_specs)

    k_steps = int(getattr(cfg, "grad_accum_steps", 1) or 1)
    if k_steps > 1:
        assert not use_1f1b and getattr(cfg, "pp_size", 1) == 1, (
            "grad accumulation under pipeline parallelism is rejected by "
            "Config.validate()")
        assert cfg.batch_size % k_steps == 0, (cfg.batch_size, k_steps)
        batch_devices = 1
        for ax in BATCH_AXES:
            batch_devices *= mesh.shape.get(ax, 1)
        assert (cfg.batch_size // k_steps) % batch_devices == 0, (
            f"microbatch {cfg.batch_size}/{k_steps} = "
            f"{cfg.batch_size // k_steps} not divisible by the "
            f"{batch_devices} batch-sharding devices (dp x fsdp x ep)")
        # grads accumulate at the SHARDED param layout (fp32): each
        # microbatch's backward reduce-scatters into the accumulator rather
        # than holding a gathered grad tree live — under ZeRO-2 the gathered
        # layout applies to params only.
        accum_shardings = state_shardings.params

    def accum_value_and_grad_dense(params, mbs, step_rng):
        """Manual accumulation (dense objective): per-microbatch
        value_and_grad inside the scan body — backward runs per iteration,
        so residuals live for ONE microbatch — summed into an fp32 carry.
        Exact vs K=1 by linearity of the gradient in the loss mean."""
        grad0 = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32),
                             params)

        def accum(carry, xs):
            gsum, loss_sum = carry
            mb, k = xs
            loss_k, g_k = jax.value_and_grad(loss_fn)(
                params, mb, jax.random.fold_in(step_rng, k))
            if comm is not None:
                # ZeRO-2 pre-cast params yield bf16 microbatch grads: pin
                # the per-microbatch reduction dtype and upcast before the
                # f32 accumulation (no-op on the already-f32 grad paths)
                g_k = comm.finalize_grads(g_k)
            gsum = jax.tree.map(lambda a, g: a + g.astype(jnp.float32),
                                gsum, g_k)
            if mesh.size > 1:
                gsum = jax.lax.with_sharding_constraint(
                    gsum, accum_shardings)
            return (gsum, loss_sum + loss_k), None

        if mesh.size > 1:
            grad0 = jax.lax.with_sharding_constraint(grad0, accum_shardings)
        (gsum, loss_sum), _ = jax.lax.scan(
            accum, (grad0, jnp.zeros((), jnp.float32)),
            (mbs, jnp.arange(k_steps, dtype=jnp.uint32)))
        scale = 1.0 / k_steps
        return loss_sum * scale, jax.tree.map(lambda g: g * scale, gsum)

    def accum_loss_moe(params, mbs, step_rng):
        """MoE objective, differentiated THROUGH the microbatch scan: the
        load-balance aux couples microbatches (its ingredients are
        full-batch means taken before the frac*prob product), so the exact
        full-batch gradient cannot be formed one microbatch at a time. The
        scan body emits per-microbatch CE and RAW aux ingredients as
        stacked outputs; the objective combines their means AFTER the scan
        — identical to K=1 up to fp reassociation. jax.checkpoint on the
        body keeps residuals at one microbatch (the backward recomputes
        each microbatch's forward — ~+1F vs the dense manual path).

        Comm-precision caveat: the cast happens once outside the scan, so
        the scan's cross-microbatch cotangent accumulation for the (scan-
        invariant) params runs in bf16 under the bf16 policy — the one path
        that trades accumulation precision for the comm win. Use
        --param_gather_dtype float32 with MoE + grad accumulation if exact
        f32 accumulation matters more than gather bytes."""
        if comm is not None:
            params = comm.cast(params)

        def mb_terms(p, mb, k):
            images = prepare_images(mb["image"])
            r = jax.random.fold_in(step_rng, k) if dropout else None
            logits, (fracs, probs) = forward(p, images, not dropout, rng=r,
                                             with_aux="raw")
            ce = optax.softmax_cross_entropy_with_integer_labels(
                anchor_logits(logits), mb["label"]).mean()
            return ce, fracs, probs

        mb_ckpt = jax.checkpoint(mb_terms, prevent_cse=False)

        def body(carry, xs):
            mb, k = xs
            return carry, mb_ckpt(params, mb, k)

        _, (ces, frac_stacks, prob_stacks) = jax.lax.scan(
            body, jnp.zeros((), jnp.float32),
            (mbs, jnp.arange(k_steps, dtype=jnp.uint32)))
        fracs = [jnp.mean(f, axis=0) for f in frac_stacks]
        probs = [jnp.mean(p, axis=0) for p in prob_stacks]
        return (jnp.mean(ces)
                + cfg.moe_aux_weight * aux_from_frac_prob(fracs, probs, cfg))

    def accum_value_and_grad(params, batch, step_rng):
        mbs = _microbatch_split(batch, k_steps, mesh)
        if moe:
            return jax.value_and_grad(accum_loss_moe)(params, mbs, step_rng)
        return accum_value_and_grad_dense(params, mbs, step_rng)

    def train_step(state: TrainState, batch, rng):
        step_rng = jax.random.fold_in(rng, state.step)
        if zero2:
            # cast the SHARDS, then gather: the step-top all-gather (once per
            # step, reused by backward and all grad-accum microbatches) moves
            # bf16 bytes and the gathered tree holds half the live memory
            params = state.params if comm is None else comm.cast(state.params)
            params = jax.lax.with_sharding_constraint(params, gathered_shardings)
        elif use_1f1b and comm is not None:
            # the 1f1b schedule hand-assembles grads (no value_and_grad), so
            # the cast sits outside autodiff; finalize_grads upcasts below
            params = comm.cast(state.params)
        else:
            params = state.params
        if use_1f1b:
            loss, grads = vag_1f1b(params, prepare_images(batch["image"]),
                                   batch["label"])
        elif k_steps > 1:
            loss, grads = accum_value_and_grad(params, batch, step_rng)
        else:
            loss, grads = jax.value_and_grad(loss_fn)(params, batch, step_rng)
        if comm is not None:
            grads = comm.finalize_grads(grads)
        new_params, new_opt_state, grad_norm = update_fn(
            grads, state.opt_state, state.params)
        new_state = TrainState(
            step=state.step + 1, params=new_params, opt_state=new_opt_state)
        metrics = {
            "loss": loss,
            # the same reduction that fed the clip — not a second pass
            "grad_norm": grad_norm,
            # post-step schedule position: the reference logs lr AFTER
            # lr_scheduler.step() (run_vit_training.py:288); the host resolves
            # the value via the pure schedule fn
            "lr_step": new_state.step,
        }
        return new_state, metrics

    jitted = jax.jit(
        train_step,
        in_shardings=(state_shardings, batch_sharding, rng_sharding),
        out_shardings=(state_shardings, None),
        donate_argnums=(0,) if donate else (),
    )

    # Work counts for the telemetry throughput records (images/s, tokens/s).
    # They are static functions of the config, so they are attached HOST-SIDE
    # after the jitted call: the compiled program gains no outputs, no device
    # ops, and no device->host syncs (tests/test_telemetry.py pins the
    # lowered program's equality against the bare step).
    images_per_step = cfg.batch_size
    tokens_per_step = cfg.batch_size * cfg.num_patches

    def step_with_counts(state, batch, rng):
        new_state, metrics = jitted(state, batch, rng)
        metrics = dict(metrics, images=images_per_step,
                       tokens=tokens_per_step)
        return new_state, metrics

    step_with_counts.lower = jitted.lower  # AOT surface (tools/, tests/)
    step_with_counts.trace = jitted.trace  # jaxpr surface (VTX-R008)
    return step_with_counts


def make_eval_step(cfg: Config, model, mesh: Mesh, state_specs: PyTree):
    """Jitted eval step: (state, batch) -> {"correct", "correct_top5"}
    prediction counts over the global batch (reference eval_on_val's
    device-side accumulator + mesh_reduce, run_vit_training.py:306-318, as
    one compiled reduction; top-5 rides the same compiled program via
    lax.top_k — with < 5 classes, k clamps and top-5 equals top-k)."""
    state_shardings = shardings_of(mesh, state_specs)
    batch_sharding = NamedSharding(mesh, batch_pspec())
    forward = _forward_fn(cfg, model, mesh, state_specs)
    comm = make_comm_precision(cfg, mesh, state_specs.params)

    anchor_logits = _make_logits_anchor(mesh)
    k5 = min(5, cfg.num_classes)

    def eval_step(state: TrainState, batch):
        params = state.params if comm is None else comm.cast(state.params)
        logits = forward(params, prepare_images(batch["image"]), True)
        # same batch-sharded logits anchor as the train loss (the argmax
        # iota is the eval-side victim of the mixed layout)
        logits = anchor_logits(logits)
        pred = jnp.argmax(logits, axis=-1)
        _, top5 = jax.lax.top_k(logits, k5)
        in_top5 = jnp.any(top5 == batch["label"][:, None], axis=-1)
        return {
            "correct": jnp.sum((pred == batch["label"]).astype(jnp.int32)),
            "correct_top5": jnp.sum(in_top5.astype(jnp.int32)),
        }

    return jax.jit(
        eval_step,
        in_shardings=(state_shardings, batch_sharding),
        out_shardings=None,
    )
