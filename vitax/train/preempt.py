"""Preemption-safe shutdown: SIGTERM -> committed checkpoint -> clean exit.

TPU VMs (and most cluster schedulers) deliver SIGTERM with a grace window
before a hard kill. The reference has no preemption story — recovery is a
manual relaunch with --resume_epoch (reference run_vit_training.py:246-248,
README.md restart notes). Here the async-checkpoint design (orbax_io.py)
makes a graceful path cheap: the handler only sets a flag; the train loop
checks it at the next step boundary, takes a synchronous (wait=True) save of
the live state, drains the async checkpointer, and returns — so `--resume_epoch
-1` auto-resume finds a complete, committed checkpoint.

The flag-then-poll design keeps the handler async-signal-safe (no JAX, no IO
inside the handler) and the save on the main thread where the device state
lives.
"""

from __future__ import annotations

import signal
import threading

_REQUESTED = threading.Event()
_INSTALLED = False
_PREV_HANDLER = None


def _handler(signum, frame):  # noqa: ARG001 — signal handler signature
    _REQUESTED.set()


def install() -> bool:
    """Install the SIGTERM handler (idempotent). Returns False when not on the
    main thread (signal.signal raises there — e.g. pytest-xdist workers);
    preemption saving is then simply unavailable, never fatal."""
    global _INSTALLED, _PREV_HANDLER
    if _INSTALLED:
        return True
    try:
        _PREV_HANDLER = signal.signal(signal.SIGTERM, _handler)
    except ValueError:  # not the main thread
        return False
    # a SIGTERM that arrived after a PREVIOUS train() stopped polling (e.g.
    # during its final eval/drain) must not preempt THIS run at step 1
    _REQUESTED.clear()
    _INSTALLED = True
    return True


def uninstall() -> None:
    """Restore the pre-install SIGTERM disposition (idempotent). train() calls
    this on exit so post-training work (consolidation, host scripts, pytest)
    keeps normal SIGTERM semantics instead of a flag nobody polls."""
    global _INSTALLED, _PREV_HANDLER
    if not _INSTALLED:
        return
    try:
        signal.signal(signal.SIGTERM,
                      _PREV_HANDLER if _PREV_HANDLER is not None
                      else signal.SIG_DFL)
    except ValueError:
        pass  # not the main thread: leave as-is
    _INSTALLED = False
    _PREV_HANDLER = None


def requested() -> bool:
    """True once SIGTERM has been delivered (sticky until reset())."""
    return _REQUESTED.is_set()


def reset() -> None:
    """Clear the flag (tests; or a supervisor that decides to continue)."""
    _REQUESTED.clear()
