"""`python -m vitax.train` — the module-form training entry point.

Identical surface to run_vit_training.py (parse_config's full flag set,
--preset_file included, so a committed autotune winner drives a real run:
`python -m vitax.train --fake_data --preset_file presets/l14_v5e-1.json`).
Backend pinning must happen before anything touches jax.devices(), hence
the force_cpu_if_requested() call ahead of the train import.
"""

from vitax.platform import force_cpu_if_requested

force_cpu_if_requested()

from vitax.config import parse_config  # noqa: E402
from vitax.train.loop import train  # noqa: E402


def main(argv=None):
    cfg = parse_config(argv)
    train(cfg)


if __name__ == "__main__":
    main()
