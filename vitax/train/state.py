"""Train state and optimizer construction.

Replaces the reference's (model, optimizer, lr_scheduler) triple
(reference run_vit_training.py:228-240) with one immutable pytree carried
through the jitted step: {step, params, opt_state}. The LR schedule is a pure
function of `step`, so there is no separate scheduler state to checkpoint —
`step` alone reproduces it (reference save_ckpt's lr_scheduler entry,
utils.py:31, collapses to this).
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import optax

from vitax.config import Config
from vitax.parallel.mesh import Mesh
from vitax.parallel.sharding import (
    jit_init_sharded,
    param_specs,
    shardings_of,
    state_specs_like,
)
from vitax.train.schedule import warmup_cosine_schedule

PyTree = Any


class TrainState(flax.struct.PyTreeNode):
    step: jax.Array          # scalar int32 — optimizer step counter
    params: PyTree           # flax variables dict {"params": ...}
    opt_state: PyTree        # optax state (AdamW moments inherit param sharding)


def build_optimizer(cfg: Config, max_iteration: int) -> Tuple[optax.GradientTransformation, Callable]:
    """AdamW + global-norm clip + warmup-cosine, matching the reference:
    - clip BEFORE the update (reference clips grads then steps,
      run_vit_training.py:266-278); clipping by *global* norm of sharded grads
      is exact under jit — the norm is computed with a compiled all-reduce,
      which is what FSDP's model.clip_grad_norm_ does by hand (run_vit_training.py:270).
      The clip itself is applied in the train step (vitax/train/step.py),
      bitwise-reproducing optax.clip_by_global_norm's formula off the SAME
      global-norm reduction that feeds the grad_norm metric — one norm pass
      per step instead of two. The chain keeps an optax.identity() in the
      clip's historical slot so the opt_state tree (and with it state_specs,
      checkpoints, and donation) is unchanged: both lower to EmptyState.
    - AdamW betas (0.9, 0.999), eps 1e-8, weight decay on ALL params
      (torch.optim.AdamW semantics, reference run_vit_training.py:237)
    """
    schedule = warmup_cosine_schedule(cfg.lr, cfg.warmup_steps, max_iteration)
    parts = []
    if cfg.clip_grad_norm > 0:
        parts.append(optax.identity())
    parts.append(
        optax.adamw(schedule, weight_decay=cfg.weight_decay, **ADAMW_HPARAMS))
    return optax.chain(*parts), schedule


# torch.optim.AdamW defaults (reference run_vit_training.py:237); the startup
# optimizer dump (train/loop.py) prints from this same dict
ADAMW_HPARAMS = dict(b1=0.9, b2=0.999, eps=1e-8)


def make_train_state(
    cfg: Config,
    model,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    rng: jax.Array,
    materialize: bool = True,
) -> Tuple[TrainState, PyTree, PyTree]:
    """Create the train state born sharded: params AND AdamW moments are
    materialized directly into their shards — no host or device ever holds the
    full 10B tree (the shard_on_cpu capability, done the XLA way).

    With materialize=False, returns the *abstract* state (ShapeDtypeStructs
    carrying target shardings) — the restore target for checkpoint resume,
    costing no device memory.

    Returns (state, state_specs, param_specs).
    """
    # sample batch must divide evenly over the (dp, fsdp) batch axes — the
    # attention shard_map paths trace through init
    sample_b = mesh.shape["dp"] * mesh.shape["fsdp"]
    sample = jnp.zeros((sample_b, cfg.image_size, cfg.image_size, 3), jnp.float32)

    def init_fn(rng):
        params = model.init(rng, sample, True)
        opt_state = tx.init(params)
        return TrainState(step=jnp.zeros((), jnp.int32), params=params, opt_state=opt_state)

    abstract = jax.eval_shape(init_fn, rng)
    pspecs = param_specs(abstract.params, cfg, mesh)
    sspecs = state_specs_like(abstract, pspecs)
    shardings = shardings_of(mesh, sspecs)
    if materialize:
        state = jit_init_sharded(init_fn, rng, shardings, cfg.shard_on_cpu)
    else:
        state = jax.tree.map(
            lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
            abstract, shardings)
    return state, sspecs, pspecs
