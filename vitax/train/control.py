"""Coordinated multi-host failure control plane (ROADMAP item 4).

PR 7 made single-process failures survivable, but each host still decided to
escalate, save, and exit ON ITS OWN — one host entering an emergency save
while the others keep stepping interleaves mismatched collectives and wedges
the pod, which is exactly the hang class the watchdog exists to cure. This
module folds every host-local failure signal into ONE packed control word
and agrees it across hosts at the train loop's existing sync points, so all
hosts take the SAME action at the SAME step:

  bit 0  PREEMPT    SIGTERM delivered (vitax/train/preempt.py)
  bit 1  ESCALATE   watchdog hang escalation (vitax/telemetry/watchdog.py)
  bit 2  FAULT      a host flagged a non-hang fault (e.g. the watchdog's
                    hard-deadline exit publishing its cause on the way out)
  bit 3  PEER_LOST  peer-liveness monitor declared a peer dead

Agreement is the bitwise OR of the word over processes
(distributed.or_across_processes) on the same cadence the preemption-only
flag sync used — every `sync_steps` steps in-loop plus unconditionally at
each epoch boundary — so multi-host agreement costs the same single tiny
collective it did before this module existed. Single-host, poll() is a local
flag read every step (free), preserving PR 7's exact semantics.

The loop reacts to an agreed word at the step boundary:

  preempt only      -> jointly committed preemption checkpoint, exit 0
  escalate/fault/
  peer_lost         -> jointly committed emergency checkpoint, exit 42
                       (EXIT_HANG) on ALL hosts — one uniform code the
                       supervisor (vitax/supervise.py) understands

Note the subtlety on PEER_LOST: if the agreement collective itself completed,
every process is demonstrably alive, so the joint save is safe. A REALLY dead
peer never reaches agreement — that path is covered by PeerLiveness below,
which bypasses agreement entirely and bounds the survivors' exit.

Peer liveness: collectives over a dead peer block forever — the one hang the
watchdog can dump but never recover from on a pod. PeerLiveness heartbeats
through the JAX coordination service KV store (host TCP, no device
collectives: it keeps working exactly when ICI does not). Each process bumps
`vitax/hb/<pid>` every `interval_s`; a monitor thread declares a peer lost
when its key stops advancing for `grace_s` and then escalates THIS host:
raise the watchdog's sticky escalation flag (bounded by its hard deadline)
plus an independent hard-exit timer, so the survivor exits EXIT_HANG within
a deadline even while wedged inside a collective. The supervisor restarts
from the last committed checkpoint.

Elastic resume (topology change): restore is already pinned cross-topology
(Orbax reshards on load; tests/test_checkpoint.py), and the index-sampled
loaders (ImageFolder/fake, vitax/data/loader.py ShardedSampler) partition
each epoch RANK-INTERLEAVED — the first k global batches are the same record
set for any process count — so a mid-epoch step sidecar resumes exactly even
when N hosts wrote it and M hosts read it. The streaming data plane is the
exception: its shard->host assignment is disjoint per topology, so a cursor
written under N processes is meaningless under M; elastic_resume_plan()
rounds the resume DOWN to the epoch boundary (loudly) instead of letting
check_cursor fail or, worse, silently feeding different records.

Everything here is host-side and import-light (no jax at module scope): the
compiled step program is bit-identical with the control plane on or off, and
the pack/agree/plan logic unit-tests without a device runtime
(tests/test_control.py).
"""

from __future__ import annotations

import dataclasses
import sys
import threading
import time
from typing import Callable, Dict, Optional

from vitax import faults
from vitax.telemetry.watchdog import EXIT_HANG

# Control-word bit layout (documented in README "Multi-host semantics").
# The agreement fold is bitwise OR, so every host's raised bits survive
# into the one word all hosts see.
BIT_PREEMPT = 1 << 0
BIT_ESCALATE = 1 << 1
BIT_FAULT = 1 << 2
BIT_PEER_LOST = 1 << 3
_ALL_BITS = BIT_PREEMPT | BIT_ESCALATE | BIT_FAULT | BIT_PEER_LOST

# OUT-OF-BAND bit: the peer-restore agreement (agree_peer_restore) folds it
# through the same OR collective, but it is NOT part of the step-loop control
# word — unpack_word still rejects it, so a version-skewed host that leaks
# the restore-time fold into a step-time poll fails loudly instead of being
# silently read as "no signal". Semantics: a RAISED bit VETOES the peer
# restore (OR-folds agree on the raised case, so the veto wins).
BIT_PEER_RESTORE = 1 << 4

# Bound on the coordinated-exit barrier when NEITHER the watchdog NOR peer
# liveness is running (ControlPlane.arm_exit_deadline): a peer that dies
# mid-drain must not hang survivors forever, whatever the config.
DEFAULT_EXIT_DEADLINE_S = 300.0

# Default agreement cadence (steps). Bounds the extra exposure after a local
# signal to min(sync_steps, rest of the epoch) steps of wall time — the epoch
# boundary always syncs too. Hosts must use the SAME value (the word sync is
# a collective); vitax/config.py --control_sync_steps carries it.
DEFAULT_SYNC_STEPS = 10

# Coordination-service KV namespaces (per-process keys).
HEARTBEAT_KEY_PREFIX = "vitax/hb"
FAULT_KEY_PREFIX = "vitax/fault"


def pack_word(preempt: bool = False, escalate: bool = False,
              fault: bool = False, peer_lost: bool = False) -> int:
    """Fold the four host-local failure signals into one small int."""
    return ((BIT_PREEMPT if preempt else 0)
            | (BIT_ESCALATE if escalate else 0)
            | (BIT_FAULT if fault else 0)
            | (BIT_PEER_LOST if peer_lost else 0))


@dataclasses.dataclass(frozen=True)
class Signals:
    """An unpacked control word — what the hosts agreed happened."""

    preempt: bool = False
    escalate: bool = False
    fault: bool = False
    peer_lost: bool = False

    @property
    def word(self) -> int:
        return pack_word(self.preempt, self.escalate, self.fault,
                         self.peer_lost)

    @property
    def any(self) -> bool:
        return self.preempt or self.escalate or self.fault or self.peer_lost

    @property
    def emergency(self) -> bool:
        """Agreed signals that demand the EXIT_HANG emergency path (vs the
        clean preemption drain): escalation, fault, or a peer-loss verdict."""
        return self.escalate or self.fault or self.peer_lost

    def describe(self) -> str:
        names = [n for n, on in (("preempt", self.preempt),
                                 ("escalate", self.escalate),
                                 ("fault", self.fault),
                                 ("peer_lost", self.peer_lost)) if on]
        return "+".join(names) or "none"


def unpack_word(word: int) -> Signals:
    """Inverse of pack_word. Unknown high bits are rejected: an agreement
    that returns garbage (version-skewed peer, corrupted fold) must fail
    loudly, not be quietly masked into 'no signal'."""
    word = int(word)
    if word < 0 or word & ~_ALL_BITS:
        raise ValueError(f"control word {word:#x} has bits outside the "
                         f"defined layout {_ALL_BITS:#x} — mixed vitax "
                         f"versions across hosts?")
    return Signals(preempt=bool(word & BIT_PREEMPT),
                   escalate=bool(word & BIT_ESCALATE),
                   fault=bool(word & BIT_FAULT),
                   peer_lost=bool(word & BIT_PEER_LOST))


def agree_peer_restore(local_ok: bool, process_count: Optional[int] = None,
                       collective: Optional[Callable[[int], int]] = None,
                       ) -> bool:
    """The all-hosts gate on entering the peer-restore path
    (vitax/checkpoint/peer.py negotiate_restore): every host folds
    BIT_PEER_RESTORE — RAISED means "I cannot restore from peers" — through
    the same OR collective the control word uses, so one host whose shard
    fetch failed vetoes the peer path for the whole pod and everyone drops
    to the Orbax fallback together. Mixing one peer-restored host with
    Orbax-restored peers would silently diverge the replicas; this fold is
    the BIT_PEER_RESTORE seam the tentpole names. Single-process: the local
    verdict is the agreement."""
    if process_count is None:
        import jax
        process_count = jax.process_count()
    if process_count <= 1:
        return bool(local_ok)
    if collective is None:
        from vitax import distributed
        collective = distributed.or_across_processes
    agreed = int(collective(0 if local_ok else BIT_PEER_RESTORE))
    return not (agreed & BIT_PEER_RESTORE)


def coordination_client():
    """The JAX coordination-service KV client, or None when the distributed
    runtime is not initialized (single-host runs, unit tests). Host-plane
    TCP to the coordinator — alive exactly when ICI collectives may not be."""
    try:
        from jax._src import distributed as jax_distributed
        return jax_distributed.global_state.client
    except Exception:  # noqa: BLE001 — a private-API drift degrades to "no liveness", never a crash
        return None


class ControlPlane:
    """Folds local failure flags into a word and agrees it across hosts.

    The train loop calls poll(step_in_epoch) at every step boundary (and
    with step_in_epoch=None at each epoch boundary). Single-process: the
    local word is unpacked every call — identical to PR 7's per-step local
    flag checks. Multi-process: off-cadence calls return Signals() without
    any collective; on-cadence calls run ONE OR-fold of the packed word
    (the `collective` injection point — tests agree words with a plain
    python fold, no JAX).

    `watchdog`, `on_event` (wired to Recorder kind:"control" events on rank
    0) and `hard_exit` are injectable for the same reason. The plane also
    owns the peer-liveness monitor (start_liveness) and the reaction to a
    lost peer: escalate this host with a bounded hard-exit deadline.
    """

    def __init__(self, sync_steps: int = DEFAULT_SYNC_STEPS,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None,
                 watchdog=None,
                 collective: Optional[Callable[[int], int]] = None,
                 on_event: Optional[Callable[[dict], None]] = None,
                 hard_exit: Optional[Callable[[int], None]] = None):
        assert sync_steps >= 1, sync_steps
        if process_index is None or process_count is None:
            import jax
            process_index = jax.process_index()
            process_count = jax.process_count()
        self.sync_steps = int(sync_steps)
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        self.watchdog = watchdog
        self._collective = collective
        self._on_event = on_event
        self._hard_exit = hard_exit
        self._fault = threading.Event()
        self._peer_lost = threading.Event()
        self._lost_peers: list = []
        self._announced = False
        self._liveness: Optional[PeerLiveness] = None
        self._exit_timer: Optional[threading.Timer] = None
        self._lock = threading.Lock()

    # -- local word ----------------------------------------------------------
    def local_word(self) -> int:
        """THIS host's packed signals. The two polls below are the sanctioned
        call sites the VTX107 lint rule guards: every other module must read
        the agreed word through poll(), never the raw local flags."""
        from vitax.train import preempt
        word = 0
        if preempt.requested():  # vtx: ignore[VTX107] the control plane is the sanctioned raw-flag poller
            word |= BIT_PREEMPT
        if (self.watchdog is not None
                and self.watchdog.escalation_requested()):  # vtx: ignore[VTX107] sanctioned raw-flag poller
            word |= BIT_ESCALATE
        if self._fault.is_set():
            word |= BIT_FAULT
        if self._peer_lost.is_set():
            word |= BIT_PEER_LOST
        return word

    def set_fault(self, reason: str = "") -> None:
        """Raise this host's fault bit (sticky); folded into the next
        agreement so ALL hosts exit through the coordinated path."""
        self._fault.set()
        self._emit("fault_flagged", reason=reason)

    def publish_fault(self, reason: str) -> None:
        """set_fault + best-effort publication of the cause under the
        coordination-service key vitax/fault/<pid>, so peers that only see a
        lost heartbeat can attribute it. Safe on the way out of a hard exit:
        never raises, never blocks beyond the KV call itself."""
        self._fault.set()
        client = coordination_client()
        if client is None:
            return
        try:
            client.key_value_set(
                f"{FAULT_KEY_PREFIX}/{self.process_index}", reason,
                allow_overwrite=True)
        except Exception as e:  # noqa: BLE001 — publishing the cause is best-effort by design
            print(f"vitax.control: could not publish fault cause "
                  f"({type(e).__name__}: {e})", file=sys.stderr, flush=True)

    # -- agreement -----------------------------------------------------------
    def warmup(self) -> None:
        """Run one throwaway fold of word 0 so the agreement collective's
        XLA compile + transport setup happen OUTSIDE any hang-deadline
        window. Without this the FIRST on-cadence poll pays seconds of
        compile while the watchdog's hard deadline is already ticking — an
        escalating host could be hard-exited mid-agreement. The train loop
        calls this before the training-begins barrier; every process must
        (it is a collective). No-op single-host."""
        if self.process_count <= 1:
            return
        collective = self._collective
        if collective is None:
            from vitax import distributed
            collective = distributed.or_across_processes
        collective(0)

    def poll(self, step_in_epoch: Optional[int],
             epoch: int = 0) -> Signals:
        """The step-boundary check. Returns the AGREED signals (all hosts see
        the same value at the same call), or Signals() when nothing is
        flagged / this step is off-cadence. Multi-host this is a collective
        on-cadence: every process must call it at the same points."""
        if self.process_count == 1:
            sig = unpack_word(self.local_word())
            if sig.any:
                self._announce(sig, step_in_epoch, epoch)
            return sig
        on_cadence = (step_in_epoch is None
                      or (step_in_epoch + 1) % self.sync_steps == 0)
        if not on_cadence:
            return Signals()
        # drill point for the agreement path itself (site `barrier_timeout`:
        # a hang injected here starves the collective exactly like a peer
        # that died between cadences)
        faults.fire("barrier_timeout")
        collective = self._collective
        if collective is None:
            from vitax import distributed
            collective = distributed.or_across_processes
        sig = unpack_word(collective(self.local_word()))
        if sig.any:
            self._announce(sig, step_in_epoch, epoch)
        return sig

    def _announce(self, sig: Signals, step_in_epoch, epoch: int) -> None:
        """One kind:"control" event per run for the first agreed word (the
        loop acts on it immediately and terminally, but epoch-boundary and
        single-host polls can observe the same word twice)."""
        with self._lock:
            if self._announced:
                return
            self._announced = True
        self._emit("agreed_escalation" if sig.emergency else "agreed_preempt",
                   word=sig.word, signals=sig.describe(), epoch=int(epoch),
                   step_in_epoch=(None if step_in_epoch is None
                                  else int(step_in_epoch) + 1))

    def _emit(self, event: str, **payload) -> None:
        if self._on_event is None:
            return
        try:  # JSONL sinks flush per record: events survive a hard exit
            self._on_event({"event": event, **payload})
        except Exception as e:  # noqa: BLE001 — observability must not mask the failure path
            print(f"vitax.control: event sink failed ({type(e).__name__}: "
                  f"{e})", file=sys.stderr, flush=True)

    # -- peer liveness -------------------------------------------------------
    def start_liveness(self, interval_s: float, grace_s: float,
                       client=None) -> bool:
        """Start heartbeating + monitoring peers. Returns False (with a
        loud line) when no coordination service is reachable or the run is
        single-process — liveness then simply stays off, never fatal."""
        if self.process_count <= 1:
            return False
        client = client if client is not None else coordination_client()
        if client is None:
            print("vitax.control: peer liveness requested but no "
                  "coordination service client is available; peer-death "
                  "detection disabled for this run",
                  file=sys.stderr, flush=True)
            return False
        liveness = PeerLiveness(
            process_index=self.process_index,
            process_count=self.process_count,
            interval_s=interval_s, grace_s=grace_s, client=client,
            on_loss=self._on_peer_loss)
        with self._lock:  # vs the monitor thread's read in _on_peer_loss
            self._liveness = liveness
        liveness.start()
        return True

    def _on_peer_loss(self, peer: int, silent_s: float,
                      cause: Optional[str]) -> None:
        """A peer's heartbeat stopped. Collectives over it would block
        forever, so escalate THIS host under a bounded deadline: raise the
        watchdog's sticky escalation flag (its hard deadline covers a loop
        wedged mid-collective) AND an independent exit timer (covers runs
        whose watchdog is off or not yet armed). If the loop is healthy it
        reaches the next boundary first and exits through the coordinated
        path; either way the survivor is gone within the deadline instead
        of hanging in ICI forever."""
        with self._lock:
            self._lost_peers.append(peer)
        self._peer_lost.set()
        why = f" (peer published cause: {cause})" if cause else ""
        print(f"vitax.control: peer {peer} lost — no heartbeat for "
              f"{silent_s:.1f}s{why}; escalating to checkpoint_exit "
              f"(exit {EXIT_HANG} within the liveness deadline)",
              file=sys.stderr, flush=True)
        self._emit("peer_loss", peer=int(peer), silent_s=round(silent_s, 3),
                   cause=cause, exit_code=EXIT_HANG)
        with self._lock:  # stop() may be nulling _liveness concurrently
            liveness = self._liveness
        deadline_s = liveness.grace_s if liveness is not None else 30.0
        if self.watchdog is not None:
            self.watchdog.request_escalation(
                f"peer {peer} lost (heartbeat silent {silent_s:.1f}s)")
        with self._lock:
            if self._exit_timer is None:
                self._exit_timer = threading.Timer(
                    deadline_s, self._deadline_exit, args=(peer,))
                self._exit_timer.daemon = True
                self._exit_timer.start()

    def _deadline_exit(self, peer: int) -> None:
        print(f"vitax.control: loop did not reach a step boundary within "
              f"the liveness deadline after losing peer {peer} — "
              f"hard-exiting {EXIT_HANG} for the supervisor",
              file=sys.stderr, flush=True)
        hard_exit = self._hard_exit
        if hard_exit is None:
            import os
            hard_exit = os._exit
        hard_exit(EXIT_HANG)

    def arm_exit_deadline(self, deadline_s: Optional[float] = None) -> None:
        """Bound the coordinated-exit barrier. A peer that dies after
        agreement but before the barrier wedges survivors in the drain
        forever; this arms a hard deadline on THIS host's exit. Prefers the
        watchdog's own deadline machinery when one is running (same knob the
        emergency path re-arms); otherwise — watchdog off, liveness off, the
        PR 10 gap — arms the plane's own timer with DEFAULT_EXIT_DEADLINE_S,
        so the barrier is bounded under EVERY config. No-op single-host
        (nothing to wait on) and idempotent (first armed timer wins)."""
        if self.process_count <= 1:
            return
        if (self.watchdog is not None
                and getattr(self.watchdog, "running", False)):
            self.watchdog.arm_exit_deadline()
            return
        deadline = float(deadline_s) if deadline_s else DEFAULT_EXIT_DEADLINE_S
        with self._lock:
            if self._exit_timer is not None:
                return
            self._exit_timer = threading.Timer(
                deadline, self._drain_deadline_exit, args=(deadline,))
            self._exit_timer.daemon = True
            self._exit_timer.start()

    def _drain_deadline_exit(self, deadline: float) -> None:
        print(f"vitax.control: coordinated-exit barrier did not complete "
              f"within {deadline:.0f}s — a peer likely died mid-drain; "
              f"hard-exiting {EXIT_HANG} for the supervisor",
              file=sys.stderr, flush=True)
        hard_exit = self._hard_exit
        if hard_exit is None:
            import os
            hard_exit = os._exit
        hard_exit(EXIT_HANG)

    def peer_loss_suspected(self, wait: bool = True) -> Optional[int]:
        """Classify a runtime error that escaped a collective region: is a
        dead peer the likely cause? A peer death shows up two ways — ICI
        collectives BLOCK on it (the timer path above), host-plane transports
        like Gloo surface it as a runtime ERROR instead. The loop calls this
        from its error path: returns the lost peer's index once the liveness
        monitor reaches its verdict (waiting up to grace + one beat interval
        when `wait`), or None — no liveness running, or every peer still
        beating, i.e. the error is a genuine bug the caller must re-raise."""
        with self._lock:
            liveness = self._liveness
        if liveness is None:
            return None
        # worst case the peer died a whole grace window before the error
        # surfaced here, so the verdict lands within grace + one monitor
        # poll of NOW; the extra second absorbs scheduler jitter
        deadline = (time.monotonic() + liveness.grace_s
                    + liveness.interval_s + 1.0)
        while wait and time.monotonic() < deadline:
            if self._peer_lost.is_set():
                break
            time.sleep(min(liveness.interval_s, 0.2))
        if not self._peer_lost.is_set():
            return None
        with self._lock:
            return self._lost_peers[0] if self._lost_peers else None

    def stop(self) -> None:
        with self._lock:
            liveness, self._liveness = self._liveness, None
        if liveness is not None:
            liveness.stop()  # joins its threads — must not hold our lock
        with self._lock:
            if self._exit_timer is not None:
                self._exit_timer.cancel()
                self._exit_timer = None


class PeerLiveness:
    """KV heartbeats: every process bumps its key; a monitor thread flags
    peers whose key stops advancing for `grace_s`.

    All calls are bounded (`blocking_key_value_get` carries a timeout), so
    the monitor keeps turning even when the coordinator is slow; KV errors
    count as "no advance" rather than crashing — a survivor mid-outage must
    converge to the peer-loss verdict, not die on a TCP hiccup. `on_loss`
    fires at most once per peer, from the monitor thread (it must not touch
    device state — same rule as the watchdog thread). `client` and `clock`
    are injectable: tests drive loss verdicts with a fake KV store and no
    real sleeps beyond the poll interval."""

    def __init__(self, process_index: int, process_count: int,
                 interval_s: float, grace_s: float, client,
                 on_loss: Callable[[int, float, Optional[str]], None],
                 clock: Callable[[], float] = time.monotonic):
        assert interval_s > 0, interval_s
        assert grace_s > 0, grace_s
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        self.interval_s = float(interval_s)
        self.grace_s = float(grace_s)
        self.client = client
        self.on_loss = on_loss
        self.clock = clock
        self.peers = [p for p in range(self.process_count)
                      if p != self.process_index]
        self.lost: set = set()
        self._stop = threading.Event()
        self._threads: list = []

    def start(self) -> None:
        for name, target in (("vitax-hb-beat", self._beat),
                             ("vitax-hb-monitor", self._monitor)):
            t = threading.Thread(target=target, daemon=True, name=name)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=self.interval_s + 1.0)

    def _key(self, peer: int) -> str:
        return f"{HEARTBEAT_KEY_PREFIX}/{peer}"

    def _beat(self) -> None:
        seq = 0
        while True:
            seq += 1
            try:
                self.client.key_value_set(self._key(self.process_index),
                                          str(seq), allow_overwrite=True)
            except Exception as e:  # noqa: BLE001 — a beat lost to a KV hiccup must not kill the beater
                print(f"vitax.control: heartbeat write failed "
                      f"({type(e).__name__}: {e}); retrying",
                      file=sys.stderr, flush=True)
            if self._stop.wait(self.interval_s):
                return

    def _monitor(self) -> None:
        # a peer that NEVER writes (died during compile, before its first
        # beat) still gets flagged: the grace clock starts at monitor start
        last_seen: Dict[int, tuple] = {p: (None, self.clock())
                                       for p in self.peers}
        # near-non-blocking per-peer reads, INDEPENDENT of interval_s: the
        # peers are polled serially, so a cycle over P peers costs up to
        # P x timeout when they are all slow/missing — at 2s each a large
        # pod's loss verdict would land whole multiples of grace_s late and
        # overstate the silence it reports. 200ms keeps a full cycle short
        # (a healthy-but-slow read counts as "no advance" for ONE cycle;
        # the grace window, not a single read, decides loss).
        timeout_ms = max(int(min(self.interval_s, 0.2) * 1000), 50)
        while not self._stop.wait(self.interval_s):
            now = self.clock()
            for peer in self.peers:
                if peer in self.lost:
                    continue
                try:
                    value = self.client.blocking_key_value_get(
                        self._key(peer), timeout_ms)
                except Exception:  # noqa: BLE001 — timeout/KV error == no advance; the grace window decides
                    value = None
                prev_value, since = last_seen[peer]
                if value is not None and value != prev_value:
                    last_seen[peer] = (value, now)
                elif now - since >= self.grace_s:
                    self.lost.add(peer)
                    self.on_loss(peer, now - since, self._cause(peer))

    def _cause(self, peer: int) -> Optional[str]:
        """The cause the dying peer published (publish_fault), if any."""
        try:
            return self.client.blocking_key_value_get(
                f"{FAULT_KEY_PREFIX}/{peer}", 200)
        except Exception:  # noqa: BLE001 — no published cause is the common case, not an error
            return None


class ArbiterReporter:
    """Rank 0's heartbeat to the chip arbiter (--arbiter_url): POST
    /telemetry with the latest committed step/epoch so borrow policy can
    gate on "training is actually progressing" instead of inferring it
    from process liveness.

    One daemon thread; the train loop calls `update()` from its log path
    (cheap: a lock and three assignments) and the thread posts the latest
    snapshot every `interval_s`. A snapshot that has not changed is still
    re-posted every `refresh_s`: a CPU-starved trainer whose steps take
    longer than the arbiter's staleness window is slow, not stalled, and
    must not read as a wedged job (the arbiter's dirty-drain rollback is
    the backstop for the truly wedged case). Transport failures are
    counted and swallowed — an unreachable arbiter must never slow a
    step. `http_json` is injectable so tests drive the posting loop with
    a fake transport and no sockets (same seam style as PeerLiveness)."""

    def __init__(self, arbiter_url: str, process_count: int = 1,
                 interval_s: float = 2.0, refresh_s: float = 10.0,
                 http_json: Optional[Callable] = None,
                 timeout_s: float = 2.0):
        assert arbiter_url, "ArbiterReporter needs a non-empty arbiter_url"
        assert interval_s > 0, interval_s
        assert refresh_s > 0, refresh_s
        self.url = arbiter_url.rstrip("/") + "/telemetry"
        self.process_count = int(process_count)
        self.interval_s = float(interval_s)
        self.refresh_s = float(refresh_s)
        self.timeout_s = float(timeout_s)
        self._http_json = http_json or self._default_http_json
        self._lock = threading.Lock()
        # guarded by _lock:
        self._latest: Optional[dict] = None
        self._posted: Optional[dict] = None
        self._last_post_t = 0.0
        self.posts_total = 0
        self.post_failures = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @staticmethod
    def _default_http_json(url: str, payload: dict, timeout: float) -> dict:
        import json
        import urllib.request
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode("utf-8"),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.load(resp)

    def update(self, step: int, epoch: int) -> None:
        """Called from the train loop's log path; never blocks on I/O."""
        with self._lock:
            self._latest = {"step": int(step), "epoch": int(epoch),
                            "process_count": self.process_count}

    def post_once(self, force: bool = False) -> bool:
        """One delivery attempt of the latest unsent snapshot (the loop
        body; tests call it directly). True iff something was posted.
        `force` re-posts an unchanged snapshot — the heartbeat refresh."""
        with self._lock:
            latest = self._latest
            if latest is None or (not force and latest == self._posted):
                return False
        try:
            self._http_json(self.url, latest, self.timeout_s)
        except Exception:  # noqa: BLE001 — an unreachable arbiter must never hurt training
            with self._lock:
                self.post_failures += 1
            return False
        with self._lock:
            self._posted = latest
            self._last_post_t = time.time()
            self.posts_total += 1
        return True

    def start(self) -> None:
        assert self._thread is None, "reporter already running"
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="vitax-arbiter-report")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            with self._lock:
                last = self._last_post_t
            self.post_once(force=time.time() - last >= self.refresh_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.timeout_s + self.interval_s + 1.0)
            self._thread = None
        self.post_once()  # final flush: the last committed step matters most


# -- elastic resume (topology change) ----------------------------------------

@dataclasses.dataclass(frozen=True)
class ResumePlan:
    """How to re-enter a checkpointed epoch under the CURRENT topology."""

    resume_step: int           # steps already done; 0 = epoch-boundary entry
    topology_changed: bool     # sidecar written under a different layout
    epoch_rounded: bool        # stream cursor invalidated -> boundary resume
    from_processes: int        # 0 when the sidecar predates this field
    skipped_steps: int         # mid-epoch progress dropped by the rounding


def elastic_resume_plan(meta: Optional[dict],
                        process_count: int) -> ResumePlan:
    """Decide the resume step for a (possibly) topology-changed restart.

    `meta` is the mid-epoch resume sidecar payload (orbax_io.load_resume_meta)
    or None for an epoch-boundary checkpoint. The index-sampled loaders
    partition rank-interleaved, so their step-granular resume survives any
    N->M change; a stream cursor's shard->host assignment does not — when
    the sidecar carries one AND the topology drifted, round down to the
    epoch boundary (re-running the partial epoch beats feeding a silently
    different record stream, and beats check_cursor's hard failure). Pure
    function: unit-tested without JAX."""
    step = int(meta.get("step_in_epoch") or 0) if meta else 0
    recorded = int(meta.get("process_count") or 0) if meta else 0
    changed = bool(recorded) and recorded != int(process_count)
    has_cursor = bool(meta) and isinstance(meta.get("stream_cursor"), dict)
    rounded = changed and has_cursor and step > 0
    return ResumePlan(resume_step=0 if rounded else step,
                      topology_changed=changed,
                      epoch_rounded=rounded,
                      from_processes=recorded,
                      skipped_steps=step if rounded else 0)
