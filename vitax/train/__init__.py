from vitax.train.schedule import warmup_cosine_schedule  # noqa: F401
