"""Deterministic fault injection: chaos drills for the failure-reaction layer.

Elastic-training machinery (watchdog escalation, the supervisor's restart
loop, checkpoint-write retries, torn-checkpoint skip) is only trustworthy if
it is *exercised* — preemption and hangs on real pods do not arrive on a test
schedule. This module injects them on one: a JSON **fault plan**
(``--fault_plan`` or the ``VITAX_FAULT_PLAN`` env var) names a hook site, a
1-based call index at that site, and an action:

    {"site": "step", "at": 6, "action": "crash", "exit_code": 13}

or a list of such specs (optionally wrapped as ``{"faults": [...]}``).

Sites (each a single host-side hook point; see the wiring modules):
  step        once per dispatched optimizer step, index = global step count
              (vitax/train/loop.py)
  ckpt_write  once per checkpoint write *attempt*, so ``times`` > 1 exercises
              the save retry path (vitax/checkpoint/orbax_io.py)
  loader      once per produced host batch, on the producer thread
              (vitax/data/loader.py)
  stream_read once per shard-file open attempt in the streaming reader
              (vitax/data/stream/format.py) — `oserror` exercises the
              open-retry-then-LoaderWorkerError path, `stall` a slow store
  barrier_timeout
              once per control-word agreement collective (vitax/train/
              control.py ControlPlane.poll) — a `hang` here starves the
              agreement exactly like a peer that died between cadences
  peer_restore
              once per peer-shard load during a peer restore, index = the
              shard's source host (vitax/checkpoint/peer.py PeerStore.load)
              — `oserror` drills the missing/corrupted-buddy fallback to
              the last committed Orbax epoch

Serve-path sites (the chaos layer for vitax/serve/ — same deterministic
per-site index semantics; a plan forwarded to a replica via --fault_plan
scripts replica crash/hang/slow-response/flaky-health scenarios):
  engine_predict
              once per InferenceEngine.predict call (vitax/serve/engine.py)
              — `hang` is a stuck accelerator, `crash` an OOM-killed
              replica mid-request
  batcher_flush
              once per DynamicBatcher flush (vitax/serve/batcher.py), on
              the batcher worker thread — `hang` stalls every request in
              the batch (the predict-hang drill), `oserror` fails the
              batch (delivered to each request future)
  replica_health
              once per ReplicaManager healthz probe, in the ROUTER process
              (vitax/serve/fleet/replica.py _poll_replica) — `oserror`
              makes one probe fail, so windows of them drill the
              flaky-health ejection/re-admission path. Probes sweep the
              fleet in registration order, so with N replicas index
              k*N + i targets replica i (1-based)
  router_dispatch
              once per router dispatch attempt (vitax/serve/fleet/
              router.py) — `oserror` drills the retry/breaker/budget path
              without needing a sick replica

Actions:
  crash    os._exit(exit_code) — a hard kill: no atexit, no drains, exactly
           what a segfault/OOM-kill leaves behind (default exit code 13)
  hang     time.sleep(seconds) on the hooked thread (default 3600) — drives
           the watchdog past --hang_timeout_s
  oserror  raise OSError at the hook — a transient write/read failure
  stall    alias of hang for the loader site (a starved consumer)
  sigterm  os.kill(os.getpid(), SIGTERM) — a self-delivered preemption notice
  peer_loss
           os.kill(os.getpid(), SIGKILL) — an ABRUPT death (no handlers, no
           flushes beyond the injection log): in the multiprocess harness
           the surviving hosts see exactly what a real peer death leaves
           behind — a heartbeat that stops (vitax/train/control.py
           PeerLiveness drills)

Multi-process drills: a spec may carry ``"process": K`` to fire on exactly
one designated process (``peer_loss`` killing host K while host J survives);
the default -1 fires on every process, preserving single-host plans
unchanged. The process index comes from JAX_PROCESS_ID when set (the
multiprocess harness exports it) so producer threads never have to touch
the JAX runtime to decide.

Every spec is deterministic: it fires when the site's call index (the
explicit ``index=`` the hook passes, else an internal per-site counter)
lands in [at, at + times). With no plan installed the hooks are a single
module-global ``is None`` check — zero-cost, and the compiled step program
is bit-identical with a plan armed or not (all hooks are host-side;
tests/test_faults.py pins that like telemetry did in PR 4).
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

SITES = ("step", "ckpt_write", "loader", "stream_read", "barrier_timeout",
         "peer_restore",
         # serve-path chaos sites (the serving sibling of the train hooks)
         "engine_predict", "batcher_flush", "replica_health",
         "router_dispatch")
ACTIONS = ("crash", "hang", "oserror", "stall", "sigterm", "peer_loss")

DEFAULT_CRASH_EXIT_CODE = 13
DEFAULT_HANG_SECONDS = 3600.0

ENV_VAR = "VITAX_FAULT_PLAN"


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One scheduled fault: fire `action` at call indices [at, at + times)."""

    site: str
    action: str
    at: int = 1
    times: int = 1
    exit_code: int = DEFAULT_CRASH_EXIT_CODE
    seconds: float = DEFAULT_HANG_SECONDS
    process: int = -1  # fire only on this process index; -1 = every process

    def __post_init__(self):
        if self.site not in SITES:
            raise ValueError(f"fault_plan: unknown site {self.site!r} "
                             f"(expected one of {SITES})")
        if self.action not in ACTIONS:
            raise ValueError(f"fault_plan: unknown action {self.action!r} "
                             f"(expected one of {ACTIONS})")
        if self.at < 1:
            raise ValueError(f"fault_plan: `at` is a 1-based call index, "
                             f"got {self.at}")
        if self.times < 1:
            raise ValueError(f"fault_plan: `times` must be >= 1, got {self.times}")
        if self.seconds < 0:
            raise ValueError(f"fault_plan: `seconds` must be >= 0, got {self.seconds}")
        if self.process < -1:
            raise ValueError(f"fault_plan: `process` must be a process "
                             f"index >= 0, or -1 for all processes, got "
                             f"{self.process}")

    @staticmethod
    def from_dict(d: dict) -> "FaultSpec":
        known = {f.name for f in dataclasses.fields(FaultSpec)}
        extra = set(d) - known
        if extra:
            raise ValueError(f"fault_plan: unknown keys {sorted(extra)} "
                             f"(expected a subset of {sorted(known)})")
        if "site" not in d or "action" not in d:
            raise ValueError("fault_plan: every spec needs `site` and `action`")
        return FaultSpec(**d)

    def describe(self) -> str:
        arg = {"crash": f"exit_code={self.exit_code}",
               "hang": f"seconds={self.seconds:g}",
               "stall": f"seconds={self.seconds:g}"}.get(self.action, "")
        window = (f"at={self.at}" if self.times == 1
                  else f"at={self.at}..{self.at + self.times - 1}")
        who = f"@p{self.process}" if self.process >= 0 else ""
        return (f"{self.site}:{self.action}{who}"
                f"({window}{', ' + arg if arg else ''})")


class FaultPlan:
    """A parsed plan plus per-site call counters (thread-safe: the loader
    site fires on the producer thread while `step` fires on the consumer)."""

    def __init__(self, specs: List[FaultSpec]):
        self.specs = list(specs)
        self._counters: Dict[str, int] = {}
        self._lock = threading.Lock()

    def describe(self) -> str:
        return ", ".join(s.describe() for s in self.specs) or "(empty)"

    def fire(self, site: str, index: Optional[int] = None) -> None:
        """Run any fault scheduled for this call of `site`. The internal
        per-site counter advances on EVERY call so plans stay deterministic
        whether or not the hook passes an explicit index."""
        with self._lock:
            self._counters[site] = self._counters.get(site, 0) + 1
            idx = self._counters[site] if index is None else index
        for spec in self.specs:
            if spec.site == site and spec.at <= idx < spec.at + spec.times:
                if spec.process >= 0 and spec.process != _process_index():
                    continue
                _act(spec, idx)


def _process_index() -> int:
    """This host's process index for `process`-designated specs. The
    explicit-bring-up env var (the multiprocess harness exports it) wins so
    hook sites on producer threads never initialize the JAX runtime as a
    side effect; single-host runs with neither are process 0."""
    env = os.environ.get("JAX_PROCESS_ID", "")
    if env.isdigit():
        return int(env)
    try:
        import jax
        return jax.process_index()
    except Exception:  # noqa: BLE001 — no runtime == single process, not an error
        return 0


def _act(spec: FaultSpec, index: int) -> None:
    payload = {"site": spec.site, "action": spec.action, "index": index}
    reporter = _REPORTER
    if reporter is not None:
        try:
            reporter(payload)  # JSONL sinks flush per record: the event
            # survives even the crash action's os._exit below
        except Exception as e:  # noqa: BLE001 — reporting must not mask the drill
            print(f"vitax.faults: reporter failed ({type(e).__name__}: {e})",
                  file=sys.stderr, flush=True)
    print(f"vitax.faults: injecting {spec.describe()} (call {index})",
          file=sys.stderr, flush=True)
    if spec.action == "crash":
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(spec.exit_code)
    elif spec.action in ("hang", "stall"):
        time.sleep(spec.seconds)
    elif spec.action == "oserror":
        raise OSError(f"injected fault: {spec.describe()} (call {index})")
    elif spec.action == "sigterm":
        os.kill(os.getpid(), signal.SIGTERM)
    elif spec.action == "peer_loss":
        # SIGKILL self: no handlers, no atexit, no final collectives — the
        # surviving processes observe only a heartbeat that stops, which is
        # the exact signature PeerLiveness (vitax/train/control.py) detects
        sys.stdout.flush()
        sys.stderr.flush()
        os.kill(os.getpid(), signal.SIGKILL)


# --- module-level registry: the hooks the subsystems call -------------------

_PLAN: Optional[FaultPlan] = None
_REPORTER: Optional[Callable[[dict], None]] = None


def parse_plan(plan_json: str) -> FaultPlan:
    """Parse + validate a plan string (raises ValueError on any problem —
    config.validate() calls this so a bad plan fails at startup, not at
    step N)."""
    try:
        data = json.loads(plan_json)
    except json.JSONDecodeError as e:
        raise ValueError(f"fault_plan: not valid JSON ({e})") from e
    if isinstance(data, dict) and "faults" in data:
        data = data["faults"]
    if isinstance(data, dict):
        data = [data]
    if not isinstance(data, list):
        raise ValueError("fault_plan: expected a spec object, a list of "
                         "them, or {\"faults\": [...]}")
    specs = [FaultSpec.from_dict(d) for d in data]
    if not specs:
        raise ValueError("fault_plan: empty plan — drop the flag instead")
    return FaultPlan(specs)


def install(plan_json: str) -> FaultPlan:
    """Arm a plan (replacing any previous one); returns it."""
    global _PLAN
    _PLAN = parse_plan(plan_json)
    return _PLAN


def install_from_config(cfg) -> Optional[FaultPlan]:
    """Arm the plan named by --fault_plan, else VITAX_FAULT_PLAN, else
    nothing. Called once per train() so every (supervised) restart re-arms
    the same deterministic plan."""
    plan_json = getattr(cfg, "fault_plan", "") or os.environ.get(ENV_VAR, "")
    if not plan_json:
        uninstall()
        return None
    return install(plan_json)


def uninstall() -> None:
    """Disarm (idempotent); hooks return to the zero-cost no-op path."""
    global _PLAN, _REPORTER
    _PLAN = None
    _REPORTER = None


def active() -> bool:
    return _PLAN is not None


def set_reporter(reporter: Optional[Callable[[dict], None]]) -> None:
    """Wire fired faults to telemetry (the loop passes
    ``lambda p: recorder.event("fault", **p)``); None clears."""
    global _REPORTER
    _REPORTER = reporter


def fire(site: str, index: Optional[int] = None) -> None:
    """The hook the subsystems call. With no plan armed this is one global
    read — cheap enough for once-per-step call sites."""
    if _PLAN is None:
        return
    _PLAN.fire(site, index)
