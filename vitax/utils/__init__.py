from vitax.utils.metrics import SmoothedValue  # noqa: F401
from vitax.utils.logging import (  # noqa: F401
    master_print, memory_stats_dict, memory_summary)
