from vitax.utils.metrics import SmoothedValue  # noqa: F401
from vitax.utils.logging import master_print, memory_summary  # noqa: F401
