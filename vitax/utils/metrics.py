"""Windowed smoothed meters (reference SmoothedValue parity, utils.py:60-102)."""

from __future__ import annotations

from collections import deque


class SmoothedValue:
    """Track a series of values; expose median / windowed batch-weighted avg /
    global avg / latest. Capability parity with reference utils.py:60-102
    (itself adapted from facebookresearch/mmf), without the numpy dependency."""

    def __init__(self, window_size: int = 20):
        self.window_size = window_size
        self.reset()

    def reset(self) -> None:
        self.deque = deque(maxlen=self.window_size)            # value * batch_size
        self.averaged_value_deque = deque(maxlen=self.window_size)  # raw values
        self.batch_sizes = deque(maxlen=self.window_size)
        self.total_samples = 0
        self.total = 0.0
        self.count = 0

    def update(self, value: float, batch_size: int = 1) -> None:
        value = float(value)
        self.deque.append(value * batch_size)
        self.averaged_value_deque.append(value)
        self.batch_sizes.append(batch_size)
        self.count += 1
        self.total_samples += batch_size
        self.total += value * batch_size

    @property
    def median(self) -> float:
        vals = sorted(self.averaged_value_deque)
        n = len(vals)
        if n == 0:
            return float("nan")
        mid = n // 2
        return vals[mid] if n % 2 else 0.5 * (vals[mid - 1] + vals[mid])

    @property
    def avg(self) -> float:
        denom = sum(self.batch_sizes)
        return sum(self.deque) / denom if denom else float("nan")

    @property
    def global_avg(self) -> float:
        return self.total / self.total_samples if self.total_samples else float("nan")

    def get_latest(self) -> float:
        return self.averaged_value_deque[-1]
