"""Windowed smoothed meters (capability parity with reference SmoothedValue,
utils.py:60-102, itself adapted from facebookresearch/mmf — same public API,
original internals: one ring buffer of (value, weight) samples plus running
totals, no numpy)."""

from __future__ import annotations

from collections import deque


class SmoothedValue:
    """Track a weighted series; expose windowed median (unweighted), windowed
    weighted average, global weighted average, and the latest raw value."""

    def __init__(self, window_size: int = 20):
        self.window_size = window_size
        self.reset()

    def reset(self) -> None:
        self._window = deque(maxlen=self.window_size)  # (value, weight) pairs
        self._sum = 0.0     # lifetime sum of value * weight
        self._weight = 0    # lifetime sum of weights
        self._n = 0         # lifetime number of updates

    def update(self, value: float, batch_size: int = 1) -> None:
        value = float(value)
        self._window.append((value, batch_size))
        self._sum += value * batch_size
        self._weight += batch_size
        self._n += 1

    @property
    def count(self) -> int:
        return self._n

    @property
    def median(self) -> float:
        vals = sorted(v for v, _ in self._window)
        n = len(vals)
        if n == 0:
            return float("nan")
        mid = n // 2
        return vals[mid] if n % 2 else 0.5 * (vals[mid - 1] + vals[mid])

    @property
    def avg(self) -> float:
        denom = sum(w for _, w in self._window)
        if not denom:
            return float("nan")
        return sum(v * w for v, w in self._window) / denom

    @property
    def global_avg(self) -> float:
        return self._sum / self._weight if self._weight else float("nan")

    def get_latest(self) -> float:
        # empty window -> nan, like median/avg/global_avg (an IndexError here
        # would crash the first log line of a run that has not updated yet)
        return self._window[-1][0] if self._window else float("nan")
