"""Rank-0 logging + device memory telemetry.

Replaces xm.master_print (reference run_vit_training.py, 15 call sites) and
xm.get_memory_info (reference run_vit_training.py:212).
"""

from __future__ import annotations

import sys

import jax


def is_master() -> bool:
    return jax.process_index() == 0


def master_print(*args, **kwargs) -> None:
    """Print on global process 0 only (xm.master_print parity)."""
    if is_master():
        kwargs.setdefault("flush", True)
        print(*args, **kwargs)


def memory_stats_dict(device=None) -> dict:
    """Raw HBM stats as a dict for machine consumers (the telemetry sinks):
    {"bytes_in_use", "peak_bytes_in_use", "bytes_limit"} — keys absent when
    the backend does not report them, {} on CPU where PJRT has no stats.

    Uses PJRT memory_stats when the backend provides them (TPU does); degrades
    gracefully where stats are unavailable.
    """
    device = device or jax.local_devices()[0]  # vtx: ignore[VTX104] host-local memory stats
    try:
        stats = device.memory_stats()
    except Exception:
        stats = None
    if not stats:
        return {}
    out = {}
    if stats.get("bytes_in_use") is not None:
        out["bytes_in_use"] = int(stats["bytes_in_use"])
    peak = stats.get("peak_bytes_in_use")
    if peak:
        out["peak_bytes_in_use"] = int(peak)
    limit = stats.get("bytes_limit") or stats.get("bytes_reservable_limit")
    if limit:
        out["bytes_limit"] = int(limit)
    return out


def memory_summary(device=None) -> str:
    """Human-readable HBM usage for the step log (xm.get_memory_info parity),
    rendered from the same memory_stats_dict the telemetry records use."""
    stats = memory_stats_dict(device)
    if not stats:
        return "mem: n/a"
    gib = 1024 ** 3
    parts = [f"used={stats.get('bytes_in_use', 0) / gib:.2f}GiB"]
    if "peak_bytes_in_use" in stats:
        parts.append(f"peak={stats['peak_bytes_in_use'] / gib:.2f}GiB")
    if "bytes_limit" in stats:
        parts.append(f"limit={stats['bytes_limit'] / gib:.2f}GiB")
    return "mem: " + " ".join(parts)
