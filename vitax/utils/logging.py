"""Rank-0 logging + device memory telemetry.

Replaces xm.master_print (reference run_vit_training.py, 15 call sites) and
xm.get_memory_info (reference run_vit_training.py:212).
"""

from __future__ import annotations

import sys

import jax


def is_master() -> bool:
    return jax.process_index() == 0


def master_print(*args, **kwargs) -> None:
    """Print on global process 0 only (xm.master_print parity)."""
    if is_master():
        kwargs.setdefault("flush", True)
        print(*args, **kwargs)


def memory_summary(device=None) -> str:
    """Human-readable HBM usage for the step log (xm.get_memory_info parity).

    Uses PJRT memory_stats when the backend provides them (TPU does); degrades
    gracefully on CPU where stats are unavailable.
    """
    device = device or jax.local_devices()[0]
    try:
        stats = device.memory_stats()
    except Exception:
        stats = None
    if not stats:
        return "mem: n/a"
    in_use = stats.get("bytes_in_use", 0)
    limit = stats.get("bytes_limit") or stats.get("bytes_reservable_limit")
    peak = stats.get("peak_bytes_in_use")
    gib = 1024 ** 3
    parts = [f"used={in_use / gib:.2f}GiB"]
    if peak:
        parts.append(f"peak={peak / gib:.2f}GiB")
    if limit:
        parts.append(f"limit={limit / gib:.2f}GiB")
    return "mem: " + " ".join(parts)
