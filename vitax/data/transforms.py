"""Image transforms: numerical parity with the reference's torchvision stacks
(reference run_vit_training.py:39-55), implemented on PIL + numpy.

Train: RandomResizedCrop(size, scale=(0.08,1.0), ratio=(3/4,4/3), bicubic)
       + RandomHorizontalFlip(0.5) + ToTensor + Normalize(ImageNet mean/std)
Val:   Resize(size*256//224, bicubic) + CenterCrop(size) + ToTensor + Normalize

Output is HWC float32 (TPU-native channels-last), not CHW.

Augmentation randomness is derived from (seed, epoch, index) SeedSequences —
thread-safe (the loader's worker pool calls into this concurrently) and
reproducible, varying per epoch like torchvision's global-RNG behavior.
"""

from __future__ import annotations

import math
from typing import Tuple

import numpy as np
from PIL import Image

IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)

BICUBIC = Image.Resampling.BICUBIC


def _to_normalized_array(img: Image.Image) -> np.ndarray:
    arr = np.asarray(img, np.float32) / 255.0  # ToTensor parity (scale to [0,1])
    return (arr - IMAGENET_MEAN) / IMAGENET_STD


def get_crop_params(width: int, height: int, rng: np.random.Generator,
                    scale: Tuple[float, float] = (0.08, 1.0),
                    ratio: Tuple[float, float] = (3 / 4, 4 / 3)
                    ) -> Tuple[int, int, int, int]:
    """torchvision RandomResizedCrop.get_params algorithm: 10 attempts at a
    random area/aspect crop, then center-crop fallback with clamped ratio.
    Returns (left, top, w, h)."""
    area = width * height
    log_ratio = (math.log(ratio[0]), math.log(ratio[1]))

    for _ in range(10):
        target_area = area * rng.uniform(scale[0], scale[1])
        aspect = math.exp(rng.uniform(log_ratio[0], log_ratio[1]))
        w = int(round(math.sqrt(target_area * aspect)))
        h = int(round(math.sqrt(target_area / aspect)))
        if 0 < w <= width and 0 < h <= height:
            top = int(rng.integers(0, height - h + 1))
            left = int(rng.integers(0, width - w + 1))
            return left, top, w, h

    # fallback: center crop at the closest valid ratio
    in_ratio = width / height
    if in_ratio < ratio[0]:
        w, h = width, int(round(width / ratio[0]))
    elif in_ratio > ratio[1]:
        h, w = height, int(round(height * ratio[1]))
    else:
        w, h = width, height
    return (width - w) // 2, (height - h) // 2, w, h


def random_resized_crop(img: Image.Image, size: int, rng: np.random.Generator,
                        scale: Tuple[float, float] = (0.08, 1.0),
                        ratio: Tuple[float, float] = (3 / 4, 4 / 3)) -> Image.Image:
    left, top, w, h = get_crop_params(img.size[0], img.size[1], rng, scale, ratio)
    return img.resize((size, size), BICUBIC, box=(left, top, left + w, top + h))


def center_crop(img: Image.Image, size: int) -> Image.Image:
    """torchvision CenterCrop parity (pads with zeros if the image is smaller)."""
    width, height = img.size
    if width < size or height < size:
        padded = Image.new("RGB", (max(width, size), max(height, size)))
        padded.paste(img, ((padded.width - width) // 2, (padded.height - height) // 2))
        img, (width, height) = padded, padded.size
    left, top = (width - size) // 2, (height - size) // 2
    return img.crop((left, top, left + size, top + size))


def resize_shorter(img: Image.Image, size: int) -> Image.Image:
    """torchvision Resize(int) parity: scale the SHORTER side to `size`."""
    width, height = img.size
    if width <= height:
        new_w, new_h = size, max(1, int(round(size * height / width)))
    else:
        new_h, new_w = size, max(1, int(round(size * width / height)))
    return img.resize((new_w, new_h), BICUBIC)


class TrainTransform:
    """Reference train stack (run_vit_training.py:39-46)."""

    def __init__(self, image_size: int, seed: int = 0, normalize: bool = True):
        self.image_size = image_size
        self.seed = seed
        self.epoch = 0
        # normalize=False emits raw uint8 (normalization happens on-device in
        # the train step — 4x smaller host->device transfer)
        self.normalize = normalize

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __call__(self, img: Image.Image, index: int = 0) -> np.ndarray:
        rng = np.random.default_rng(np.random.SeedSequence(
            [self.seed, self.epoch, index]))
        img = random_resized_crop(img, self.image_size, rng)
        if rng.random() < 0.5:
            img = img.transpose(Image.Transpose.FLIP_LEFT_RIGHT)
        if not self.normalize:
            return np.asarray(img, np.uint8)
        return _to_normalized_array(img)

    def native_params(self, width: int, height: int, index: int):
        """(mode, left, top, cw, ch, flip) for the native C++ pipeline — the
        SAME rng stream/order as __call__, so PIL and native paths apply
        identical augmentations and differ only in resample rounding."""
        rng = np.random.default_rng(np.random.SeedSequence(
            [self.seed, self.epoch, index]))
        left, top, w, h = get_crop_params(width, height, rng)
        flip = int(rng.random() < 0.5)
        return (0, left, top, w, h, flip)


class ValTransform:
    """Reference val stack (run_vit_training.py:48-55): resize shorter side to
    size*256//224, center crop."""

    def __init__(self, image_size: int, normalize: bool = True):
        self.image_size = image_size
        self.resize_to = (image_size * 256) // 224
        self.normalize = normalize

    def set_epoch(self, epoch: int) -> None:
        pass

    def __call__(self, img: Image.Image, index: int = 0) -> np.ndarray:
        img = resize_shorter(img, self.resize_to)
        img = center_crop(img, self.image_size)
        if not self.normalize:
            return np.asarray(img, np.uint8)
        return _to_normalized_array(img)

    def native_params(self, width: int, height: int, index: int):
        return (1, 0, 0, 0, 0, 0)  # val pipeline is parameter-free


def train_transform(image_size: int, seed: int = 0,
                    normalize: bool = True) -> TrainTransform:
    return TrainTransform(image_size, seed, normalize)


def val_transform(image_size: int, normalize: bool = True) -> ValTransform:
    return ValTransform(image_size, normalize)
