"""ImageFolder dataset: class-per-subdirectory layout -> (image, label).

Parity with torchvision.datasets.ImageFolder as the reference uses it
(reference run_vit_training.py:47,56; layout contract in reference
README.md:46-74): classes are the sorted subdirectory names of the split root,
samples are the images inside them, labels are the class indices.
"""

from __future__ import annotations

import os
from typing import Callable, List, Optional, Tuple

import numpy as np
from PIL import Image

IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif",
                  ".tiff", ".webp")


class ImageFolderDataset:
    def __init__(self, root: str, transform: Optional[Callable] = None):
        self.root = root
        self.transform = transform
        self.classes = sorted(
            d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d)))
        if not self.classes:
            raise FileNotFoundError(f"no class subdirectories under {root}")
        self.class_to_idx = {c: i for i, c in enumerate(self.classes)}

        self.samples: List[Tuple[str, int]] = []
        for cls in self.classes:
            cls_dir = os.path.join(root, cls)
            for dirpath, _, filenames in sorted(os.walk(cls_dir)):
                for fname in sorted(filenames):
                    if fname.lower().endswith(IMG_EXTENSIONS):
                        self.samples.append(
                            (os.path.join(dirpath, fname), self.class_to_idx[cls]))
        if not self.samples:
            raise FileNotFoundError(f"no images found under {root}")

    def set_epoch(self, epoch: int) -> None:
        if self.transform is not None and hasattr(self.transform, "set_epoch"):
            self.transform.set_epoch(epoch)

    def __getitem__(self, idx: int) -> Tuple[np.ndarray, int]:
        path, label = self.samples[idx]
        with Image.open(path) as img:
            img = img.convert("RGB")
            if self.transform is not None:
                return self.transform(img, index=idx), label
            return np.asarray(img, np.float32) / 255.0, label

    def __len__(self) -> int:
        return len(self.samples)

    def __repr__(self) -> str:
        return (f"ImageFolderDataset(root={self.root!r}, classes={len(self.classes)}, "
                f"samples={len(self.samples)})")
