"""ImageFolder dataset: class-per-subdirectory layout -> (image, label).

Parity with torchvision.datasets.ImageFolder as the reference uses it
(reference run_vit_training.py:47,56; layout contract in reference
README.md:46-74): classes are the sorted subdirectory names of the split root,
samples are the images inside them, labels are the class indices.
"""

from __future__ import annotations

import os
from typing import Callable, List, Optional, Tuple

import numpy as np
from PIL import Image

IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif",
                  ".tiff", ".webp")


class ImageFolderDataset:
    """`use_native=None` (auto) routes JPEG decode + transform through the C++
    pipeline (vitax/data/native.py) when the library is available and the
    transform exposes native_params(); anything else (PNG/TIFF, corrupt files,
    no toolchain) falls back to the PIL path per item."""

    def __init__(self, root: str, transform: Optional[Callable] = None,
                 use_native: Optional[bool] = None):
        self.root = root
        self.transform = transform
        from vitax.data import native
        self._native = native
        if use_native is None:
            use_native = native.available()
        self.use_native = (use_native and transform is not None
                           and hasattr(transform, "native_params"))
        self._normalize = getattr(transform, "normalize", True)
        self.classes = sorted(
            d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d)))
        if not self.classes:
            raise FileNotFoundError(f"no class subdirectories under {root}")
        self.class_to_idx = {c: i for i, c in enumerate(self.classes)}

        self.samples: List[Tuple[str, int]] = []
        for cls in self.classes:
            cls_dir = os.path.join(root, cls)
            for dirpath, _, filenames in sorted(os.walk(cls_dir)):
                for fname in sorted(filenames):
                    if fname.lower().endswith(IMG_EXTENSIONS):
                        self.samples.append(
                            (os.path.join(dirpath, fname), self.class_to_idx[cls]))
        if not self.samples:
            raise FileNotFoundError(f"no images found under {root}")

    def set_epoch(self, epoch: int) -> None:
        if self.transform is not None and hasattr(self.transform, "set_epoch"):
            self.transform.set_epoch(epoch)

    def _shape_args(self) -> Tuple[int, int]:
        """(out_size, resize_to) for the native calls."""
        return self.transform.image_size, getattr(self.transform, "resize_to", 0)

    def _native_params(self, idx: int) -> Optional[Tuple[int, ...]]:
        """Transform params for the native pipeline, or None to use PIL."""
        path, _ = self.samples[idx]
        if not self._native.is_jpeg_path(path):
            return None
        size = self._native.jpeg_size(path)
        if size is None:
            return None
        return self.transform.native_params(size[0], size[1], idx)

    def _pil_item(self, idx: int) -> Tuple[np.ndarray, int]:
        path, label = self.samples[idx]
        with Image.open(path) as img:
            img = img.convert("RGB")
            if self.transform is not None:
                return self.transform(img, index=idx), label
            return np.asarray(img, np.float32) / 255.0, label

    def __getitem__(self, idx: int) -> Tuple[np.ndarray, int]:
        if self.use_native:
            params = self._native_params(idx)
            if params is not None:
                out_size, resize_to = self._shape_args()
                arr = self._native.process_file(
                    self.samples[idx][0], params, out_size, resize_to,
                    normalize=self._normalize)
                if arr is not None:
                    return arr, self.samples[idx][1]
        return self._pil_item(idx)

    def load_batch(self, indices, n_threads: int = 8
                   ) -> Tuple[np.ndarray, np.ndarray]:
        """Whole-batch path: one GIL-free C++ call decodes + transforms every
        JPEG on a std::thread pool; non-JPEG or failed items fall back to PIL.
        Returns (images (N, S, S, 3), labels (N,) int32); images are normalized
        float32, or raw uint8 when the transform has normalize=False (the
        device-side normalization path)."""
        indices = list(indices)
        labels = np.asarray([self.samples[i][1] for i in indices], np.int32)
        out_size, resize_to = self._shape_args()
        dtype = np.float32 if self._normalize else np.uint8

        native_pos, params = [], []
        for pos, i in enumerate(indices):
            p = self._native_params(i) if self.use_native else None
            if p is not None:
                native_pos.append(pos)
                params.append(p)

        images = np.empty((len(indices), out_size, out_size, 3), dtype)
        native_set = set(native_pos)
        fallback = [pos for pos in range(len(indices)) if pos not in native_set]
        if native_pos:
            batch, failed = self._native.process_batch(
                [self.samples[indices[pos]][0] for pos in native_pos], params,
                out_size, resize_to, n_threads, normalize=self._normalize)
            if batch is None:
                fallback = list(range(len(indices)))
            else:
                failed_set = set(failed)
                for j, pos in enumerate(native_pos):
                    if j in failed_set:
                        fallback.append(pos)
                    else:
                        images[pos] = batch[j]
        if len(fallback) > 1:
            # parallel PIL fallback (PIL releases the GIL during decode) — a
            # mostly-non-JPEG batch keeps the pre-native path's parallelism
            from concurrent.futures import ThreadPoolExecutor
            with ThreadPoolExecutor(min(n_threads, len(fallback))) as pool:
                for pos, (img, _) in zip(fallback, pool.map(
                        self._pil_item, (indices[pos] for pos in fallback))):
                    images[pos] = img
        else:
            for pos in fallback:
                images[pos] = self._pil_item(indices[pos])[0]
        return images, labels

    def __len__(self) -> int:
        return len(self.samples)

    def __repr__(self) -> str:
        return (f"ImageFolderDataset(root={self.root!r}, classes={len(self.classes)}, "
                f"samples={len(self.samples)})")
