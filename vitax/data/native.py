"""numpy-facing wrappers over the native (C++) data-path library.

Sits between the datasets (vitax/data/imagefolder.py) and the ctypes library
(vitax/_native): single-image and batched decode+transform calls that fill
float32 HWC arrays. The batched call runs libjpeg decode + PIL-parity bicubic
resample + normalize across a C++ std::thread pool — one GIL-free call per
local batch, replacing the reference's DataLoader worker *processes*
(reference run_vit_training.py:65-73).
"""

from __future__ import annotations

import ctypes
from typing import List, Optional, Sequence, Tuple

import numpy as np

from vitax import _native

_JPEG_EXT = (".jpg", ".jpeg", ".jpe", ".jfif")
_JPEG_MAGIC = b"\xff\xd8\xff"  # SOI marker + first segment byte


def available() -> bool:
    return _native.available()


def mem_available() -> bool:
    """True when the library exposes the memory-source API (vitax_process_mem
    et al.) — a stale .so built before the streaming data plane doesn't, and
    callers fall back to PIL for in-memory records."""
    lib = _native.load()
    return lib is not None and hasattr(lib, "vitax_process_mem")


def is_jpeg_path(path: str) -> bool:
    return path.lower().endswith(_JPEG_EXT)


def is_jpeg_bytes(data: bytes) -> bool:
    """Content sniff: JPEG streams start with the SOI marker. Shard records
    and /predict bodies carry no filename, so the extension check above
    doesn't apply."""
    return data[:3] == _JPEG_MAGIC


def jpeg_size(path: str) -> Optional[Tuple[int, int]]:
    """(width, height) from the JPEG header, or None on failure."""
    lib = _native.load()
    if lib is None:
        return None
    w, h = ctypes.c_int(), ctypes.c_int()
    if lib.vitax_jpeg_size(path.encode(), ctypes.byref(w), ctypes.byref(h)) != 0:
        return None
    return w.value, h.value


def process_file(path: str, params: Sequence[int], out_size: int,
                 resize_to: int, normalize: bool = True) -> Optional[np.ndarray]:
    """Decode + transform one JPEG; params = (mode, left, top, cw, ch, flip)
    from a transform's native_params(). Returns (S, S, 3) float32 normalized
    when `normalize`, else raw uint8 (device-side normalization path), or
    None on failure."""
    lib = _native.load()
    if lib is None:
        return None
    out = np.empty((out_size, out_size, 3),
                   np.float32 if normalize else np.uint8)
    mode, left, top, cw, ch, flip = (int(x) for x in params)
    rc = lib.vitax_process_file(
        path.encode(), mode, left, top, cw, ch, flip, out_size, resize_to,
        int(normalize), out.ctypes.data_as(ctypes.c_void_p))
    return out if rc == 0 else None


def process_batch(paths: Sequence[str], params: Sequence[Sequence[int]],
                  out_size: int, resize_to: int, n_threads: int = 8,
                  normalize: bool = True
                  ) -> Tuple[Optional[np.ndarray], List[int]]:
    """Decode + transform a batch on the C++ thread pool.

    Returns (batch (N, S, S, 3) float32-normalized or raw-uint8,
    failed_indices); failed slots are untouched and must be filled by the
    caller's fallback path. Returns (None, all indices) if the native library
    is unavailable.
    """
    n = len(paths)
    if _native.load() is None:
        return None, list(range(n))
    lib = _native.load()
    out = np.empty((n, out_size, out_size, 3),
                   np.float32 if normalize else np.uint8)
    fail = np.zeros(n, np.uint8)
    params_arr = np.ascontiguousarray(params, np.int32).reshape(n, 6)
    c_paths = (ctypes.c_char_p * n)(*[p.encode() for p in paths])
    lib.vitax_process_batch(
        c_paths, n, params_arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        out_size, resize_to, int(normalize),
        out.ctypes.data_as(ctypes.c_void_p),
        fail.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), n_threads)
    return out, list(np.nonzero(fail)[0])


def jpeg_size_bytes(data: bytes) -> Optional[Tuple[int, int]]:
    """(width, height) from an in-memory JPEG header, or None on failure."""
    if not mem_available():
        return None
    lib = _native.load()
    w, h = ctypes.c_int(), ctypes.c_int()
    if lib.vitax_jpeg_size_mem(data, len(data), ctypes.byref(w),
                               ctypes.byref(h)) != 0:
        return None
    return w.value, h.value


def process_bytes(data: bytes, params: Sequence[int], out_size: int,
                  resize_to: int, normalize: bool = True
                  ) -> Optional[np.ndarray]:
    """Decode + transform one in-memory JPEG (a shard record or a /predict
    request body) — same pipeline and bitwise-identical output to
    process_file on the same bytes. Returns None on failure or when the
    memory-source API is unavailable (caller falls back to PIL)."""
    if not mem_available():
        return None
    lib = _native.load()
    out = np.empty((out_size, out_size, 3),
                   np.float32 if normalize else np.uint8)
    mode, left, top, cw, ch, flip = (int(x) for x in params)
    rc = lib.vitax_process_mem(
        data, len(data), mode, left, top, cw, ch, flip, out_size, resize_to,
        int(normalize), out.ctypes.data_as(ctypes.c_void_p))
    return out if rc == 0 else None


def process_batch_bytes(blobs: Sequence[bytes],
                        params: Sequence[Sequence[int]], out_size: int,
                        resize_to: int, n_threads: int = 8,
                        normalize: bool = True
                        ) -> Tuple[Optional[np.ndarray], List[int]]:
    """Decode + transform a batch of in-memory JPEG records on the C++ thread
    pool — the streaming data plane's hot path (one GIL-free call per local
    batch, no per-record Python and no filesystem round-trip).

    Same contract as process_batch: (batch, failed_indices), or
    (None, all indices) when the memory-source API is unavailable.
    """
    n = len(blobs)
    if not mem_available():
        return None, list(range(n))
    lib = _native.load()
    out = np.empty((n, out_size, out_size, 3),
                   np.float32 if normalize else np.uint8)
    fail = np.zeros(n, np.uint8)
    params_arr = np.ascontiguousarray(params, np.int32).reshape(n, 6)
    # c_char_p conversion keeps a pointer to each bytes object's buffer (the
    # array holds references); embedded NULs are fine — lengths are explicit
    c_blobs = (ctypes.c_char_p * n)(*blobs)
    lens = np.asarray([len(b) for b in blobs], np.int32)
    lib.vitax_process_batch_mem(
        c_blobs, lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), n,
        params_arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        out_size, resize_to, int(normalize),
        out.ctypes.data_as(ctypes.c_void_p),
        fail.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), n_threads)
    return out, list(np.nonzero(fail)[0])
