"""numpy-facing wrappers over the native (C++) data-path library.

Sits between the datasets (vitax/data/imagefolder.py) and the ctypes library
(vitax/_native): single-image and batched decode+transform calls that fill
float32 HWC arrays. The batched call runs libjpeg decode + PIL-parity bicubic
resample + normalize across a C++ std::thread pool — one GIL-free call per
local batch, replacing the reference's DataLoader worker *processes*
(reference run_vit_training.py:65-73).
"""

from __future__ import annotations

import ctypes
from typing import List, Optional, Sequence, Tuple

import numpy as np

from vitax import _native

_JPEG_EXT = (".jpg", ".jpeg", ".jpe", ".jfif")


def available() -> bool:
    return _native.available()


def is_jpeg_path(path: str) -> bool:
    return path.lower().endswith(_JPEG_EXT)


def jpeg_size(path: str) -> Optional[Tuple[int, int]]:
    """(width, height) from the JPEG header, or None on failure."""
    lib = _native.load()
    if lib is None:
        return None
    w, h = ctypes.c_int(), ctypes.c_int()
    if lib.vitax_jpeg_size(path.encode(), ctypes.byref(w), ctypes.byref(h)) != 0:
        return None
    return w.value, h.value


def process_file(path: str, params: Sequence[int], out_size: int,
                 resize_to: int, normalize: bool = True) -> Optional[np.ndarray]:
    """Decode + transform one JPEG; params = (mode, left, top, cw, ch, flip)
    from a transform's native_params(). Returns (S, S, 3) float32 normalized
    when `normalize`, else raw uint8 (device-side normalization path), or
    None on failure."""
    lib = _native.load()
    if lib is None:
        return None
    out = np.empty((out_size, out_size, 3),
                   np.float32 if normalize else np.uint8)
    mode, left, top, cw, ch, flip = (int(x) for x in params)
    rc = lib.vitax_process_file(
        path.encode(), mode, left, top, cw, ch, flip, out_size, resize_to,
        int(normalize), out.ctypes.data_as(ctypes.c_void_p))
    return out if rc == 0 else None


def process_batch(paths: Sequence[str], params: Sequence[Sequence[int]],
                  out_size: int, resize_to: int, n_threads: int = 8,
                  normalize: bool = True
                  ) -> Tuple[Optional[np.ndarray], List[int]]:
    """Decode + transform a batch on the C++ thread pool.

    Returns (batch (N, S, S, 3) float32-normalized or raw-uint8,
    failed_indices); failed slots are untouched and must be filled by the
    caller's fallback path. Returns (None, all indices) if the native library
    is unavailable.
    """
    n = len(paths)
    if _native.load() is None:
        return None, list(range(n))
    lib = _native.load()
    out = np.empty((n, out_size, out_size, 3),
                   np.float32 if normalize else np.uint8)
    fail = np.zeros(n, np.uint8)
    params_arr = np.ascontiguousarray(params, np.int32).reshape(n, 6)
    c_paths = (ctypes.c_char_p * n)(*[p.encode() for p in paths])
    lib.vitax_process_batch(
        c_paths, n, params_arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        out_size, resize_to, int(normalize),
        out.ctypes.data_as(ctypes.c_void_p),
        fail.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), n_threads)
    return out, list(np.nonzero(fail)[0])
