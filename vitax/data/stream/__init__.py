"""vitax.data.stream — sharded streaming data plane (ROADMAP item 3).

A WebDataset/ArrayRecord-style input subsystem: ImageFolder trees are packed
once into `.vtxshard` containers (tools/make_shards.py), then training
streams length-prefixed records sequentially — no per-file opens, native
in-memory JPEG decode, deterministic per-host shard assignment, and a
checkpointable mid-epoch resume cursor.

Selected with `--data_format stream` (`--data_dir` points at the shard root);
`build_stream_datasets` is the `build_datasets` (vitax/data/loader.py)
counterpart with the same return contract.
"""

from __future__ import annotations

import os

from jax.sharding import Mesh

from vitax.config import Config
from vitax.data.stream.format import (ShardFormatError, ShardReader,
                                      ShardWriter, load_split_meta)
from vitax.data.stream.loader import StreamDataset, StreamLoader
from vitax.data.stream.sampler import StreamSampler, assign_shards

__all__ = [
    "ShardFormatError", "ShardReader", "ShardWriter", "StreamDataset",
    "StreamLoader", "StreamSampler", "assign_shards",
    "build_stream_datasets", "load_split_meta",
]


def build_stream_datasets(cfg: Config, mesh: Mesh):
    """(train_ds, train_loader, val_ds, val_loader) over a shard root —
    the `--data_format stream` branch of vitax.data.build_datasets."""
    from vitax.data.transforms import train_transform, val_transform

    norm_on_host = not cfg.device_normalize
    train_ds = StreamDataset(
        os.path.join(cfg.data_dir, "train"),
        train_transform(cfg.image_size, cfg.seed, normalize=norm_on_host))
    val_ds = StreamDataset(
        os.path.join(cfg.data_dir, "val"),
        val_transform(cfg.image_size, normalize=norm_on_host))
    train_sampler = StreamSampler(train_ds.meta, cfg.batch_size,
                                  shuffle=True, seed=cfg.seed)
    val_sampler = StreamSampler(val_ds.meta, cfg.batch_size,
                                shuffle=False, seed=cfg.seed)
    train_loader = StreamLoader(train_ds, train_sampler, mesh,
                                cfg.num_workers,
                                prefetch=cfg.stream_prefetch)
    val_loader = StreamLoader(val_ds, val_sampler, mesh, cfg.num_workers,
                              prefetch=cfg.stream_prefetch)
    return train_ds, train_loader, val_ds, val_loader
