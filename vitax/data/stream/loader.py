"""Streaming dataset + loader: shard records -> native decode -> device.

Mirrors ShardedLoader's interface (vitax/data/loader.py) so train/loop.py
consumes either transparently — `epoch(epoch, start_step)`, `steps_per_epoch`,
`consume_wait_s()`, `close()` — with three streaming-specific upgrades:

- records arrive as in-memory bytes from the shard reader (ONE open handle,
  sequential shard consumption) and decode through the native memory-source
  batch call (`vitax/data/native.py process_batch_bytes`): one GIL-free C++
  call per local batch, no filesystem round-trip per sample;
- the host->device stage is explicitly double-buffered: the transfer of
  batch k+1 is ISSUED before batch k is yielded to the step loop, so H2D
  overlaps step k even on transports whose device_put is lazier than XLA's
  async dispatch suggests;
- `cursor_for_step` / `check_cursor` expose the deterministic mid-epoch
  resume cursor (vitax/data/stream/sampler.py) that train/loop.py stores in
  the checkpoint sidecar.
"""

from __future__ import annotations

import io
import queue
import threading
import time
import traceback
from typing import Dict, Iterator, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding

from vitax.data.loader import LoaderWorkerError, _ProducerFailure
from vitax.data.stream.format import ShardReader, load_split_meta
from vitax.data.stream.sampler import StreamSampler
from vitax.parallel.mesh import batch_pspec


class StreamDataset:
    """Decodes (shard_id, record_id) entries from one split's shard set.

    `use_native=None` (auto) routes JPEG records through the C++
    memory-source pipeline when available; anything else (non-JPEG payloads,
    corrupt records, stale .so without the mem API) falls back to PIL per
    record — the same degradation ladder as ImageFolderDataset."""

    def __init__(self, split_dir: str, transform=None,
                 use_native: Optional[bool] = None):
        from vitax.data import native
        self._native = native
        self.split_dir = split_dir
        self.transform = transform
        self.meta = load_split_meta(split_dir)
        self.reader = ShardReader(split_dir, self.meta)
        self.classes = list(self.meta.get("classes", []))
        self.num_records = int(self.meta["num_records"])
        if use_native is None:
            use_native = native.mem_available()
        self.use_native = (use_native and transform is not None
                           and hasattr(transform, "native_params"))
        self._normalize = getattr(transform, "normalize", True)

    def set_epoch(self, epoch: int) -> None:
        if self.transform is not None and hasattr(self.transform, "set_epoch"):
            self.transform.set_epoch(epoch)

    def __len__(self) -> int:
        return self.num_records

    def __repr__(self) -> str:
        return (f"StreamDataset(split_dir={self.split_dir!r}, "
                f"classes={len(self.classes)}, records={self.num_records}, "
                f"shards={len(self.meta['shards'])})")

    def _shape_args(self) -> Tuple[int, int]:
        return self.transform.image_size, getattr(self.transform, "resize_to", 0)

    def _pil_decode(self, payload: bytes, global_id: int) -> np.ndarray:
        from PIL import Image
        with Image.open(io.BytesIO(payload)) as img:
            img = img.convert("RGB")
            if self.transform is not None:
                return self.transform(img, index=global_id)
            return np.asarray(img, np.float32) / 255.0

    def load_entries(self, entries: Sequence[Tuple[int, int, int]],
                     n_threads: int = 8) -> Tuple[np.ndarray, np.ndarray]:
        """One local batch: entries = (shard_id, record_id, global_id)
        triples in plan order (grouped by shard — the reader advances
        sequentially). Returns (images, labels) like
        ImageFolderDataset.load_batch: normalized float32 or raw uint8 when
        the transform has normalize=False (device-side normalization)."""
        payloads, labels = [], []
        for shard_id, record_id, _ in entries:
            payload, label = self.reader.read_record(int(shard_id),
                                                     int(record_id))
            payloads.append(payload)
            labels.append(label)
        labels_arr = np.asarray(labels, np.int32)
        out_size, resize_to = self._shape_args()
        dtype = np.float32 if self._normalize else np.uint8
        images = np.empty((len(entries), out_size, out_size, 3), dtype)

        native_pos, params = [], []
        if self.use_native:
            for pos, (_, _, global_id) in enumerate(entries):
                payload = payloads[pos]
                if not self._native.is_jpeg_bytes(payload):
                    continue
                size = self._native.jpeg_size_bytes(payload)
                if size is None:
                    continue
                native_pos.append(pos)
                params.append(self.transform.native_params(
                    size[0], size[1], int(global_id)))

        native_set = set(native_pos)
        fallback = [p for p in range(len(entries)) if p not in native_set]
        if native_pos:
            batch, failed = self._native.process_batch_bytes(
                [payloads[p] for p in native_pos], params, out_size,
                resize_to, n_threads, normalize=self._normalize)
            if batch is None:
                fallback = list(range(len(entries)))
            else:
                failed_set = set(failed)
                for j, pos in enumerate(native_pos):
                    if j in failed_set:
                        fallback.append(pos)
                    else:
                        images[pos] = batch[j]
        for pos in fallback:
            images[pos] = self._pil_decode(payloads[pos],
                                           int(entries[pos][2]))
        return images, labels_arr

    def close(self) -> None:
        self.reader.close()


class StreamLoader:
    """Iterates global batches as sharded device arrays: background producer
    thread (shard read + native decode), double-buffered H2D on the consumer
    thread, deterministic mid-epoch cursor."""

    def __init__(self, dataset: StreamDataset, sampler: StreamSampler,
                 mesh: Mesh, num_workers: int = 4, prefetch: int = 2):
        self.dataset = dataset
        self.sampler = sampler
        self.mesh = mesh
        self.sharding = NamedSharding(mesh, batch_pspec())
        self.label_sharding = NamedSharding(mesh, batch_pspec())
        self.num_workers = max(num_workers, 1)
        self.prefetch = max(prefetch, 1)
        self.steps_per_epoch = sampler.steps_per_epoch
        self._wait_s = 0.0

    def consume_wait_s(self) -> float:
        """Seconds the training thread spent blocked on the prefetch queue
        since the last call, then reset — flows into the data_wait_s
        telemetry field exactly like ShardedLoader.consume_wait_s (the
        input-bound signal tools/metrics_report.py aggregates)."""
        w = self._wait_s
        self._wait_s = 0.0
        return w

    def cursor_for_step(self, epoch: int, step: int) -> Dict:
        """Resume cursor after `step` consumed batches — what train/loop.py
        stores in the mid-epoch checkpoint sidecar."""
        return self.sampler.cursor_for_step(epoch, step)

    def check_cursor(self, cursor: Dict, resume_step: int) -> None:
        """Validate a restored sidecar cursor against this run's derived
        resume position (shard-set drift detection)."""
        self.sampler.check_cursor(cursor, int(cursor.get("epoch", 0)),
                                  resume_step)

    def _load_local(self, rows: np.ndarray) -> Dict[str, np.ndarray]:
        entries = [(int(s), int(r), self.sampler.global_id(int(s), int(r)))
                   for s, r in rows]
        images, labels = self.dataset.load_entries(entries, self.num_workers)
        return {"image": images, "label": labels}

    def _to_device(self, local: Dict[str, np.ndarray]) -> Dict[str, jax.Array]:
        return {
            "image": jax.make_array_from_process_local_data(
                self.sharding, local["image"]),
            "label": jax.make_array_from_process_local_data(
                self.label_sharding, local["label"]),
        }

    def epoch(self, epoch: int, start_step: int = 0
              ) -> Iterator[Dict[str, jax.Array]]:
        """Yield device batches for one epoch. `start_step` skips the first N
        batches EXACTLY (the plan is a pure function of (seed, epoch), so no
        skipped record is read) — mid-epoch resume lands on precisely the
        not-yet-seen records."""
        if hasattr(self.dataset, "set_epoch"):
            self.dataset.set_epoch(epoch)
        plan = self.sampler.epoch_entries(epoch)[start_step:]
        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        stop = threading.Event()

        def producer():
            # Host-side work only (shard read + decode). ALL JAX dispatch
            # stays on the consumer thread — a second dispatch thread can
            # interleave compiled collectives and deadlock their rendezvous
            # (see ShardedLoader.epoch).
            try:
                for rows in plan:
                    if stop.is_set():
                        return
                    q.put(self._load_local(rows))
            except BaseException as e:
                q.put(_ProducerFailure(e, traceback.format_exc()))
            finally:
                q.put(None)

        t = threading.Thread(target=producer, daemon=True,
                             name="vitax-stream-prefetch")
        t.start()
        pending: Optional[Dict[str, jax.Array]] = None
        try:
            while True:
                t_wait = time.monotonic()
                item = q.get()
                self._wait_s += time.monotonic() - t_wait
                if item is None:
                    break
                if isinstance(item, _ProducerFailure):
                    raise LoaderWorkerError(
                        f"stream worker failed while producing epoch {epoch}:"
                        f" {type(item.exc).__name__}: {item.exc}\n"
                        f"--- worker traceback (vitax-stream-prefetch thread)"
                        f" ---\n{item.tb}") from item.exc
                # double buffer: ISSUE the transfer of this batch, then yield
                # the previous one — batch k+1's H2D is in flight while the
                # step loop consumes batch k
                device_batch = self._to_device(item)
                if pending is not None:
                    yield pending
                pending = device_batch
            if pending is not None:
                yield pending
        finally:
            stop.set()
            # drain until the producer actually exits (a producer blocked in
            # q.put needs the consumer to free a slot — see ShardedLoader)
            deadline = time.monotonic() + 10.0
            while t.is_alive() and time.monotonic() < deadline:
                try:
                    q.get(timeout=0.05)
                except queue.Empty:
                    pass
            t.join(timeout=max(0.0, deadline - time.monotonic()))

    def close(self) -> None:
        self.dataset.close()
