"""`.vtxshard` container format: writer, index, and a seeking record reader.

The sharded streaming data plane replaces per-file directory scans (ImageFolder:
one open()+stat() per sample, millions of tiny reads per epoch) with a
WebDataset/ArrayRecord-style layout a pod can actually feed from:

    <root>/<split>/shard-00000.vtxshard        length-prefixed records
    <root>/<split>/shard-00000.vtxshard.json   per-shard index (offsets, labels)
    <root>/<split>/stream_meta.json            split manifest (classes, shards)

Shard file layout (version 1):

    magic  b"VTXSHARD1\\n"                      (10 bytes)
    record := uint32le payload_len | int32le label | payload bytes
    ... repeated; payloads are the ORIGINAL image file bytes, verbatim
    (JPEGs stay JPEGs — no re-encode, so streaming and ImageFolder deliver
    bit-identical samples; non-JPEG records fall back to PIL at decode time).

The per-shard JSON index carries record offsets (of each record header from
the start of the file), payload lengths and labels, so a reader can both
stream sequentially and seek to an epoch-shuffled record order with ONE open
file handle per shard. The record header is re-validated against the index on
every read — a torn or truncated shard fails loudly at the record that hit
it, not with garbage pixels.
"""

from __future__ import annotations

import json
import os
import struct
from typing import BinaryIO, Dict, List, Optional, Tuple

from vitax import faults

MAGIC = b"VTXSHARD1\n"
FORMAT_VERSION = 1
META_NAME = "stream_meta.json"
SHARD_SUFFIX = ".vtxshard"
INDEX_SUFFIX = ".vtxshard.json"

_HEADER = struct.Struct("<Ii")  # payload_len (uint32), label (int32)

DEFAULT_SHARD_SIZE_MB = 100


class ShardFormatError(RuntimeError):
    """A shard file or index that violates the container format — the torn /
    truncated / wrong-magic cases a crash mid-write or a partial copy leaves
    behind."""


class ShardWriter:
    """Packs records into size-targeted shards under `split_dir`.

    Usage:
        with ShardWriter(split_dir, classes=[...]) as w:
            w.add(payload_bytes, label)
        # -> shard-*.vtxshard + per-shard indexes + stream_meta.json
    """

    def __init__(self, split_dir: str, classes: Optional[List[str]] = None,
                 shard_size_mb: float = DEFAULT_SHARD_SIZE_MB):
        assert shard_size_mb > 0, "shard size target must be positive"
        self.split_dir = split_dir
        self.classes = list(classes) if classes else []
        self.target_bytes = int(shard_size_mb * 1024 * 1024)
        os.makedirs(split_dir, exist_ok=True)
        self._shards: List[Dict] = []   # manifest entries
        self._f: Optional[BinaryIO] = None
        self._offsets: List[int] = []
        self._lengths: List[int] = []
        self._labels: List[int] = []
        self._pos = 0

    def _shard_name(self, i: int) -> str:
        return f"shard-{i:05d}{SHARD_SUFFIX}"

    def _open_shard(self) -> None:
        name = self._shard_name(len(self._shards))
        self._f = open(os.path.join(self.split_dir, name), "wb")
        self._f.write(MAGIC)
        self._pos = len(MAGIC)
        self._offsets, self._lengths, self._labels = [], [], []

    def _close_shard(self) -> None:
        if self._f is None:
            return
        self._f.close()
        name = self._shard_name(len(self._shards))
        index = {
            "version": FORMAT_VERSION,
            "records": len(self._offsets),
            "offsets": self._offsets,
            "lengths": self._lengths,
            "labels": self._labels,
            "bytes": self._pos,
        }
        # atomic index write: the shard becomes visible to readers only once
        # its index exists, and never half-written
        idx_path = os.path.join(self.split_dir, name[:-len(SHARD_SUFFIX)]
                                + INDEX_SUFFIX)
        tmp = idx_path + f".tmp{os.getpid()}"
        with open(tmp, "w") as jf:
            json.dump(index, jf)
        os.replace(tmp, idx_path)
        self._shards.append({"name": name, "records": len(self._offsets),
                             "bytes": self._pos})
        self._f = None

    def add(self, payload: bytes, label: int) -> None:
        if self._f is None:
            self._open_shard()
        self._offsets.append(self._pos)
        self._lengths.append(len(payload))
        self._labels.append(int(label))
        self._f.write(_HEADER.pack(len(payload), int(label)))
        self._f.write(payload)
        self._pos += _HEADER.size + len(payload)
        if self._pos >= self.target_bytes:
            self._close_shard()

    def close(self) -> Dict:
        """Finalize the open shard and write the split manifest; returns it."""
        self._close_shard()
        meta = {
            "version": FORMAT_VERSION,
            "classes": self.classes,
            "num_records": sum(s["records"] for s in self._shards),
            "shards": self._shards,
        }
        meta_path = os.path.join(self.split_dir, META_NAME)
        tmp = meta_path + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, meta_path)
        return meta

    def __enter__(self) -> "ShardWriter":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is None:
            self.close()
        elif self._f is not None:
            self._f.close()  # leave no dangling handle; partial shard has no
            # index so readers never see it


def load_split_meta(split_dir: str) -> Dict:
    """The split manifest, validated. Raises FileNotFoundError when the dir
    holds no stream_meta.json (the config check that `--data_format stream`
    actually points at a shard set)."""
    path = os.path.join(split_dir, META_NAME)
    if not os.path.isfile(path):
        raise FileNotFoundError(
            f"no {META_NAME} under {split_dir!r} — not a vitax shard "
            f"directory (build one with tools/make_shards.py)")
    with open(path) as f:
        meta = json.load(f)
    if meta.get("version") != FORMAT_VERSION:
        raise ShardFormatError(
            f"{path}: format version {meta.get('version')!r}, reader "
            f"supports {FORMAT_VERSION}")
    if not meta.get("shards"):
        raise ShardFormatError(f"{path}: empty shard list")
    return meta


def load_shard_index(split_dir: str, shard_name: str) -> Dict:
    path = os.path.join(split_dir,
                        shard_name[:-len(SHARD_SUFFIX)] + INDEX_SUFFIX)
    with open(path) as f:
        index = json.load(f)
    if index.get("version") != FORMAT_VERSION:
        raise ShardFormatError(
            f"{path}: format version {index.get('version')!r}, reader "
            f"supports {FORMAT_VERSION}")
    return index


class ShardReader:
    """Seeking record reader over one split: ONE open file handle at a time,
    records fetched by (shard_id, record_id) with header-vs-index validation.

    The access pattern the epoch plan produces is sequential over shards
    (shard k is fully consumed before shard k+1) with shuffled offsets inside
    the open shard — so the reader is a current-handle cache, not a pool.
    Opens run through the `stream_read` fault site (vitax/faults.py) and are
    retried once before surfacing, so a transient NFS hiccup costs one
    reopen, not the run.
    """

    def __init__(self, split_dir: str, meta: Optional[Dict] = None):
        self.split_dir = split_dir
        self.meta = meta if meta is not None else load_split_meta(split_dir)
        self.shards = self.meta["shards"]
        self._indexes: Dict[int, Dict] = {}
        self._f: Optional[BinaryIO] = None
        self._open_shard_id: Optional[int] = None

    def index(self, shard_id: int) -> Dict:
        idx = self._indexes.get(shard_id)
        if idx is None:
            idx = load_shard_index(self.split_dir,
                                   self.shards[shard_id]["name"])
            self._indexes[shard_id] = idx
        return idx

    def _open(self, shard_id: int) -> BinaryIO:
        if self._open_shard_id == shard_id and self._f is not None:
            return self._f
        self.close()
        path = os.path.join(self.split_dir, self.shards[shard_id]["name"])
        last_err: Optional[OSError] = None
        for attempt in (0, 1):  # one retry: transient open failures happen
            # on shared stores; a second failure is a real torn/missing shard
            try:
                faults.fire("stream_read")  # drill point: `oserror` here
                # exercises the retry, `stall` starves the consumer like a
                # slow store
                f = open(path, "rb")
            except OSError as e:
                last_err = e
                continue
            magic = f.read(len(MAGIC))
            if magic != MAGIC:
                f.close()
                raise ShardFormatError(
                    f"{path}: bad magic {magic!r} — torn or not a "
                    f"{SHARD_SUFFIX} file")
            self._f = f
            self._open_shard_id = shard_id
            return f
        from vitax.data.loader import LoaderWorkerError
        raise LoaderWorkerError(
            f"shard open failed after retry: {path} "
            f"({type(last_err).__name__}: {last_err})") from last_err

    def read_record(self, shard_id: int, record_id: int) -> Tuple[bytes, int]:
        """(payload bytes, label) for one record, header-validated."""
        idx = self.index(shard_id)
        f = self._open(shard_id)
        offset = idx["offsets"][record_id]
        f.seek(offset)
        header = f.read(_HEADER.size)
        if len(header) != _HEADER.size:
            raise ShardFormatError(
                f"{self.shards[shard_id]['name']}: truncated record header "
                f"at offset {offset} (record {record_id})")
        length, label = _HEADER.unpack(header)
        if (length != idx["lengths"][record_id]
                or label != idx["labels"][record_id]):
            raise ShardFormatError(
                f"{self.shards[shard_id]['name']}: record {record_id} header "
                f"(len={length}, label={label}) disagrees with index "
                f"(len={idx['lengths'][record_id]}, "
                f"label={idx['labels'][record_id]}) — torn shard or stale "
                f"index")
        payload = f.read(length)
        if len(payload) != length:
            raise ShardFormatError(
                f"{self.shards[shard_id]['name']}: truncated payload for "
                f"record {record_id} (wanted {length} bytes, got "
                f"{len(payload)})")
        return payload, label

    def iter_shard(self, shard_id: int):
        """Sequential (payload, label) stream over one shard — the pure
        streaming path (writer order, no index-driven seeks between
        records)."""
        n = self.shards[shard_id]["records"]
        for record_id in range(n):
            yield self.read_record(shard_id, record_id)

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None
            self._open_shard_id = None
