"""Deterministic shard->host assignment, epoch plans, and resume-cursor math.

Same contract as ShardedSampler (vitax/data/loader.py) at shard granularity:

- **Disjoint**: each shard belongs to exactly one host, by a STATIC
  assignment derived from the mesh/process topology (process_index,
  process_count) and the shard manifest — never from the epoch. A static
  assignment keeps steps_per_epoch identical across epochs and makes the
  epoch plan a pure function of (seed, epoch), which is what the resume
  cursor depends on.
- **Epoch-seeded shuffle**: each epoch permutes the host's shard ORDER and
  every shard's internal record order from SeedSequence-derived streams, so
  the plan is reproducible on any restart of the same config.

The cursor: a host consumes its epoch plan strictly in order, so the resume
position after `step` consumed batches is the single integer
p = step * local_batch, equivalently `(shard_cursor, record_offset)` into the
epoch's ordered shard list. Both directions are pure functions of
(seed, epoch, step) — the checkpoint sidecar stores the tuple form for
drift detection (a changed shard set between runs fails loudly instead of
silently feeding different records).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


def assign_shards(record_counts: Sequence[int], process_count: int
                  ) -> List[List[int]]:
    """Static greedy-balanced shard assignment: shards (largest first,
    shard id as tie-break) go to the currently lightest host (host id as
    tie-break). Deterministic in the manifest order, independent of epoch.
    Returns per-host lists of shard ids, disjoint and jointly exhaustive."""
    assert process_count >= 1
    hosts: List[List[int]] = [[] for _ in range(process_count)]
    loads = [0] * process_count
    order = sorted(range(len(record_counts)),
                   key=lambda i: (-record_counts[i], i))
    for shard_id in order:
        h = min(range(process_count), key=lambda j: (loads[j], j))
        hosts[h].append(shard_id)
        loads[h] += record_counts[shard_id]
    for h in hosts:
        h.sort()
    return hosts


class StreamSampler:
    """Per-host epoch plans over a shard manifest (ShardedSampler parity at
    shard granularity, plus the resume-cursor math)."""

    def __init__(self, meta: Dict, global_batch: int, shuffle: bool,
                 seed: int, process_index: Optional[int] = None,
                 process_count: Optional[int] = None):
        import jax
        self.shards = meta["shards"]
        self.shuffle = shuffle
        self.seed = seed
        self.global_batch = global_batch
        self.process_index = (jax.process_index() if process_index is None
                              else process_index)
        self.process_count = (jax.process_count() if process_count is None
                              else process_count)
        assert global_batch % self.process_count == 0
        self.local_batch = global_batch // self.process_count
        self.record_counts = [int(s["records"]) for s in self.shards]
        # global record ids (for the per-sample augmentation rng): record r of
        # shard s has id shard_base[s] + r — stable across epochs and hosts,
        # playing the role ImageFolder's dataset index plays
        self.shard_base = np.concatenate(
            ([0], np.cumsum(self.record_counts)))[:-1].astype(np.int64)
        self.assignment = assign_shards(self.record_counts,
                                        self.process_count)
        self.my_shards = self.assignment[self.process_index]
        host_records = [sum(self.record_counts[i] for i in a)
                        for a in self.assignment]
        # drop_last at the host level: every host must deliver the SAME step
        # count (the global batch is a collective), so the epoch length is
        # pinned by the lightest host. With shards balanced by assign_shards
        # and a divisible dataset this equals dataset_len // global_batch —
        # ShardedSampler parity.
        self.steps_per_epoch = min(hr // self.local_batch
                                   for hr in host_records)

    def shard_order(self, epoch: int) -> List[int]:
        """This host's shards in epoch-consumption order."""
        if not self.shuffle or len(self.my_shards) <= 1:
            return list(self.my_shards)
        rng = np.random.default_rng(np.random.SeedSequence(
            [self.seed, epoch, 1, self.process_index]))
        return [self.my_shards[i]
                for i in rng.permutation(len(self.my_shards))]

    def record_order(self, epoch: int, shard_id: int) -> np.ndarray:
        """Within-shard record order for `epoch` (host-agnostic: keyed on the
        shard id, so the permutation survives assignment changes)."""
        n = self.record_counts[shard_id]
        if not self.shuffle:
            return np.arange(n, dtype=np.int64)
        rng = np.random.default_rng(np.random.SeedSequence(
            [self.seed, epoch, 2, shard_id]))
        return rng.permutation(n).astype(np.int64)

    def epoch_entries(self, epoch: int) -> np.ndarray:
        """(steps_per_epoch, local_batch, 2) int64 of (shard_id, record_id):
        this host's full epoch plan, shards consumed strictly in order (the
        reader keeps ONE open handle), records within each shard in the
        epoch's permutation, truncated to whole local batches (drop_last)."""
        parts = []
        for shard_id in self.shard_order(epoch):
            rec = self.record_order(epoch, shard_id)
            ids = np.full_like(rec, shard_id)
            parts.append(np.stack([ids, rec], axis=1))
        flat = (np.concatenate(parts) if parts
                else np.empty((0, 2), np.int64))
        usable = self.steps_per_epoch * self.local_batch
        return flat[:usable].reshape(self.steps_per_epoch, self.local_batch, 2)

    def global_id(self, shard_id: int, record_id: int) -> int:
        return int(self.shard_base[shard_id]) + int(record_id)

    def cursor_for_step(self, epoch: int, step: int) -> Dict:
        """The resume cursor after `step` consumed batches of `epoch`: where
        in the ordered shard list the NEXT record comes from. Stored in the
        checkpoint sidecar by train/loop.py; the resume itself re-derives the
        position from (seed, epoch, step) and uses this record to detect a
        drifted shard set."""
        shard_cursor, record_offset = self._locate(epoch, step)
        order = self.shard_order(epoch)
        shard_name = (self.shards[order[shard_cursor]]["name"]
                      if shard_cursor < len(order) else None)
        return {
            "epoch": int(epoch),
            "step": int(step),
            "shard_cursor": int(shard_cursor),
            "record_offset": int(record_offset),
            "shard": shard_name,
            "process_index": int(self.process_index),
            "process_count": int(self.process_count),
        }

    def _locate(self, epoch: int, step: int) -> Tuple[int, int]:
        """(shard_cursor, record_offset) for consumed position
        p = step * local_batch; shard_cursor == len(order) means the epoch's
        plan is fully consumed."""
        assert 0 <= step <= self.steps_per_epoch, (
            f"step {step} outside epoch of {self.steps_per_epoch} steps")
        p = step * self.local_batch
        order = self.shard_order(epoch)
        for j, shard_id in enumerate(order):
            n = self.record_counts[shard_id]
            if p < n:
                return j, p
            p -= n
        return len(order), 0

    def check_cursor(self, cursor: Dict, epoch: int, step: int) -> None:
        """Validate a sidecar cursor against the position this sampler derives
        for (epoch, step). A mismatch means the shard set, seed, or topology
        changed since the checkpoint — resuming would silently feed different
        records, so fail loudly instead."""
        if int(cursor.get("process_index", self.process_index)) != self.process_index:
            return  # another host's cursor — not comparable to this plan
        expect = self.cursor_for_step(epoch, step)
        for key in ("shard_cursor", "record_offset", "shard"):
            if cursor.get(key) != expect[key]:
                raise RuntimeError(
                    f"stream resume cursor mismatch at epoch {epoch} step "
                    f"{step}: checkpoint recorded {key}="
                    f"{cursor.get(key)!r}, current shard set derives "
                    f"{expect[key]!r} — the shard directory, seed, or "
                    f"topology changed since the checkpoint was written")
