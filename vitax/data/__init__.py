from vitax.data.fake import FakeImageNetDataset  # noqa: F401
from vitax.data.loader import ShardedLoader, build_datasets  # noqa: F401
