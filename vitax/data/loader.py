"""Sharded host input pipeline with device prefetch.

Replaces the reference's DistributedSampler + DataLoader + MpDeviceLoader stack
(reference run_vit_training.py:62-88; SURVEY.md section 2.2):

- `ShardedSampler`    — per-process disjoint index shard with epoch-seeded
                        reshuffle and drop-last (DistributedSampler parity,
                        including the rank::world_size interleaving).
- worker pool         — parallel __getitem__ (decode + augment) on host CPU
                        threads (PIL releases the GIL during JPEG decode).
- `ShardedLoader`     — assembles the *global* batch as one sharded jax.Array
                        via make_array_from_process_local_data and
                        double-buffers device transfer on a background thread
                        (MpDeviceLoader parity: async host->device staging,
                        run_vit_training.py:74,88 — without the implicit
                        mark_step, which has no jit equivalent or need).

There is no per-core process fan-out (xmp.spawn): one process per host feeds
all its local devices through the sharded global array.
"""

from __future__ import annotations

import queue
import threading
import time
import traceback
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Iterator, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding

from vitax import faults
from vitax.config import Config
from vitax.parallel.mesh import batch_pspec


class LoaderWorkerError(RuntimeError):
    """A data-worker (producer-thread) failure, re-raised on the CONSUMING
    host with the worker's own traceback attached. Without this, a dead
    producer just starves the consumer until the watchdog fires a dump with
    no cause in it — the stall is visible, the broken sample is not."""


class _ProducerFailure:
    """Queue envelope for a producer exception + its formatted traceback
    (the traceback object itself must not cross threads via re-raise: the
    consumer's `raise` would show the consumer's stack, not the worker's)."""

    __slots__ = ("exc", "tb")

    def __init__(self, exc: BaseException, tb: str):
        self.exc = exc
        self.tb = tb


class ShardedSampler:
    """Epoch-seeded, per-process index shard (DistributedSampler parity,
    reference run_vit_training.py:62-64,76-78 and set_epoch at :258)."""

    def __init__(self, dataset_len: int, global_batch: int, shuffle: bool,
                 seed: int, process_index: Optional[int] = None,
                 process_count: Optional[int] = None):
        self.dataset_len = dataset_len
        self.global_batch = global_batch
        self.shuffle = shuffle
        self.seed = seed
        self.process_index = jax.process_index() if process_index is None else process_index
        self.process_count = jax.process_count() if process_count is None else process_count
        assert global_batch % self.process_count == 0
        self.local_batch = global_batch // self.process_count
        # drop_last at the global-batch level: identical step count on every
        # process (reference drop_last=True on sampler AND loader, :63-69)
        self.steps_per_epoch = dataset_len // global_batch

    def epoch_indices(self, epoch: int) -> np.ndarray:
        """(steps_per_epoch, local_batch) index matrix for this process."""
        if self.shuffle:
            order = np.random.default_rng(
                np.random.SeedSequence([self.seed, epoch])).permutation(self.dataset_len)
        else:
            order = np.arange(self.dataset_len)
        usable = self.steps_per_epoch * self.global_batch
        order = order[:usable].reshape(self.steps_per_epoch, self.global_batch)
        # rank-interleaved split of each global batch (DistributedSampler's
        # indices[rank::world] layout)
        return order[:, self.process_index::self.process_count]


class ShardedLoader:
    """Iterates global batches as sharded device arrays, with background
    prefetch (double buffering)."""

    def __init__(self, dataset, sampler: ShardedSampler, mesh: Mesh,
                 num_workers: int = 4, prefetch: int = 2):
        self.dataset = dataset
        self.sampler = sampler
        self.mesh = mesh
        self.sharding = NamedSharding(mesh, batch_pspec())
        self.label_sharding = NamedSharding(mesh, batch_pspec())
        self.num_workers = max(num_workers, 1)
        self.prefetch = max(prefetch, 1)
        self.steps_per_epoch = sampler.steps_per_epoch
        self._wait_s = 0.0  # host time the consumer spent blocked on q.get
        self._pool = ThreadPoolExecutor(max_workers=self.num_workers,
                                        thread_name_prefix="vitax-data")

    def consume_wait_s(self) -> float:
        """Seconds the TRAINING THREAD spent blocked waiting for a decoded
        batch since the last call (accumulated around the prefetch-queue get
        in epoch()), then reset. This is the data-starvation signal: device
        step time hides inside JAX's async dispatch, so a loop whose
        sec/iter grows while data_wait_s stays ~0 is compute/comm-bound; one
        whose data_wait_s tracks sec/iter is input-bound. Read by the
        telemetry Recorder once per log step — single-threaded with the
        accumulation (both happen on the consumer thread), so no lock."""
        w = self._wait_s
        self._wait_s = 0.0
        return w

    def _load_local(self, indices: Sequence[int]) -> Dict[str, np.ndarray]:
        if getattr(self.dataset, "use_native", False):
            # whole-batch native path: one GIL-free C++ call, its own thread pool
            images, labels = self.dataset.load_batch(indices, self.num_workers)
            return {"image": images, "label": labels}
        items = list(self._pool.map(self.dataset.__getitem__, indices))
        images = np.stack([it[0] for it in items])
        if images.dtype != np.uint8:  # uint8 = device-side normalization path
            images = images.astype(np.float32)
        labels = np.asarray([it[1] for it in items], np.int32)
        return {"image": images, "label": labels}

    def _to_device(self, local: Dict[str, np.ndarray]) -> Dict[str, jax.Array]:
        # Builds the GLOBAL (B, ...) array from each process's local shard; on a
        # single host this is a plain sharded device_put over the mesh.
        return {
            "image": jax.make_array_from_process_local_data(self.sharding, local["image"]),
            "label": jax.make_array_from_process_local_data(self.label_sharding, local["label"]),
        }

    def epoch(self, epoch: int, start_step: int = 0) -> Iterator[Dict[str, jax.Array]]:
        """Yield device batches for one epoch. `epoch` seeds the shuffle
        (train_sampler.set_epoch parity, reference run_vit_training.py:258)
        and the per-sample augmentation randomness. `start_step` skips the
        first N global batches exactly (the index matrix is a pure function
        of (seed, epoch), so no data is loaded for the skipped steps) —
        step-granular preemption resume (vitax/train/loop.py)."""
        if hasattr(self.dataset, "set_epoch"):
            self.dataset.set_epoch(epoch)
        index_matrix = self.sampler.epoch_indices(epoch)[start_step:]
        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        stop = threading.Event()

        def producer():
            # Host-side work only (decode + stack). ALL JAX dispatch happens on
            # the consumer thread: a second dispatch thread can interleave
            # compiled programs containing collectives and deadlock their
            # rendezvous (observed on XLA:CPU's in-process communicator).
            try:
                for row in index_matrix:
                    if stop.is_set():
                        return
                    faults.fire("loader")  # host-side drill point: a `stall`
                    # here starves the consumer; an `oserror` exercises the
                    # worker-traceback surfacing below
                    q.put(self._load_local(row))
            except BaseException as e:  # surface worker errors to the consumer
                q.put(_ProducerFailure(e, traceback.format_exc()))
            finally:
                q.put(None)

        t = threading.Thread(target=producer, daemon=True, name="vitax-prefetch")
        t.start()
        try:
            while True:
                t_wait = time.monotonic()
                item = q.get()
                self._wait_s += time.monotonic() - t_wait
                if item is None:
                    return
                if isinstance(item, _ProducerFailure):
                    raise LoaderWorkerError(
                        f"data worker failed while producing epoch {epoch}: "
                        f"{type(item.exc).__name__}: {item.exc}\n"
                        f"--- worker traceback (vitax-prefetch thread) ---\n"
                        f"{item.tb}") from item.exc
                # device transfer is async in JAX — this enqueues the copies
                # and returns; compute/transfer overlap still happens
                yield self._to_device(item)
        finally:
            stop.set()
            # Drain until the producer thread actually exits: a producer
            # blocked in q.put never observes `stop` on its own — it needs
            # the consumer to free a slot first. Breaking on the first empty
            # read (the old behavior) races exactly that window: the
            # producer is awake between puts, the queue is momentarily
            # empty, the drain stops, and the next q.put blocks forever —
            # leaking the thread (and with it a reference to the dataset)
            # every time an epoch iterator is abandoned early. Bounded so a
            # wedged worker can't hang shutdown.
            deadline = time.monotonic() + 10.0
            while t.is_alive() and time.monotonic() < deadline:
                try:
                    q.get(timeout=0.05)
                except queue.Empty:
                    pass
            t.join(timeout=max(0.0, deadline - time.monotonic()))

    def close(self):
        # cancel queued decode work, then wait: a shutdown(wait=False) can
        # drop the pool while __getitem__ calls are mid-flight, and their
        # exceptions land in dead futures nobody observes
        try:
            self._pool.shutdown(wait=True, cancel_futures=True)
        except TypeError:  # cancel_futures needs python>=3.9
            self._pool.shutdown(wait=True)


def build_datasets(cfg: Config, mesh: Mesh):
    """Build (train_dataset, train_loader, val_dataset, val_loader)
    (reference build_datasets parity, run_vit_training.py:30-96)."""
    from vitax.data.fake import TRAIN_SPLIT_LEN, VAL_SPLIT_LEN, FakeImageNetDataset

    world = jax.process_count()
    assert cfg.batch_size % world == 0, (
        f"batch_size {cfg.batch_size} not divisible by process count {world}")

    if cfg.data_format == "stream":
        # .vtxshard streaming containers (vitax/data/stream/): same return
        # contract, sharded-streaming input plane (config.validate() already
        # rejected stream+fake_data)
        from vitax.data.stream import build_stream_datasets
        return build_stream_datasets(cfg, mesh)

    if cfg.fake_data:
        train_ds = FakeImageNetDataset(cfg.image_size, TRAIN_SPLIT_LEN)
        val_ds = FakeImageNetDataset(cfg.image_size, VAL_SPLIT_LEN)
    else:
        from vitax.data.imagefolder import ImageFolderDataset
        from vitax.data.transforms import train_transform, val_transform
        import os
        # device_normalize: transforms emit raw uint8 and the jitted step
        # normalizes on-device (step.py:prepare_images)
        norm_on_host = not cfg.device_normalize
        train_ds = ImageFolderDataset(
            os.path.join(cfg.data_dir, "train"),
            train_transform(cfg.image_size, cfg.seed, normalize=norm_on_host))
        val_ds = ImageFolderDataset(
            os.path.join(cfg.data_dir, "val"),
            val_transform(cfg.image_size, normalize=norm_on_host))

    train_sampler = ShardedSampler(len(train_ds), cfg.batch_size, shuffle=True, seed=cfg.seed)
    val_sampler = ShardedSampler(len(val_ds), cfg.batch_size, shuffle=False, seed=cfg.seed)
    train_loader = ShardedLoader(train_ds, train_sampler, mesh, cfg.num_workers,
                                 prefetch=cfg.prefetch_batches)
    val_loader = ShardedLoader(val_ds, val_sampler, mesh, cfg.num_workers,
                               prefetch=cfg.prefetch_batches)
    return train_ds, train_loader, val_ds, val_loader
