"""Fake ImageNet dataset (reference FakeImageNetDataset parity, utils.py:46-55).

Zero-filled images, label 0, real ImageNet split lengths (1,281,167 train /
50,000 val — reference run_vit_training.py:59-60). This is the fixture that
validates the whole distributed graph — compile, collectives, memory — without
any data on disk (reference README.md:76; SURVEY.md section 4).

Images are NHWC (TPU-native layout; XLA convolutions want channels-last),
vs the reference's CHW torch tensors.
"""

from __future__ import annotations

import numpy as np

TRAIN_SPLIT_LEN = 1_281_167
VAL_SPLIT_LEN = 50_000


class FakeImageNetDataset:
    def __init__(self, image_size: int, length: int):
        self.image_size = image_size
        self.length = length

    def __getitem__(self, idx: int):
        s = self.image_size
        return np.zeros((s, s, 3), np.float32), 0

    def __len__(self) -> int:
        return self.length

    def __repr__(self) -> str:
        return f"FakeImageNetDataset(image_size={self.image_size}, length={self.length})"
