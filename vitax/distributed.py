"""Multi-host runtime: initialization, barriers, host-level reductions.

TPU-native replacement for the reference's process/cluster layer (SURVEY.md
sections 2.4, L1/L2):
- xmp.spawn per-core processes (reference run_vit_training.py:364)  ->  ONE
  process per host; jit spans all local devices; nothing to fork.
- XRT mesh service control plane (xm.rendezvous at :224,230,241,252;
  xm.mesh_reduce at :205,315)  ->  JAX coordination service
  (jax.distributed.initialize) + multihost_utils collective barriers.
- Data-plane collectives stay inside the compiled step (GSPMD over ICI).
"""

from __future__ import annotations

import os
from typing import Any

import jax

_initialized = False


def maybe_initialize() -> None:
    """Initialize the JAX distributed coordination service when running
    multi-host (TPU pod metadata autodetects coordinator/rank). Single-host
    (and CPU test) runs skip it — jit still spans all local devices.

    Replaces the reference's xla_dist + per-core xmp.spawn bring-up
    (reference README.md:99-101, run_vit_training.py:364).
    """
    global _initialized
    if _initialized:
        return
    # Explicit bring-up: JAX_COORDINATOR_ADDRESS + JAX_NUM_PROCESSES +
    # JAX_PROCESS_ID work on any transport (CPU clusters, tests — jax's
    # auto-detection only covers SLURM/MPI/TPU-metadata/K8s).
    coord = os.environ.get("JAX_COORDINATOR_ADDRESS")
    nproc = os.environ.get("JAX_NUM_PROCESSES")
    pid = os.environ.get("JAX_PROCESS_ID")
    tpu_pod = (int(os.environ.get("TPU_WORKER_COUNT", "1")) > 1
               or bool(os.environ.get("MEGASCALE_COORDINATOR_ADDRESS")))
    if coord and not ((nproc or "").isdigit() and (pid or "").isdigit()):
        if tpu_pod:
            # a pod that exports the coordinator address but leaves process
            # count/id to TPU metadata: let auto-detection fill them in
            import warnings
            warnings.warn(
                "JAX_COORDINATOR_ADDRESS is set without JAX_NUM_PROCESSES/"
                "JAX_PROCESS_ID; using TPU metadata auto-detection instead")
            coord = None
        else:
            raise ValueError(
                "JAX_COORDINATOR_ADDRESS is set but JAX_NUM_PROCESSES="
                f"{nproc!r} / JAX_PROCESS_ID={pid!r} are missing or not "
                "integers — all three are required for explicit multi-process "
                "bring-up (otherwise every process would silently train "
                "standalone on the full dataset)")
    if coord:
        if "cpu" in os.environ.get("JAX_PLATFORMS", "").lower():
            # CPU multi-process runs (clusters, the 2-process test harness)
            # need a host collectives transport: jax's default ("none")
            # fails every cross-process computation on the CPU backend with
            # "Multiprocess computations aren't implemented". Gloo ships in
            # jaxlib; TPU pods never reach this branch (ICI/DCN transports).
            try:
                jax.config.update("jax_cpu_collectives_implementation",
                                  "gloo")
            except Exception as e:  # noqa: BLE001 — a jaxlib without gloo
                import warnings
                warnings.warn(
                    f"could not select the gloo CPU collectives transport "
                    f"({type(e).__name__}: {e}); cross-process CPU "
                    f"collectives will likely fail")
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=int(nproc),
                                   process_id=int(pid))
    elif tpu_pod:
        # TPU pod: worker topology comes from env/metadata.
        jax.distributed.initialize()
    _initialized = True


def barrier(tag: str) -> None:
    """Named cross-host barrier (xm.rendezvous parity, reference
    run_vit_training.py:224,230,241,252). No-op single-host."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(tag)


def broadcast_from_process0(value: int) -> int:
    """Host-level scalar broadcast: every process adopts process 0's value.
    Used to agree on the auto-resume epoch when a non-atomic shared store
    (e.g. GCS fuse) could give hosts different directory listings. No-op
    single-host. (The reference's xm.mesh_reduce host plane, SURVEY.md
    section 2.4, is otherwise compiled into the step as in-graph reductions.)"""
    if jax.process_count() == 1:
        return value
    from jax.experimental import multihost_utils
    import numpy as np
    return int(multihost_utils.broadcast_one_to_all(np.int64(value)))


def any_across_processes(value: bool) -> bool:
    """True iff ANY process passes True. Collective: when process_count > 1
    every process must call this at the same point (the train loop calls it
    on a fixed step cadence). Used for preemption agreement — a SIGTERM
    delivered to a subset of hosts must still stop ALL hosts at the same step,
    or the preemption save's collectives would interleave with other hosts'
    train steps and deadlock. Free single-host."""
    if jax.process_count() == 1:
        return value
    from jax.experimental import multihost_utils
    import numpy as np
    return bool(np.max(multihost_utils.process_allgather(
        np.int32(bool(value)))))


def orderly_shutdown() -> None:
    """Coordinated multi-process exit for a clean (exit 0) run: barrier so
    every host finishes its teardown first, disconnect from the coordination
    service, then _exit(0) to skip C++ static destructors.

    Without this, whichever rank exits first tears down the coordination
    service under its peers: their background PollForError threads turn a
    COMPLETED run into SIGABRT, and XLA CPU's destructor-time thread races
    can corrupt the heap after all state is already committed. An elastic
    drain (vitax/arbiter) runs this gauntlet on every resize — the agreed
    preemption contract is "every rank exits 0", so the exit itself must be
    as coordinated as the save. No-op single-process."""
    if jax.process_count() <= 1:
        return
    import sys
    barrier("vitax_orderly_shutdown")
    try:
        jax.distributed.shutdown()  # its own barrier: all ranks disconnect
    except Exception as e:  # noqa: BLE001 — a dirty disconnect must not fail a committed run
        print(f"vitax.distributed: shutdown after barrier failed "
              f"({type(e).__name__}: {e}); exiting anyway", file=sys.stderr)
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(0)


def or_across_processes(value: int) -> int:
    """Bitwise OR of a small non-negative host int over all processes — the
    control plane's word-agreement fold (vitax/train/control.py): every
    host's raised bits survive into the one agreed word every host sees
    (a max fold would drop bits: max(PREEMPT, ESCALATE) keeps only one).
    Same collective cost (one tiny allgather) and same call-discipline as
    any_across_processes, which it generalizes. Free single-host."""
    if jax.process_count() == 1:
        return int(value)
    from jax.experimental import multihost_utils
    import numpy as np
    return int(np.bitwise_or.reduce(multihost_utils.process_allgather(
        np.int64(int(value)))))
