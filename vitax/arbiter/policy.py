"""Borrow/return policy: when does serve get a training host back?

Same two chatter guards as the fleet autoscaler (vitax/serve/fleet/
autoscale.py), composed at the pod level: a `dwell_s` streak requirement
so traffic blips never move a host, and a `cooldown_s` dead time after
every executed action so one borrow's consequences (warmup, admission
relaxing toward the new capacity) are observed before the next decision.
Inputs are signals the stack already emits — the fleet's shed rate and
predicted-wait overshoot (autoscaler signal definitions), explicit
`request_capacity` escalations from a maxed-out autoscaler, and the
train job's step telemetry (a stalled train job is never shrunk: a drain
needs the step loop alive to reach its preemption checkpoint).

Three modes (`--arbiter_policy`):

  train_priority  borrow ONLY on explicit autoscaler escalation backed
                  by live pressure; return as soon as pressure clears
                  (quiet dwell = dwell_s).
  serve_priority  borrow on any sustained pressure signal; hold borrowed
                  hosts through lulls (quiet dwell = 4x dwell_s).
  slo_bounded     borrow when the SLO is at risk (shed rate / predicted
                  wait / escalation); return after a 2x-dwell quiet
                  streak — the middle ground and the default.

Pure state machine: `tick(signals, counts, borrowed, now)` takes every
input as an argument and returns a Decision; no clock reads, no I/O —
unit-tested socketless with an injected `now` exactly like
Autoscaler.tick(now=...).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

POLICIES = ("train_priority", "serve_priority", "slo_bounded")

DEFAULT_DWELL_S = 3.0
DEFAULT_COOLDOWN_S = 10.0
DEFAULT_SHED_RATE_PER_S = 1.0

# quiet-dwell multiple per policy: how long pressure must stay clear
# before a borrowed host goes back to training
_QUIET_MULT = {"train_priority": 1.0, "slo_bounded": 2.0,
               "serve_priority": 4.0}


@dataclass(frozen=True)
class Decision:
    """One tick's verdict. action is "borrow", "return", or None; deny is
    True when a sustained borrow demand was REFUSED (floor, cooldown,
    stalled train job) — the daemon surfaces those as deny events so a
    starved fleet is visible, not silent."""

    action: Optional[str]
    reason: str
    deny: bool = False


class ArbiterPolicy:
    """Hysteretic borrow/return decisions; all state is tick-local."""

    def __init__(self, policy: str = "slo_bounded",
                 min_train_hosts: int = 1,
                 dwell_s: float = DEFAULT_DWELL_S,
                 cooldown_s: float = DEFAULT_COOLDOWN_S,
                 shed_rate_per_s: float = DEFAULT_SHED_RATE_PER_S,
                 quiet_dwell_s: Optional[float] = None):
        assert min_train_hosts >= 1, min_train_hosts
        assert dwell_s >= 0 and cooldown_s >= 0, (dwell_s, cooldown_s)
        assert shed_rate_per_s > 0, shed_rate_per_s
        self.min_train_hosts = min_train_hosts
        self.dwell_s = dwell_s
        self.cooldown_s = cooldown_s
        self.shed_rate_per_s = shed_rate_per_s
        self._explicit_quiet = quiet_dwell_s
        self._pressure_since: Optional[float] = None
        self._quiet_since: Optional[float] = None
        self._cooldown_until = 0.0
        self.set_policy(policy)

    def set_policy(self, policy: str) -> None:
        """Switch modes (POST /policy); hysteresis streaks reset so the new
        mode earns its own dwell instead of inheriting the old streak."""
        assert policy in POLICIES, policy
        self.policy = policy
        self.quiet_dwell_s = (self._explicit_quiet
                              if self._explicit_quiet is not None
                              else self.dwell_s * _QUIET_MULT[policy])
        self._pressure_since = None
        self._quiet_since = None

    # -- signal folding -------------------------------------------------------

    def _pressure(self, signals: dict) -> Optional[str]:
        """Which borrow signal fires, or None. `signals` keys (all
        optional): shed_rate_per_s, predicted_wait_overshoot (bool),
        escalations (request_capacity calls since last tick)."""
        escalated = int(signals.get("escalations", 0)) > 0
        shed = (float(signals.get("shed_rate_per_s", 0.0))
                >= self.shed_rate_per_s)
        wait = bool(signals.get("predicted_wait_overshoot", False))
        if self.policy == "train_priority":
            # the fleet must ASK (escalation) and the ask must be backed by
            # live pressure — train_priority never moves on raw signals
            if escalated and (shed or wait):
                return "escalation"
            return None
        if escalated:
            return "escalation"
        if shed:
            return "shed_rate"
        if wait:
            return "predicted_wait"
        return None

    # -- decision -------------------------------------------------------------

    def tick(self, signals: dict, counts: dict, borrowed: int,
             now: float) -> Decision:
        """One evaluation. `counts` is HostLedger.counts(); `borrowed` is
        how many hosts the daemon currently holds on loan to serve."""
        why = self._pressure(signals)
        if why is not None:
            self._quiet_since = None
            if self._pressure_since is None:
                self._pressure_since = now
            if now - self._pressure_since < self.dwell_s:
                return Decision(None, "dwell")
            # sustained demand: borrow, or say loudly why not
            if counts.get("train", 0) - 1 < self.min_train_hosts:
                return Decision(None, "min_train_hosts", deny=True)
            if not signals.get("train_progressing", True):
                # a wedged step loop cannot reach its preemption save; a
                # drain now would hang, not hand off
                return Decision(None, "train_stalled", deny=True)
            if now < self._cooldown_until:
                return Decision(None, "cooldown", deny=True)
            return Decision("borrow", why)
        self._pressure_since = None
        if borrowed <= 0:
            return Decision(None, "idle")
        if self._quiet_since is None:
            self._quiet_since = now
        if now - self._quiet_since < self.quiet_dwell_s:
            return Decision(None, "quiet_dwell")
        if not signals.get("train_progressing", True):
            # the return's re-expand drains the current generation too —
            # same preemption-save requirement as a borrow
            return Decision(None, "train_stalled", deny=True)
        if now < self._cooldown_until:
            return Decision(None, "cooldown")
        return Decision("return", "pressure_cleared")

    def action_taken(self, now: float) -> None:
        """An executed borrow/return opens the cooldown window and resets
        both streaks (the daemon calls this, not tick — a decision the
        executor failed to carry out must not burn the cooldown)."""
        self._cooldown_until = now + self.cooldown_s
        self._pressure_since = None
        self._quiet_since = None

    def snapshot(self) -> dict:
        return {"policy": self.policy,
                "min_train_hosts": self.min_train_hosts,
                "dwell_s": self.dwell_s,
                "quiet_dwell_s": self.quiet_dwell_s,
                "cooldown_s": self.cooldown_s,
                "cooldown_until": self._cooldown_until}
