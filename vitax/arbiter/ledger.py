"""Leased host ledger: who owns each chip-bearing host right now.

The ledger is the arbiter's single source of truth, and the only state
that must survive an arbiter restart: every host in the pod maps to an
owner in {train, serve, free}, and every ownership flip is a new LEASE
recorded with the monotonically increasing ledger version that granted
it. Persistence is atomic (tmp + os.replace into place) and every
mutation persists before it is visible to readers, so a killed arbiter
recovers exactly the last granted state — a borrow that died between the
train shrink and the fleet adopt is re-derived from the ledger ("host h1
is serve-owned but has no replica url") instead of being forgotten.

No sockets, no threads of its own: callers (the Arbiter daemon and its
HTTP handlers) share one lock here. `clock` is injectable so lease
timestamps are deterministic under test.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

LEDGER_SCHEMA = 1
OWNERS = ("train", "serve", "free")


class HostLedger:
    """Versioned host -> owner leases with atomic persistence."""

    def __init__(self, hosts: Sequence[str] = (), owner: str = "train",
                 path: str = "",
                 clock: Callable[[], float] = time.time):
        assert owner in OWNERS, owner
        self.path = path
        self._clock = clock
        self._lock = threading.Lock()
        # guarded by _lock:
        self.version = 0
        self._hosts: Dict[str, dict] = {}
        recovered = self._load() if path else False
        with self._lock:
            for h in hosts:
                if h not in self._hosts:
                    self.version += 1
                    self._hosts[h] = {"owner": owner,
                                      "lease_version": self.version,
                                      "since": self._clock()}
            if not recovered or hosts:
                self._persist()
        self.recovered = recovered

    # -- persistence ----------------------------------------------------------

    def _load(self) -> bool:
        """Recover the last persisted ledger; False when none exists (or it
        is unreadable — a torn tmp never lands, so an unreadable file means
        external damage and the arbiter starts fresh, loudly)."""
        try:
            with open(self.path, encoding="utf-8") as f:
                data = json.load(f)
        except (OSError, ValueError):
            return False
        hosts = data.get("hosts")
        version = data.get("version")
        if not isinstance(hosts, dict) or not isinstance(version, int):
            return False
        with self._lock:
            self.version = version
            self._hosts = {
                str(h): {"owner": (e.get("owner")
                                   if e.get("owner") in OWNERS else "free"),
                         "lease_version": int(e.get("lease_version", 0)),
                         "since": float(e.get("since", 0.0))}
                for h, e in hosts.items() if isinstance(e, dict)}
        return True

    def _persist(self) -> None:
        """Atomic write-into-place; caller holds _lock. A crash between tmp
        write and replace leaves the previous ledger intact."""
        if not self.path:
            return
        payload = {"schema": LEDGER_SCHEMA, "version": self.version,
                   "hosts": self._hosts}
        tmp = f"{self.path}.tmp.{os.getpid()}"
        os.makedirs(os.path.dirname(os.path.abspath(self.path)),
                    exist_ok=True)
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    # -- leases ---------------------------------------------------------------

    def assign(self, host: str, owner: str) -> dict:
        """Grant `host` to `owner` under a fresh lease; persists before
        returning, so a crash after assign() never forgets the flip."""
        assert owner in OWNERS, owner
        with self._lock:
            if host not in self._hosts:
                raise KeyError(f"unknown host {host!r}")
            self.version += 1
            entry = {"owner": owner, "lease_version": self.version,
                     "since": self._clock()}
            self._hosts[host] = entry
            self._persist()
            return dict(entry, host=host, version=self.version)

    def owner_of(self, host: str) -> Optional[str]:
        with self._lock:
            entry = self._hosts.get(host)
            return entry["owner"] if entry else None

    def hosts_owned(self, owner: str) -> List[str]:
        """Hosts under `owner`, oldest lease first — the borrow path picks
        the NEWEST train lease (last element) so repeated borrows peel from
        one end and returns restore in reverse order."""
        with self._lock:
            held = [(e["lease_version"], h)
                    for h, e in self._hosts.items() if e["owner"] == owner]
        return [h for _, h in sorted(held)]

    def counts(self) -> Dict[str, int]:
        with self._lock:
            out = {o: 0 for o in OWNERS}
            for e in self._hosts.values():
                out[e["owner"]] += 1
            return out

    def snapshot(self) -> dict:
        with self._lock:
            return {"schema": LEDGER_SCHEMA, "version": self.version,
                    "hosts": {h: dict(e) for h, e in self._hosts.items()}}
