"""CLI entry: python -m vitax.arbiter — run the chip-ledger arbiter.

    python -m vitax.arbiter \\
        --hosts h0,h1 --ledger_path /pod/ledger.json \\
        --arbiter_port 8200 --arbiter_policy slo_bounded \\
        --min_train_hosts 1 \\
        --fleet_url http://router:8000 \\
        --agent_urls h1=http://h1:8100 \\
        --serve_argv "--npz /ckpts/model.npz --serve_quant_dtype int8" \\
        --metrics_dir /pod/metrics \\
        -- python run_vit_training.py --fake_data ...

Everything after `--` is the training command; the arbiter launches one
process of it per train-owned host (supervise.topology_env builds the
bring-up env) and resizes the job through drain-and-relaunch on every
borrow/return. Without a training command the arbiter only keeps the
ledger and serve side (training managed externally). `--agent_urls`
names the placement agent on each borrowable host; a borrowed host
without one still flips the ledger and shrinks training, it just cannot
warm a replica. SIGTERM/SIGINT stop the loop, return nothing, and drain
the training job cleanly — the persisted ledger carries the loan state
into the next arbiter launch.
"""

from __future__ import annotations

import argparse
import shlex
import signal
import sys
import threading

from vitax.arbiter.daemon import (Arbiter, FleetSignals, JsonlRecorder,
                                  TrainDirector, default_http_json,
                                  start_arbiter, stop_arbiter)
from vitax.arbiter.ledger import HostLedger
from vitax.arbiter.policy import POLICIES, ArbiterPolicy


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m vitax.arbiter",
        description="chip-ledger arbiter for co-located train + serve")
    p.add_argument("--hosts", type=str, required=True,
                   help="comma-separated host names in the pod; all start "
                        "train-owned unless the ledger file says otherwise")
    p.add_argument("--ledger_path", type=str, default="",
                   help="ledger persistence file (restart recovers leases); "
                        "empty = in-memory only")
    p.add_argument("--arbiter_port", type=int, default=8200,
                   help="HTTP port for GET /ledger, GET /metrics, "
                        "POST /request, gated POST /policy (0 = ephemeral)")
    p.add_argument("--arbiter_policy", type=str, default="slo_bounded",
                   choices=list(POLICIES),
                   help="borrow/return mode (see vitax/arbiter/policy.py)")
    p.add_argument("--min_train_hosts", type=int, default=1,
                   help="training never shrinks below this many hosts")
    p.add_argument("--arbiter_dwell_s", type=float, default=3.0,
                   help="pressure must hold this long before a borrow")
    p.add_argument("--arbiter_cooldown_s", type=float, default=10.0,
                   help="dead time after every executed borrow/return")
    p.add_argument("--arbiter_interval_s", type=float, default=1.0,
                   help="seconds between decision ticks")
    p.add_argument("--arbiter_allow_admin", action="store_true",
                   help="arm POST /policy (runtime policy flips); NEVER "
                        "enable on an internet-reachable port")
    p.add_argument("--fleet_url", type=str, default="",
                   help="fleet router base URL: pressure signals are pulled "
                        "from /metrics, borrowed replicas handed over via "
                        "POST /fleet/adopt and drained via POST /fleet/release")
    p.add_argument("--agent_urls", type=str, default="",
                   help="comma-separated host=url placement-agent pairs for "
                        "borrowable hosts (python -m vitax.serve.fleet.agent)")
    p.add_argument("--serve_argv", type=str, default="",
                   help="replica argv (shell-quoted) provisioned on a "
                        "borrowed host, e.g. '--npz m.npz "
                        "--serve_quant_dtype int8'")
    p.add_argument("--metrics_dir", type=str, default="",
                   help="write kind:\"arbiter\" events to "
                        "<metrics_dir>/metrics.jsonl")
    p.add_argument("--train_grace_s", type=float, default=120.0,
                   help="drain window per resize: SIGTERM -> joint "
                        "checkpoint -> exit 0, hard-kill after this")
    p.add_argument("--train_log_dir", type=str, default="",
                   help="per-process training logs (train_g<gen>_p<rank>"
                        ".log); empty = inherit stdout")
    return p


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    train_argv: list = []
    if "--" in argv:
        split = argv.index("--")
        argv, train_argv = argv[:split], argv[split + 1:]
    ns = build_parser().parse_args(argv)

    hosts = [h.strip() for h in ns.hosts.split(",") if h.strip()]
    assert hosts, "--hosts must name at least one host"
    agent_urls = {}
    for pair in ns.agent_urls.split(","):
        if pair.strip():
            host, _, url = pair.partition("=")
            assert url, f"--agent_urls entry {pair!r} is not host=url"
            agent_urls[host.strip()] = url.strip().rstrip("/")

    ledger = HostLedger(hosts, owner="train", path=ns.ledger_path)
    policy = ArbiterPolicy(ns.arbiter_policy,
                           min_train_hosts=ns.min_train_hosts,
                           dwell_s=ns.arbiter_dwell_s,
                           cooldown_s=ns.arbiter_cooldown_s)
    recorder = JsonlRecorder(ns.metrics_dir) if ns.metrics_dir else None

    train = None
    if train_argv:
        train = TrainDirector(train_argv, term_grace_s=ns.train_grace_s,
                              log_dir=ns.train_log_dir)

    serve_argv = shlex.split(ns.serve_argv)
    placed = {}  # host -> (client, remote replica name)
    placed_lock = threading.Lock()

    def provision(host: str):
        from vitax.serve.fleet.placement import PlacementClient
        if host not in agent_urls:
            return None  # ledger-only borrow: no agent to warm a replica on
        client = PlacementClient(agent_urls[host])
        out = client.provision(serve_argv, name=f"borrow_{host}")
        with placed_lock:
            placed[host] = (client, out["name"])
        return out["url"]

    def release(host: str, url: str) -> None:  # noqa: ARG001 — seam signature
        with placed_lock:
            entry = placed.pop(host, None)
        if entry is not None:
            client, remote_name = entry
            client.release(remote_name)

    fleet_adopt = fleet_release = None
    signals_fn = None
    if ns.fleet_url:
        fleet_url = ns.fleet_url.rstrip("/")
        signals_fn = FleetSignals(fleet_url)
        def _fleet_adopt(url: str) -> None:
            default_http_json(fleet_url + "/fleet/adopt", {"url": url}, 30.0)

        def _fleet_release(url: str) -> None:
            # drain-to-zero on the router side can take a while
            default_http_json(fleet_url + "/fleet/release", {"url": url},
                              60.0)

        fleet_adopt, fleet_release = _fleet_adopt, _fleet_release

    arbiter = Arbiter(ledger, policy, train=train, provision=provision,
                      release=release, fleet_adopt=fleet_adopt,
                      fleet_release=fleet_release, signals_fn=signals_fn,
                      recorder=recorder, interval_s=ns.arbiter_interval_s,
                      allow_admin=ns.arbiter_allow_admin)

    if train is not None:
        train.start(max(len(ledger.hosts_owned("train")), 1))
    httpd = start_arbiter(arbiter, ns.arbiter_port)
    ledger_state = "recovered" if ledger.recovered else "fresh"
    print(f"arbiter: on :{httpd.server_address[1]}, "
          f"{len(hosts)} hosts ({ledger_state} ledger), "
          f"policy {ns.arbiter_policy}, min_train_hosts "
          f"{ns.min_train_hosts}, fleet {ns.fleet_url or 'off'}, "
          f"train {'managed' if train else 'external'}", flush=True)

    stop = threading.Event()

    def _on_signal(signum, frame):  # noqa: ARG001 — handler signature
        stop.set()

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, _on_signal)
        except ValueError:
            pass  # not the main thread (embedded use)
    while not stop.wait(timeout=0.5):
        pass
    print("arbiter: shutting down (loop first, then train drain)",
          flush=True)
    stop_arbiter(httpd, arbiter)
    if train is not None:
        train.stop()
    if recorder is not None:
        recorder.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
