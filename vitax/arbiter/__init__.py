"""vitax.arbiter — chip-ledger arbiter for co-located train + serve.

One pod, two tenants: the FSDP training job (vitax/supervise.py restart
contract, vitax/train/control.py agreed preemption, PR 11 peer-replicated
elastic resume) and the serving fleet (PR 17 autoscaler + placement
agents). Neither owns the pod's chips, so before this subsystem a serve
surge could only shed — the fleet's autoscaler had nowhere to grow once
every serve-owned host was full. The arbiter closes that gap: it owns a
leased host ledger (ledger.py), decides borrow/return under a hysteretic
policy (policy.py), and speaks BOTH sides' existing contracts to move a
host between tenants (daemon.py):

  borrow: drain training to a joint preemption checkpoint (SIGTERM ->
  vitax/train/preempt.py -> committed save + clean exit 0), relaunch at
  N - k processes (elastic resume restores from surviving peer stores in
  seconds, zero Orbax reads), provision an int8 replica on the freed
  host via the placement agent's POST /provision, and hand its URL to
  the fleet router's POST /fleet/adopt.

  return: POST /fleet/release to the router (retire -> drain-to-zero),
  POST /release to the agent (SIGTERM-drain the replica process), then
  re-expand training back to N.

`python -m vitax.arbiter` runs the daemon; GET /ledger, GET /metrics and
the gated POST /policy are its surface. Everything is seam-injected
(clock, spawn, transport, fleet/agent callables) so the whole state
machine unit-tests socketless like tests/test_autoscale.py.
"""

from vitax.arbiter.ledger import OWNERS, HostLedger          # noqa: F401
from vitax.arbiter.policy import ArbiterPolicy, Decision     # noqa: F401
from vitax.arbiter.daemon import (                           # noqa: F401
    Arbiter, TrainDirector, start_arbiter, stop_arbiter)
