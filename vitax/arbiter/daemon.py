"""The arbiter daemon: ledger + policy + both tenants' contracts.

`Arbiter.tick()` is the whole control loop: fold the fleet's pressure
signals with any `request_capacity` escalations, ask the policy for a
verdict, and execute it through seams —

  train side   `TrainDirector`: the training job's processes, one per
               train-owned host. A borrow drains them through the
               agreed-preemption path (SIGTERM -> vitax/train/preempt.py
               -> joint committed checkpoint -> exit 0) and relaunches
               at N - k with the bring-up env rebuilt
               (supervise.topology_env); elastic resume + peer
               replication make that a seconds-long handoff. A return
               re-expands the same way.
  serve side   `provision(host) -> url` / `release(host, url)` speak the
               placement agent's POST /provision / POST /release, and
               `fleet_adopt(url)` / `fleet_release(url)` the router's
               POST /fleet/adopt / POST /fleet/release, so the running
               fleet routes to (and later drains) the borrowed replica.

Every seam is injectable (clock, spawn, transport, the four callables)
so the full borrow/return state machine unit-tests socketless
(tests/test_arbiter.py), exactly like the autoscaler. Failures roll
back: a borrow that dies between the train shrink and the fleet adopt
restores the ledger and re-expands training, then surfaces as a
borrow_failed event — the ledger never claims a state the pod is not in.

Threading (VTX200 discipline): one ticker thread plus the HTTP server's
handler threads. `_lock` guards the borrowed map, counters and policy
state; the slow tenant calls (drain, provision, transport) all run
OUTSIDE it, so /ledger and /metrics stay responsive mid-borrow.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Sequence

from vitax.arbiter.ledger import HostLedger
from vitax.arbiter.policy import POLICIES, ArbiterPolicy

DEFAULT_INTERVAL_S = 1.0
DEFAULT_TRAIN_GRACE_S = 120.0
DEFAULT_TRANSPORT_TIMEOUT_S = 30.0
EVENT_KIND = "arbiter"


def free_port() -> int:
    """An OS-assigned free TCP port (coordinator relaunches need a fresh
    one: the old coordinator socket may linger in TIME_WAIT)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def default_http_json(url: str, payload: Optional[dict],
                      timeout: float) -> dict:
    data = (json.dumps(payload).encode("utf-8")
            if payload is not None else None)
    req = urllib.request.Request(
        url, data=data,
        headers={"Content-Type": "application/json"} if data else {})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.load(resp)


class JsonlRecorder:
    """Schema-1 JSONL event sink with no jax/telemetry import — the
    arbiter is a control-plane process like the supervisor, and stays as
    light (vitax/supervise.py keeps the same literal for the same
    reason). Thread-safe: handler threads and the ticker both emit."""

    SCHEMA_VERSION = 1  # matches vitax.telemetry.record.SCHEMA_VERSION

    def __init__(self, metrics_dir: str):
        self.path = os.path.join(metrics_dir, "metrics.jsonl")
        os.makedirs(metrics_dir, exist_ok=True)
        self._lock = threading.Lock()

    def event(self, kind: str, **payload) -> None:
        record = {"schema": self.SCHEMA_VERSION, "time": time.time(),
                  "kind": kind, "rank": 0, **payload}
        line = json.dumps(record, sort_keys=True) + "\n"
        try:
            with self._lock:
                with open(self.path, "a", encoding="utf-8") as f:
                    f.write(line)
        except OSError as e:
            print(f"[vitax.arbiter] cannot write {kind} event ({e})",
                  file=sys.stderr, flush=True)

    def close(self) -> None:
        pass


class TrainDirector:
    """The training job's processes, resized by draining and relaunching.

    There is deliberately no in-place membership change: the train job's
    topology flips through the contract the stack already trusts —
    SIGTERM every process (the control plane's agreed preemption commits
    one joint checkpoint and every rank exits 0), then spawn the new
    count with supervise.topology_env and let elastic resume + the peer
    stores bring the smaller (or larger) pod back in seconds. `spawn`
    and `sleep` are injectable so resize logic unit-tests on fakes."""

    def __init__(self, child_argv: Sequence[str],
                 term_grace_s: float = DEFAULT_TRAIN_GRACE_S,
                 env: Optional[dict] = None, log_dir: str = "",
                 spawn: Optional[Callable] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 port_fn: Callable[[], int] = free_port):
        from vitax.supervise import ensure_auto_resume
        self.child_argv = ensure_auto_resume(list(child_argv))
        self.term_grace_s = term_grace_s
        self.base_env = dict(os.environ if env is None else env)
        self.log_dir = log_dir
        self._spawn = spawn or self._default_spawn
        self._sleep = sleep
        self._port_fn = port_fn
        self._lock = threading.Lock()
        # guarded by _lock:
        self._procs: List[object] = []
        self._generation = 0
        self.resizes_total = 0
        # wall-clock of the newest generation's launch (operator
        # observability only — the arbiter's booting-rank gate keeps its
        # own per-resize stamp in its own clock domain, Arbiter._gen_start_t)
        self.last_start_t: Optional[float] = None

    def _default_spawn(self, argv: Sequence[str], env: dict, tag: str):
        out = None
        if self.log_dir:
            os.makedirs(self.log_dir, exist_ok=True)
            out = open(os.path.join(self.log_dir, f"train_{tag}.log"), "ab")
        try:
            return subprocess.Popen(list(argv), env=env, stdout=out,
                                    stderr=subprocess.STDOUT if out else None)
        finally:
            if out is not None:
                out.close()  # the child holds its own fd from here

    @property
    def process_count(self) -> int:
        with self._lock:
            return len(self._procs)

    def alive(self) -> int:
        with self._lock:
            procs = list(self._procs)
        return sum(1 for p in procs if p.poll() is None)

    def healthy(self) -> bool:
        """Every launched process still running (a crashed rank means the
        pod is mid-recovery — not a moment to drain it)."""
        with self._lock:
            n = len(self._procs)
        return n == 0 or self.alive() == n

    def start(self, n: int) -> None:
        from vitax.supervise import topology_env
        assert n >= 1, n
        with self._lock:
            assert not self._procs, "training already running"
            generation = self._generation
            self._generation += 1
        port = self._port_fn() if n > 1 else 0
        procs = []
        for pid in range(n):
            env = topology_env(self.base_env, n, pid, port)
            procs.append(self._spawn(self.child_argv, env,
                                     f"g{generation}_p{pid}"))
        with self._lock:
            self._procs = procs
            self.last_start_t = time.time()

    def drain(self) -> List[Optional[int]]:
        """SIGTERM every process FIRST (the preemption fold needs all
        ranks alive to agree and reach the joint save barrier), then wait
        each out through the grace window."""
        from vitax.supervise import terminate_child
        with self._lock:
            procs, self._procs = self._procs, []
        for p in procs:
            try:
                p.send_signal(15)  # signal.SIGTERM
            except (OSError, ValueError):
                pass
        return [terminate_child(p, self.term_grace_s, sleep=self._sleep)
                for p in procs]

    def resize(self, n: int) -> dict:
        """Drain to a joint checkpoint, relaunch at `n`. Raises if any
        rank failed to exit cleanly — the caller must not hand off a host
        whose training state never committed. A dirty drain still
        relaunches at the ORIGINAL count first (the last committed
        checkpoint is intact): the director must never be left with zero
        training processes, or every later resize computes from 0."""
        was = self.process_count
        codes = self.drain()
        bad = [c for c in codes if c != 0]
        if bad:
            if was >= 1:
                self.start(was)
            raise RuntimeError(
                f"train drain failed: exit codes {codes} (expected all 0); "
                f"relaunched at {was}")
        self.start(n)
        with self._lock:
            self.resizes_total += 1
        return {"from_processes": was, "to_processes": n,
                "exit_codes": codes}

    def stop(self) -> List[Optional[int]]:
        return self.drain()


class Arbiter:
    """Ledger + policy + executor; see module docstring."""

    def __init__(self, ledger: HostLedger, policy: ArbiterPolicy,
                 train: Optional[TrainDirector] = None,
                 provision: Optional[Callable[[str], str]] = None,
                 release: Optional[Callable[[str, str], None]] = None,
                 fleet_adopt: Optional[Callable[[str], None]] = None,
                 fleet_release: Optional[Callable[[str], None]] = None,
                 signals_fn: Optional[Callable[[], dict]] = None,
                 recorder=None, interval_s: float = DEFAULT_INTERVAL_S,
                 clock: Callable[[], float] = time.monotonic,
                 allow_admin: bool = False,
                 telemetry_stale_s: float = 30.0):
        self.ledger = ledger
        self.policy = policy
        self.train = train
        self._provision = provision
        self._release = release
        self._fleet_adopt = fleet_adopt
        self._fleet_release = fleet_release
        self._signals_fn = signals_fn
        self.recorder = recorder
        self.interval_s = interval_s
        self._clock = clock
        self.allow_admin = allow_admin
        self.telemetry_stale_s = telemetry_stale_s
        self._lock = threading.Lock()
        # guarded by _lock:
        self._borrowed: Dict[str, Optional[str]] = {}  # host -> replica url
        self._train_telemetry: Optional[dict] = None   # last POST /telemetry
        # _clock() stamp of the newest train generation launched by an
        # ARBITER resize (None until the first borrow/return). Stamped in
        # _resize_train so it shares a clock domain with observed_at; the
        # director's last_start_t is wall-clock and must never be compared
        # against arbiter timestamps (the default _clock is monotonic).
        self._gen_start_t: Optional[float] = None
        self._escalations = 0
        self._last_deny_reason: Optional[str] = None
        self.borrows_total = 0
        self.returns_total = 0
        self.denies_total = 0
        self.requests_total = 0
        self.last_event: Optional[dict] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- escalation intake (HTTP handler threads) ------------------------------

    def request_capacity(self, reason: str = "") -> dict:
        """A maxed-out autoscaler asking for more chips. Recorded as
        pressure for the next tick; the answer is always asynchronous
        (the borrow itself takes seconds of train drain)."""
        with self._lock:
            self._escalations += 1
            self.requests_total += 1
        self._event(event="request", reason=reason or "escalation",
                    ledger_version=self.ledger.version)
        return {"accepted": True, "status": "pending"}

    def observe_train(self, payload: dict) -> dict:
        """Train-side heartbeat (rank 0's ArbiterReporter, POST
        /telemetry): step/epoch/process_count. A heartbeat newer than
        `telemetry_stale_s` is direct evidence the pod is progressing —
        stronger than the director's process-alive check, which cannot
        see a wedged-but-running rank."""
        record = {k: payload[k] for k in ("step", "epoch", "process_count")
                  if k in payload}
        record["observed_at"] = self._clock()
        with self._lock:
            self._train_telemetry = record
        return {"ok": True}

    def set_policy(self, name: str) -> dict:
        if name not in POLICIES:
            raise ValueError(f"unknown policy {name!r} (one of {POLICIES})")
        with self._lock:
            self.policy.set_policy(name)
        self._event(event="policy_change", policy=name,
                    ledger_version=self.ledger.version)
        return {"policy": name}

    # -- decision loop ---------------------------------------------------------

    def tick(self, now: Optional[float] = None) -> Optional[str]:
        """One evaluation; returns the executed action ("borrow" /
        "return") or None. The background loop calls this every
        `interval_s`; tests drive it directly with an injected now."""
        now = self._clock() if now is None else now
        sig = dict(self._signals_fn() if self._signals_fn else {})
        with self._lock:
            tel = self._train_telemetry
            gen_t = self._gen_start_t
        if tel is not None:
            fresh = now - tel["observed_at"] <= self.telemetry_stale_s
            # a heartbeat only vouches for the generation that posted it:
            # after a resize, the new ranks must report a step of their
            # own before any further drain — a booting rank has no
            # preemption handler installed and would die dirty
            this_gen = gen_t is None or tel["observed_at"] >= gen_t
            if fresh and this_gen:
                sig.setdefault("train_progressing", True)
            elif gen_t is not None:
                sig.setdefault("train_progressing", False)
        if self.train is not None:
            sig.setdefault("train_progressing", self.train.healthy())
        counts = self.ledger.counts()
        repeat = False
        with self._lock:
            sig["escalations"] = sig.get("escalations", 0) + self._escalations
            self._escalations = 0
            decision = self.policy.tick(sig, counts,
                                        len(self._borrowed), now)
            if decision.deny:
                repeat = decision.reason == self._last_deny_reason
                self._last_deny_reason = decision.reason
                if not repeat:
                    self.denies_total += 1
            else:
                self._last_deny_reason = None
        if decision.deny and not repeat:
            extra = {}
            if decision.reason == "train_stalled" and tel is not None:
                # the inputs behind the verdict, so a starved fleet's log
                # says WHY the train job read as stalled
                extra["telemetry_age_s"] = round(now - tel["observed_at"], 3)
                if gen_t is not None:
                    extra["generation_lag_s"] = round(
                        gen_t - tel["observed_at"], 3)
            self._event(event="deny", reason=decision.reason,
                        ledger_version=self.ledger.version, **extra)
            return None
        if decision.action == "borrow":
            return self._do_borrow(decision.reason, now)
        if decision.action == "return":
            return self._do_return(decision.reason, now)
        return None

    def _resize_train(self, n: int) -> None:
        """Every arbiter-driven resize goes through here so the new
        generation is stamped with the arbiter's OWN clock. The stamp is
        in a finally: a dirty drain raises AFTER self-healing by
        relaunching at the old count, which is a new generation too."""
        try:
            self.train.resize(n)
        finally:
            with self._lock:
                self._gen_start_t = self._clock()

    def _do_borrow(self, reason: str, now: float) -> Optional[str]:
        train_hosts = self.ledger.hosts_owned("train")
        if not train_hosts:
            return None
        host = train_hosts[-1]  # newest train lease: peel from one end
        self._event(event="borrow_start", host=host, reason=reason,
                    ledger_version=self.ledger.version)
        t0 = self._clock()
        shrunk = False
        url: Optional[str] = None
        try:
            if self.train is not None:
                self._resize_train(self.train.process_count - 1)
                shrunk = True
            lease = self.ledger.assign(host, "serve")
            if self._provision is not None:
                url = self._provision(host)
            if url and self._fleet_adopt is not None:
                self._fleet_adopt(url)
        except Exception as e:  # noqa: BLE001 — a failed borrow must roll back, not crash the loop
            self._rollback_borrow(host, url, shrunk)
            with self._lock:
                self.policy.action_taken(now)
            self._event(event="borrow_failed", host=host, reason=reason,
                        detail=f"{type(e).__name__}: {e}",
                        ledger_version=self.ledger.version)
            return None
        with self._lock:
            self._borrowed[host] = url
            self.borrows_total += 1
            self.policy.action_taken(now)
            self.last_event = {"event": "borrow", "host": host,
                               "reason": reason, "url": url,
                               "ledger_version": lease["version"],
                               "duration_s": round(self._clock() - t0, 3)}
        self._event(**self.last_event)
        return "borrow"

    def _rollback_borrow(self, host: str, url: Optional[str],
                         shrunk: bool) -> None:
        """Best-effort unwind so the ledger never claims a state the pod
        is not in; each step is independently fail-soft."""
        try:
            if url and self._release is not None:
                self._release(host, url)
        except Exception:  # noqa: BLE001 # vtx: ignore[VTX106] unwind is best-effort by design
            pass
        try:
            if self.ledger.owner_of(host) == "serve":
                self.ledger.assign(host, "train")
        except Exception:  # noqa: BLE001 # vtx: ignore[VTX106] unwind is best-effort by design
            pass
        try:
            if shrunk and self.train is not None:
                self._resize_train(self.train.process_count + 1)
        except Exception as e:  # noqa: BLE001 — training down after a failed borrow is the loudest case
            self._event(event="rollback_failed", host=host,
                        detail=f"{type(e).__name__}: {e}")

    def _do_return(self, reason: str, now: float) -> Optional[str]:
        with self._lock:
            if not self._borrowed:
                return None
            host, url = next(reversed(self._borrowed.items()))
        self._event(event="return_start", host=host, reason=reason,
                    ledger_version=self.ledger.version)
        t0 = self._clock()
        try:
            if url and self._fleet_release is not None:
                self._fleet_release(url)   # router: retire -> drain to zero
            if url and self._release is not None:
                self._release(host, url)   # agent: SIGTERM-drain the process
            lease = self.ledger.assign(host, "train")
            if self.train is not None:
                self._resize_train(self.train.process_count + 1)
        except Exception as e:  # noqa: BLE001 — a failed return keeps the loan; next tick retries
            with self._lock:
                self.policy.action_taken(now)
            self._event(event="return_failed", host=host, reason=reason,
                        detail=f"{type(e).__name__}: {e}",
                        ledger_version=self.ledger.version)
            return None
        with self._lock:
            self._borrowed.pop(host, None)
            self.returns_total += 1
            self.policy.action_taken(now)
            self.last_event = {"event": "return", "host": host,
                               "reason": reason, "url": url,
                               "ledger_version": lease["version"],
                               "duration_s": round(self._clock() - t0, 3)}
        self._event(**self.last_event)
        return "return"

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> None:
        assert self._thread is None, "arbiter loop already running"
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="vitax-arbiter")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(timeout=self.interval_s):
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 — the loop must survive
                print(f"[vitax.arbiter] tick failed: "
                      f"{type(e).__name__}: {e}", file=sys.stderr, flush=True)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            # a tick mid-borrow blocks on the train drain; bound the join
            # by the grace window it could be waiting out
            grace = (self.train.term_grace_s if self.train is not None
                     else DEFAULT_TRAIN_GRACE_S)
            self._thread.join(timeout=grace + self.interval_s * 4 + 5.0)
            self._thread = None

    # -- observability ---------------------------------------------------------

    def metrics(self) -> dict:
        with self._lock:
            out = {"borrows_total": self.borrows_total,
                   "returns_total": self.returns_total,
                   "denies_total": self.denies_total,
                   "requests_total": self.requests_total,
                   "borrowed": dict(self._borrowed),
                   "last_event": self.last_event,
                   "train_telemetry": self._train_telemetry,
                   "policy": self.policy.snapshot()}
        out["ledger"] = self.ledger.snapshot()
        if self.train is not None:
            out["train_processes"] = self.train.process_count
            out["train_alive"] = self.train.alive()
        return out

    def _event(self, **payload) -> None:
        if self.recorder is not None:
            try:
                self.recorder.event(EVENT_KIND, **payload)
            except Exception:  # noqa: BLE001 # vtx: ignore[VTX106] telemetry must not kill arbitration
                pass


class FleetSignals:
    """Pull-based pressure signals from the fleet router's GET /metrics,
    shaped for ArbiterPolicy: shed rate between pulls plus the same
    predicted-wait formula the autoscaler scales on (depth * EWMA service
    over discounted capacity vs the admission deadline). Fail-soft: an
    unreachable fleet reads as zero pressure, never as an error."""

    def __init__(self, fleet_url: str,
                 timeout_s: float = 5.0,
                 http_json: Optional[Callable] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.fleet_url = fleet_url.rstrip("/")
        self.timeout_s = timeout_s
        self._http_json = http_json or default_http_json
        self._clock = clock
        self._last_shed: Optional[int] = None
        self._last_time: Optional[float] = None

    def __call__(self) -> dict:
        try:
            snap = self._http_json(self.fleet_url + "/metrics", None,
                                   self.timeout_s)
        except Exception:  # noqa: BLE001 — an unreachable fleet is zero pressure, not a crash
            return {}
        now = self._clock()
        adm = snap.get("admission") or {}
        fleet = snap.get("fleet") or {}
        shed_total = int(adm.get("shed_total", 0))
        rate = 0.0
        if self._last_shed is not None and now > self._last_time:
            rate = max(shed_total - self._last_shed, 0) \
                / (now - self._last_time)
        self._last_shed, self._last_time = shed_total, now
        overshoot = False
        ewma = adm.get("ewma_service_s")
        deadline = (adm.get("deadline_ms") or 0.0) / 1000.0
        if ewma and deadline > 0:
            frac = adm.get("warming_capacity_frac", 0.5)
            capacity = (fleet.get("ready", 0)
                        + frac * fleet.get("warming", 0))
            predicted = fleet.get("in_flight", 0) * ewma \
                / max(capacity, 1e-9)
            overshoot = predicted >= deadline
        return {"shed_rate_per_s": rate,
                "predicted_wait_overshoot": overshoot}


# -- HTTP surface --------------------------------------------------------------

def _make_handler(arbiter: Arbiter):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # noqa: A003
            pass

        def _reply(self, code: int, payload: dict) -> None:
            body = json.dumps(payload).encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
            if self.path == "/ledger":
                self._reply(200, arbiter.ledger.snapshot())
            elif self.path == "/metrics":
                self._reply(200, arbiter.metrics())
            elif self.path == "/healthz":
                self._reply(200, {"status": "ok"})
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})

        def do_POST(self):  # noqa: N802
            length = int(self.headers.get("Content-Length", 0))
            try:
                payload = json.loads(self.rfile.read(length) or b"{}")
            except ValueError as e:
                self._reply(400, {"error": f"bad JSON body: {e}"})
                return
            if self.path == "/request":
                self._reply(200, arbiter.request_capacity(
                    str(payload.get("reason", ""))))
            elif self.path == "/telemetry":
                self._reply(200, arbiter.observe_train(payload))
            elif self.path == "/policy":
                # gated hard, chaos-endpoint style: flipping the pod's
                # arbitration mode is an operator action, not a default
                if not arbiter.allow_admin:
                    self._reply(403, {"error": "policy endpoint disabled "
                                      "(start with --arbiter_allow_admin)"})
                    return
                try:
                    self._reply(200, arbiter.set_policy(
                        str(payload.get("policy", ""))))
                except ValueError as e:
                    self._reply(400, {"error": str(e)})
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})

    return Handler


def start_arbiter(arbiter: Arbiter, port: int = 0):
    """Bind the arbiter API (background threads) and start the decision
    loop. Returns the httpd; server_address[1] is the bound port."""
    httpd = ThreadingHTTPServer(("0.0.0.0", port), _make_handler(arbiter))
    httpd.daemon_threads = True
    thread = threading.Thread(  # vtx: ignore[VTX205] stop_arbiter's httpd.shutdown() ends serve_forever
        target=httpd.serve_forever, daemon=True, name="vitax-arbiter-http")
    thread.start()
    arbiter.start()
    return httpd


def stop_arbiter(httpd, arbiter: Arbiter) -> None:
    httpd.shutdown()
    httpd.server_close()
    arbiter.stop()
