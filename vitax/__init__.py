"""vitax — a TPU-native (JAX/XLA) framework for training large Vision Transformers.

Built from scratch with the capability surface of ronghanghu/vit_10b_fsdp_example
(see SURVEY.md): FSDP/ZeRO-3 sharded training of 10B+ ViTs on TPU pods, activation
checkpointing, sharded checkpoint save/resume + consolidation, fake-data and pure-DP
baseline modes, and the reference's exact CLI flag surface — expressed TPU-first as
sharding declarations over a `jax.sharding.Mesh` compiled by GSPMD, not as module
wrappers over a lazy-tensor runtime.

Package map:
  config        CLI + typed config (reference run_vit_training.py:327-363 parity)
  models        Flax ViT (patchify, attention, MLP, scanned+remat blocks)
  ops           TPU kernels (Pallas flash attention) + reference implementations
  parallel      mesh construction, sharding rules (FSDP/DP/TP/SP), ring attention
  data          host input pipeline (fake data, ImageFolder, transforms, prefetch)
  train         train state, jitted step functions, epoch loop, LR schedule
  checkpoint    Orbax sharded save/restore + consolidation
  utils         metrics, logging, profiling
  distributed   multi-host runtime (init, barriers, host reductions)
"""

__version__ = "0.1.0"

# Layout-invariant PRNG everywhere: newer jax defaults this on, 0.4.x does
# not — and without it param init DRAWS (not just layouts) change with the
# mesh shape, breaking the repo's core sharding-must-not-change-the-math
# contract (tests/test_train_smoke.py::test_dp_fsdp_zero2_equivalence and
# every sp/tp/pp equivalence test). No-op where it is already the default.
import jax as _jax

try:
    _jax.config.update("jax_threefry_partitionable", True)
except (AttributeError, ValueError):  # flag retired once always-on
    pass
del _jax
