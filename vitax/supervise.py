"""Supervised auto-restart: run training as a subprocess and keep it alive.

The reaction half of the elastic-training loop (ROADMAP open item 4, Varuna-
style, Athlur et al. 2022): PRs 4-6 made failures *detectable* (watchdog
dumps, preemption saves, telemetry) — this module makes them *survivable*
without an operator. The supervisor launches the training command with
``--resume_epoch -1`` forced on (auto-resume from the latest COMMITTED
checkpoint, vitax/checkpoint/orbax_io.py), then:

- restarts on any nonzero exit — a fault crash, an OOM-kill, the watchdog's
  escalation exit (code 42, vitax/telemetry/watchdog.py EXIT_HANG) — with
  capped exponential backoff and a total restart budget;
- detects CRASH LOOPS: a child that dies without advancing the checkpoint
  frontier (latest committed epoch + resume-step sidecar, maxed with the
  peer-replication store's frontier when the child runs with
  ``--replicate_steps`` — peer-restored progress is real progress even when
  no Orbax commit advanced) is burning the budget on a deterministic bug,
  not riding out flaky infrastructure — after
  ``crash_loop_tolerance`` consecutive no-progress deaths the supervisor
  gives up with EXIT_BUDGET (3) so the launcher sees a *distinct* failure;
- forwards SIGTERM/SIGINT to the child exactly once for a clean preemption
  drain (the child's preempt.py path saves and exits 0; the supervisor
  passes that code through instead of restarting), hard-killing after
  ``term_grace_s``;
- appends ``kind:"restart"`` schema-1 events to ``<metrics_dir>/
  metrics.jsonl`` — the same stream the child's Recorder writes — so
  tools/metrics_report.py surfaces restart count and last exit code;
- detects ELASTIC (topology-change) restarts: when the checkpoint frontier's
  sidecar records a different process count than the one the next child
  launch runs under (``--expect_processes``, default: the JAX_NUM_PROCESSES
  bring-up env var, else checking stays off — TPU pods auto-detect their
  topology without the var), the supervisor announces it loudly and appends
  a ``kind:"control"`` ``topology_change`` event — the child's own
  elastic-resume path (vitax/train/control.py) re-derives steps_per_epoch
  and remaps or epoch-rounds the stream cursor, so an N-host checkpoint
  restarts on M hosts without operator surgery. Exit 42 now also covers the
  COORDINATED multi-host escalations (agreed hang/fault/peer-loss verdicts):
  every host exits with the same code at the same committed step, so one
  supervisor decision fits all hosts.

Exit-code contract:
  0           child completed (or drained cleanly after a forwarded SIGTERM)
  EXIT_BUDGET (3) restart budget exhausted or crash loop detected
  (anything else: the child's own final code, passed through on SIGTERM)

CLI: ``python tools/supervise.py [flags] -- python run_vit_training.py ...``
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from typing import Callable, List, Optional, Sequence, Tuple

EXIT_BUDGET = 3  # distinct from the child's codes: the SUPERVISOR gave up

DEFAULT_MAX_RESTARTS = 10
DEFAULT_BACKOFF_S = 1.0
DEFAULT_BACKOFF_MAX_S = 60.0
DEFAULT_CRASH_LOOP_TOLERANCE = 2
DEFAULT_TERM_GRACE_S = 30.0


def backoff_delay(restart_count: int, backoff_s: float,
                  backoff_max_s: float) -> float:
    """Capped exponential backoff before restart N (1-based): backoff_s
    doubles per restart up to backoff_max_s. The shared seam between the
    training Supervisor below and the serve fleet's ReplicaManager
    (vitax/serve/fleet/replica.py) — one backoff policy, tested once."""
    return min(backoff_s * (2 ** max(restart_count - 1, 0)), backoff_max_s)


def terminate_child(proc, grace_s: float,
                    sleep: Callable[[float], None] = time.sleep,
                    poll_interval_s: float = 0.1) -> Optional[int]:
    """SIGTERM -> drain window -> SIGKILL: ask `proc` to drain cleanly (the
    child's SIGTERM path — preempt.py for training, the serve drain for
    replicas — saves/answers and exits 0), hard-killing after `grace_s`.
    Returns the child's exit code (None only if it outlives the kill too,
    which a real process cannot). Shared by the Supervisor's forwarded-drain
    and the serve fleet's replica shutdown."""
    try:
        proc.send_signal(signal.SIGTERM)
    except (OSError, ValueError):
        pass  # already gone: poll() below returns its code
    deadline = time.monotonic() + grace_s
    while proc.poll() is None and time.monotonic() < deadline:
        sleep(poll_interval_s)
    if proc.poll() is None:
        try:
            proc.kill()
        except (OSError, ValueError):
            pass
        for _ in range(600):  # a killed process reaps promptly
            if proc.poll() is not None:
                break
            sleep(poll_interval_s)
    return proc.poll()

SCHEMA_VERSION = 1  # matches vitax.telemetry.record.SCHEMA_VERSION (kept
# literal here so the supervisor never imports the jax-backed telemetry
# stack into its own lightweight process)


def ensure_auto_resume(argv: Sequence[str]) -> List[str]:
    """Force --resume_epoch -1 on the child command: a supervised restart
    that re-trains from scratch (the default resume_epoch=0) would silently
    discard every committed epoch."""
    argv = list(argv)
    for i, arg in enumerate(argv):
        if arg == "--resume_epoch":
            if i + 1 < len(argv):
                argv[i + 1] = "-1"
            return argv
        if arg.startswith("--resume_epoch="):
            argv[i] = "--resume_epoch=-1"
            return argv
    return argv + ["--resume_epoch", "-1"]


def scrape_flag(argv: Sequence[str], flag: str) -> Optional[str]:
    """Value of `flag` in a child argv (both `--flag v` and `--flag=v`)."""
    for i, arg in enumerate(argv):
        if arg == flag and i + 1 < len(argv):
            return argv[i + 1]
        if arg.startswith(flag + "="):
            return arg.split("=", 1)[1]
    return None


def checkpoint_progress(ckpt_dir: str) -> Tuple[int, int]:
    """The child's durable progress frontier: (latest committed epoch,
    resume-sidecar step of that epoch). Tuple-ordered so any committed
    advance — a new epoch, or a later mid-epoch preemption/escalation save
    of the same epoch — counts as progress between restarts."""
    from vitax.checkpoint.orbax_io import committed_epochs, load_resume_step
    epochs = committed_epochs(ckpt_dir)
    if not epochs:
        return (0, 0)
    latest = epochs[-1]
    return (latest, load_resume_step(ckpt_dir, latest) or 0)


def peer_store_root(child_argv: Sequence[str], ckpt_dir: str) -> str:
    """Root of the child's peer-replication store (PR 11, vitax/checkpoint/
    peer.py), or "" when peer replication is off for this child command.
    Same resolution order the child itself uses (peer.resolve_peer_dir,
    minus the per-process suffix): VITAX_PEER_DIR env > --peer_dir >
    <ckpt_dir>/peerstore — gated on --replicate_steps > 0 so supervising a
    replication-free run never invents a phantom frontier directory."""
    steps = scrape_flag(child_argv, "--replicate_steps")
    try:
        if int(steps or 0) <= 0:
            return ""
    except ValueError:
        return ""
    env = os.environ.get("VITAX_PEER_DIR", "")
    if env:
        return env
    flagged = scrape_flag(child_argv, "--peer_dir")
    if flagged:
        return flagged
    from vitax.checkpoint.peer import default_peer_root
    return default_peer_root(ckpt_dir)


def run_progress(ckpt_dir: str, peer_root: str = "") -> Tuple[int, int]:
    """The combined durable-progress frontier: the Orbax checkpoint frontier
    maxed with the peer-replication store's frontier (when one exists). A
    child that died between Orbax commits but after a replication window
    still made REAL progress — its shards live on the surviving buddies and
    the next launch restores them without touching shared storage — so the
    crash-loop detector must count it, or a run surviving on peer restores
    would read as a crash loop and the supervisor would give up mid-save.

    Both sides are NORMALIZED with peer.progress_key — a boundary save of
    epoch e, recorded as (e, 0), means e is COMPLETE and counts as
    (e + 1, 0) — so an epoch-completing peer version is never outranked by
    a stale mid-epoch Orbax frontier (e, s) of the same epoch. (0, 0) means
    no durable progress at all."""
    from vitax.checkpoint.peer import progress_key, store_frontier
    epoch, step = checkpoint_progress(ckpt_dir)
    progress = progress_key(epoch, step) if (epoch or step) else (0, 0)
    if peer_root:
        progress = max(progress, store_frontier(peer_root))
    return progress


def checkpoint_topology(ckpt_dir: str) -> Optional[int]:
    """The process count that wrote the frontier checkpoint's mid-epoch
    sidecar, or None (boundary save, pre-PR-10 sidecar, no checkpoint).
    The elastic-restart path compares this against the topology the child
    is about to launch with (vitax/train/control.py elastic_resume_plan
    makes the in-loop decision; the supervisor's job is only to SAY what
    is about to happen and record it)."""
    from vitax.checkpoint.orbax_io import committed_epochs, load_resume_meta
    epochs = committed_epochs(ckpt_dir)
    if not epochs:
        return None
    meta = load_resume_meta(ckpt_dir, epochs[-1]) or {}
    count = meta.get("process_count")
    return int(count) if isinstance(count, int) and count >= 1 else None


def topology_env(base_env: dict, process_count: int, process_id: int = 0,
                 coordinator_port: int = 0) -> dict:
    """Child environment for one rank of an N-process launch: exactly the
    bring-up variables vitax/distributed.py reads. Single-process launches
    get them REMOVED — a stale 2-process JAX_NUM_PROCESSES inherited across
    an elastic shrink would wedge bring-up waiting on a phantom peer. The
    canonical builder for every component that relaunches training at a
    new topology (the arbiter's TrainDirector, the elastic drills)."""
    env = dict(base_env)
    for key in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
                "JAX_PROCESS_ID"):
        env.pop(key, None)
    if process_count > 1:
        assert coordinator_port > 0, (
            "multi-process launches need a fresh coordinator port")
        env["JAX_COORDINATOR_ADDRESS"] = f"localhost:{coordinator_port}"
        env["JAX_NUM_PROCESSES"] = str(process_count)
        env["JAX_PROCESS_ID"] = str(process_id)
    return env


def expected_process_count() -> int:
    """The topology the next child launch will run under: the explicit
    bring-up env var (the same one vitax/distributed.py reads), else 0 =
    topology checking OFF. The supervisor launches the child with its own
    inherited environment, so when the var is set this is exactly what
    jax.process_count() will say in the child. When it is absent the child
    may still be multi-process (TPU pods auto-detect their topology from
    platform metadata, never setting the var) — guessing 1 would flag a
    spurious TOPOLOGY CHANGE against the sidecar's real process count on
    every restart, so the supervisor stays quiet unless told
    --expect_processes explicitly."""
    nproc = os.environ.get("JAX_NUM_PROCESSES", "")
    return int(nproc) if nproc.isdigit() and int(nproc) >= 1 else 0


class Supervisor:
    """Restart loop around one training subprocess.

    `spawn`, `progress_fn` and `sleep` are injectable so the restart /
    backoff / crash-loop logic is unit-testable on a fake child with no real
    processes (tests/test_faults.py)."""

    def __init__(self, child_argv: Sequence[str], ckpt_dir: str,
                 metrics_dir: str = "",
                 max_restarts: int = DEFAULT_MAX_RESTARTS,
                 backoff_s: float = DEFAULT_BACKOFF_S,
                 backoff_max_s: float = DEFAULT_BACKOFF_MAX_S,
                 crash_loop_tolerance: int = DEFAULT_CRASH_LOOP_TOLERANCE,
                 term_grace_s: float = DEFAULT_TERM_GRACE_S,
                 spawn: Optional[Callable] = None,
                 progress_fn: Optional[Callable[[], Tuple]] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 poll_interval_s: float = 0.1,
                 expect_processes: int = 0,
                 topology_fn: Optional[Callable[[], Optional[int]]] = None,
                 peer_root: str = ""):
        assert max_restarts >= 0, max_restarts
        assert crash_loop_tolerance >= 0, crash_loop_tolerance
        assert backoff_s >= 0 and backoff_max_s >= 0
        self.child_argv = ensure_auto_resume(child_argv)
        self.ckpt_dir = ckpt_dir
        self.metrics_dir = metrics_dir
        self.max_restarts = max_restarts
        self.backoff_s = backoff_s
        self.backoff_max_s = backoff_max_s
        self.crash_loop_tolerance = crash_loop_tolerance
        self.term_grace_s = term_grace_s
        self.poll_interval_s = poll_interval_s
        self._spawn = spawn or (lambda argv: subprocess.Popen(argv))
        # peer-replicated progress counts too: a child surviving on peer
        # restores (no Orbax commit between deaths) is not a crash loop
        self.peer_root = peer_root
        self._progress = progress_fn or (
            lambda: run_progress(self.ckpt_dir, self.peer_root))
        self._sleep = sleep
        # elastic restarts: 0 = topology checking off; > 0 = the process
        # count the next child launch runs under, compared against the
        # frontier sidecar's recorded topology before each spawn
        self.expect_processes = expect_processes
        self._topology = topology_fn or (
            lambda: checkpoint_topology(self.ckpt_dir))
        self.topology_changes = 0
        self._topology_noted: Optional[int] = None
        self.restart_count = 0
        self.last_exit_code: Optional[int] = None
        self._term_requested = False
        self._term_forwarded = False

    def set_expect_processes(self, n: int) -> None:
        """Flip the topology the NEXT child launch is expected under — the
        arbiter's borrow/return path drives this on a supervised
        deployment. A plain int store (atomic in CPython) read once per
        restart cycle; resetting _topology_noted makes the next
        _check_topology announce the change instead of staying quiet."""
        self.expect_processes = int(n)
        self._topology_noted = None

    # -- signal forwarding ---------------------------------------------------
    def _on_term(self, signum, frame):  # noqa: ARG002 — signal handler signature
        self._term_requested = True

    def _install_handlers(self) -> None:
        try:
            signal.signal(signal.SIGTERM, self._on_term)
            signal.signal(signal.SIGINT, self._on_term)
        except ValueError:
            pass  # not the main thread (tests): forwarding unavailable

    # -- telemetry -----------------------------------------------------------
    def _append_event(self, kind: str, **payload) -> None:
        """Append one schema-1 event to the run's metrics.jsonl (the child is
        not running while the supervisor writes, so the append interleaves
        with the Recorder's stream only at line granularity — which JSONL is
        built for). Fail-soft: supervision must not die over observability."""
        record = {"schema": SCHEMA_VERSION, "time": time.time(),
                  "kind": kind, "rank": 0, **payload}
        if not self.metrics_dir:
            return
        try:
            os.makedirs(self.metrics_dir, exist_ok=True)
            path = os.path.join(self.metrics_dir, "metrics.jsonl")
            with open(path, "a", encoding="utf-8") as f:
                f.write(json.dumps(record, sort_keys=True) + "\n")
        except OSError as e:
            self._log(f"cannot write {kind} event ({e}); continuing")

    def _event(self, **payload) -> None:
        self._log(f"restart {payload.get('restart')}: child exit "
                  f"{payload.get('exit_code')}, "
                  f"{'progress' if payload.get('progress') else 'NO progress'}"
                  f" since last start, backing off "
                  f"{payload.get('backoff_s'):.2f}s")
        self._append_event("restart", **payload)

    def _check_topology(self) -> None:
        """Before each child launch: compare the frontier checkpoint's
        recorded topology against the one this launch runs under, and say
        LOUDLY (log + kind:"control" event) when they differ — the child's
        elastic-resume path (vitax/train/loop.py _elastic_resume) re-derives
        steps_per_epoch and remaps or epoch-rounds the stream cursor, so the
        restart proceeds instead of failing on cursor/shape checks."""
        if not self.expect_processes:
            return
        recorded = self._topology()
        if recorded is None or recorded == self.expect_processes:
            return
        if recorded == self._topology_noted:
            return  # already announced this same mismatch
        self._topology_noted = recorded
        self.topology_changes += 1
        self._log(f"TOPOLOGY CHANGE: checkpoint frontier was written by "
                  f"{recorded} process(es); child launching with "
                  f"{self.expect_processes} — elastic resume will re-derive "
                  f"steps_per_epoch and remap or epoch-round the stream "
                  f"cursor")
        self._append_event("control", event="topology_change",
                           from_processes=recorded,
                           to_processes=self.expect_processes)

    @staticmethod
    def _log(msg: str) -> None:
        print(f"[vitax.supervise] {msg}", file=sys.stderr, flush=True)

    # -- child lifecycle -----------------------------------------------------
    def _wait(self, child) -> int:
        """Wait for the child, forwarding one SIGTERM when asked and
        hard-killing after the grace window."""
        kill_at: Optional[float] = None
        while True:
            rc = child.poll()
            if rc is not None:
                return rc
            if self._term_requested and not self._term_forwarded:
                self._term_forwarded = True
                self._log(f"forwarding SIGTERM to the child (clean drain; "
                          f"hard kill after {self.term_grace_s:.0f}s)")
                try:
                    child.send_signal(signal.SIGTERM)
                except (OSError, ValueError):
                    pass  # already gone: the next poll() returns its code
                kill_at = time.monotonic() + self.term_grace_s
            if kill_at is not None and time.monotonic() >= kill_at:
                self._log("grace window passed; killing the child")
                try:
                    child.kill()
                except (OSError, ValueError):
                    pass
                kill_at = None
            self._sleep(self.poll_interval_s)

    def run(self) -> int:
        self._install_handlers()
        no_progress = 0
        self._log(f"supervising: {' '.join(map(str, self.child_argv))}")
        if self.peer_root:
            self._log(f"peer-replication store at {self.peer_root}: its "
                      f"frontier counts as checkpoint progress")
        while True:
            before = self._progress()
            self._check_topology()
            child = self._spawn(self.child_argv)
            rc = self._wait(child)
            self.last_exit_code = rc
            if self._term_requested:
                # the drain was OURS to request: pass the child's code
                # through (0 for a clean preemption save) — the scheduler is
                # taking the host, restarting here would fight it
                self._log(f"child exited {rc} after forwarded SIGTERM; "
                          f"supervisor exiting")
                return rc
            if rc == 0:
                self._log("child completed cleanly")
                return 0
            after = self._progress()
            progressed = after > before  # tuple order: (epoch, step_in_epoch)
            no_progress = 0 if progressed else no_progress + 1
            if no_progress > self.crash_loop_tolerance:
                self._log(
                    f"CRASH LOOP: {no_progress} consecutive exit(s) with no "
                    f"checkpoint progress (frontier {after}); giving up with "
                    f"exit {EXIT_BUDGET}")
                return EXIT_BUDGET
            self.restart_count += 1
            if self.restart_count > self.max_restarts:
                self._log(f"restart budget ({self.max_restarts}) exhausted; "
                          f"giving up with exit {EXIT_BUDGET}")
                return EXIT_BUDGET
            delay = backoff_delay(self.restart_count, self.backoff_s,
                                  self.backoff_max_s)
            self._event(exit_code=rc, restart=self.restart_count,
                        backoff_s=delay, progress=progressed,
                        epoch=after[0], step_in_epoch=after[1])
            if delay > 0:
                self._sleep(delay)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python tools/supervise.py",
        description="supervised auto-restart for vitax training: "
                    "`python tools/supervise.py [flags] -- python "
                    "run_vit_training.py ...` (the child is forced to "
                    "--resume_epoch -1)")
    p.add_argument("--ckpt_dir", type=str, default="",
                   help="checkpoint dir for crash-loop progress detection "
                        "(default: scraped from the child command's "
                        "--ckpt_dir, else the trainer's default)")
    p.add_argument("--metrics_dir", type=str, default="",
                   help="append kind:'restart' events to <metrics_dir>/"
                        "metrics.jsonl (default: scraped from the child "
                        "command)")
    p.add_argument("--max_restarts", type=int, default=DEFAULT_MAX_RESTARTS,
                   help="total restarts before giving up with exit "
                        f"{EXIT_BUDGET}")
    p.add_argument("--backoff_s", type=float, default=DEFAULT_BACKOFF_S,
                   help="first restart delay; doubles per restart")
    p.add_argument("--backoff_max_s", type=float,
                   default=DEFAULT_BACKOFF_MAX_S, help="backoff cap")
    p.add_argument("--crash_loop_tolerance", type=int,
                   default=DEFAULT_CRASH_LOOP_TOLERANCE,
                   help="consecutive no-checkpoint-progress exits tolerated "
                        f"before giving up with exit {EXIT_BUDGET}")
    p.add_argument("--term_grace_s", type=float, default=DEFAULT_TERM_GRACE_S,
                   help="seconds a SIGTERM-forwarded child gets to drain "
                        "before a hard kill")
    p.add_argument("--expect_processes", type=int, default=0,
                   help="process count the child launches with, for elastic "
                        "(topology-change) restart detection against the "
                        "checkpoint frontier's recorded topology (default "
                        "0 = read JAX_NUM_PROCESSES from the environment; "
                        "when that is unset too — e.g. TPU pods that "
                        "auto-detect their topology — checking stays off)")
    return p


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--" not in argv:
        print("supervise: missing child command — usage: "
              "python tools/supervise.py [flags] -- python "
              "run_vit_training.py ...", file=sys.stderr)
        return 2
    split = argv.index("--")
    own, child = argv[:split], argv[split + 1:]
    if not child:
        print("supervise: empty child command after --", file=sys.stderr)
        return 2
    args = build_parser().parse_args(own)
    ckpt_dir = (args.ckpt_dir or scrape_flag(child, "--ckpt_dir")
                or "/tmp/vit_fsdp")  # the trainer's own default
    metrics_dir = args.metrics_dir or scrape_flag(child, "--metrics_dir") or ""
    sup = Supervisor(
        child, ckpt_dir, metrics_dir=metrics_dir,
        max_restarts=args.max_restarts, backoff_s=args.backoff_s,
        backoff_max_s=args.backoff_max_s,
        crash_loop_tolerance=args.crash_loop_tolerance,
        term_grace_s=args.term_grace_s,
        expect_processes=args.expect_processes or expected_process_count(),
        peer_root=peer_store_root(child, ckpt_dir))
    return sup.run()


if __name__ == "__main__":
    sys.exit(main())
