"""Static thread-safety & lock-discipline lint for the threaded runtime.

The HLO rules (:mod:`vitax.analysis.rules`) and AST lint
(:mod:`vitax.analysis.ast_lint`, VTX100-108) guard the compiled program;
this pass guards the *host* program. vitax has grown ~18 modules that
spawn threads or share lock-guarded state (serve batcher, fleet health
loop, watchdog, loader producers, control plane, snapshot worker, peer
replicator) — exactly the bug class tier-1 CPU tests rarely catch and
that surfaces as a pod-scale hang.

Per class, the analyzer extracts a thread model: thread entry points
(`threading.Thread(target=...)` / `threading.Timer(...)` with bound
methods, nested defs, or lambdas; plus bound methods passed as `on_*`
callback kwargs), sync-primitive attributes (Lock/RLock/Condition/Event/
Queue), per-method attribute read/write sets with the locks lexically
held (`with self._lock:`), and same-class call edges. Reachability is
split into a *thread side* (closure over calls from entry points) and a
*caller side* (closure from public roots), with lock context propagated
through call sites, then the VTX200-series rules check the model:

  VTX200  ERROR  shared attribute written on one side (thread or caller)
                 and accessed on the other with no common guarding lock
  VTX201  ERROR  `Condition.wait()` not re-checked in a `while` loop —
                 spurious wakeups and missed-predicate races
  VTX202  ERROR  lock-acquisition-order cycle across methods (A held
                 while taking B, elsewhere B held while taking A)
  VTX203  ERROR  blocking call while holding a lock: argless `join()`,
                 `Queue.get/put` without timeout, `Event.wait()` without
                 timeout, `Condition.wait` with a *different* lock still
                 held, or an HTTP request
  VTX204  ERROR  JAX dispatch (`jax.*` / `jnp.*` / `lax.*`) reachable
                 from a thread entry point — only sanctioned consumer
                 threads may touch the device (suppress with a reason at
                 sanctioned sites)
  VTX205  ERROR  leaked thread: started but never joined/cancelled and
                 no stop-event protocol ties it to a shutdown path

Known static limits (by design, stdlib-AST only): module-level globals
are not modeled for VTX200; callables pushed through queues or stored as
callback attributes are invisible to reachability; `.acquire()`/
`.release()` pairs outside `with` contribute lock-order edges but not
guard scopes. Suppress intentional sites with
`# vtx: ignore[VTX20x] <reason>` on the reported line (same machinery
and VTX100 bare-suppression policing as ast_lint, which runs first).

Run: `python -m vitax.analysis.concurrency [paths...] [--json]`
(default path: the vitax/ package directory). Exit 1 on any finding.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import json
import os
import sys
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from vitax.analysis.ast_lint import Finding, _dotted, _suppressions

_SYNC_KINDS = {
    "threading.Lock": "lock",
    "threading.RLock": "lock",
    "threading.Semaphore": "lock",
    "threading.BoundedSemaphore": "lock",
    "threading.Condition": "condition",
    "threading.Event": "event",
    "queue.Queue": "queue",
    "queue.SimpleQueue": "queue",
    "queue.LifoQueue": "queue",
    "queue.PriorityQueue": "queue",
}
_THREAD_CTORS = {"threading.Thread": "thread", "threading.Timer": "timer"}
# container-method calls on `self.X` that mutate X in place
_MUTATORS = {"append", "extend", "insert", "pop", "popleft", "appendleft",
             "remove", "clear", "add", "discard", "update", "setdefault",
             "sort", "reverse"}
_JAX_ROOTS = ("jax", "jnp", "lax")
_MAX_CONTEXTS = 8  # lock-context fan-out cap per method (keeps fixpoint tiny)


@dataclasses.dataclass
class _Func:
    """Everything the rules need to know about one function body."""
    name: str
    line: int
    # (attr, is_write, line, guards) for `self.X` touches
    accesses: List[Tuple[str, bool, int, frozenset]] = dataclasses.field(default_factory=list)
    # (callee_method, line, guards) for `self.m(...)` calls
    calls: List[Tuple[str, int, frozenset]] = dataclasses.field(default_factory=list)
    # (pseudo_func, line, guards): nested def/lambda inlined into this side
    # unless it turns out to be a thread entry point
    maybe_calls: List[Tuple[str, int, frozenset]] = dataclasses.field(default_factory=list)
    # (lock_token, line, guards_already_held) for `with`/`.acquire()`
    acquires: List[Tuple[str, int, frozenset]] = dataclasses.field(default_factory=list)
    # (cond_token, line, has_timeout, in_while, guards)
    cond_waits: List[Tuple[str, int, bool, bool, frozenset]] = dataclasses.field(default_factory=list)
    # (kind, desc, line, guards) — kind in join/queue/event_wait/cond_wait/http
    blockers: List[Tuple[str, str, int, frozenset]] = dataclasses.field(default_factory=list)
    jax_calls: List[Tuple[str, int]] = dataclasses.field(default_factory=list)
    events_set: Set[str] = dataclasses.field(default_factory=set)
    refs: Set[str] = dataclasses.field(default_factory=set)
    # local-thread bookkeeping for the function-scope VTX205 check
    local_threads: List[Tuple[int, Optional[str]]] = dataclasses.field(default_factory=list)
    started_names: Set[str] = dataclasses.field(default_factory=set)
    anon_starts: List[int] = dataclasses.field(default_factory=list)
    escapes: Set[str] = dataclasses.field(default_factory=set)
    has_any_start: bool = False
    has_mgmt_join: bool = False


@dataclasses.dataclass
class _Scope:
    """Thread model for one class (or the module pseudo-scope)."""
    name: str
    line: int
    module_scope: bool = False
    sync: Dict[str, str] = dataclasses.field(default_factory=dict)  # token -> kind
    method_names: Set[str] = dataclasses.field(default_factory=set)
    funcs: Dict[str, _Func] = dataclasses.field(default_factory=dict)
    entries: Set[str] = dataclasses.field(default_factory=set)
    thread_attrs: Dict[str, dict] = dataclasses.field(default_factory=dict)


def _thread_target(call: ast.Call) -> Optional[ast.AST]:
    """The `target=`/`function=` expression of a Thread/Timer constructor."""
    dot = _dotted(call.func)
    kw_name = "target" if dot == "threading.Thread" else "function"
    for kw in call.keywords:
        if kw.arg == kw_name:
            return kw.value
    if len(call.args) >= 2:
        return call.args[1]
    return None


def _self_attr(node: ast.AST, selfname: Optional[str]) -> Optional[str]:
    if (selfname and isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name) and node.value.id == selfname):
        return node.attr
    return None


class _FuncCollector(ast.NodeVisitor):
    """Collects one _Func; recurses into nested defs as pseudo-methods."""

    def __init__(self, scope: _Scope, func: _Func, selfname: Optional[str],
                 local_syncs: Optional[Dict[str, str]] = None,
                 local_funcs: Optional[Dict[str, str]] = None) -> None:
        self.scope = scope
        self.func = func
        self.selfname = selfname
        self.guards: List[str] = []
        self.while_depth = 0
        self.local_syncs: Dict[str, str] = dict(local_syncs or {})
        self.local_funcs: Dict[str, str] = dict(local_funcs or {})
        self.local_thread_names: Set[str] = set()
        self._bound: Set[int] = set()    # Thread ctor Call ids bound by Assign
        self._claimed: Set[int] = set()  # lambda ids turned into entry pseudos

    # -- helpers ------------------------------------------------------------
    def _sync_ref(self, node: ast.AST) -> Tuple[Optional[str], Optional[str]]:
        attr = _self_attr(node, self.selfname)
        if attr is not None:
            tok = "self." + attr
            return (tok, self.scope.sync.get(tok))
        if isinstance(node, ast.Name) and node.id in self.local_syncs:
            return (node.id, self.local_syncs[node.id])
        return (None, None)

    def _resolve_entry(self, ctor: ast.Call) -> Optional[str]:
        """Register (and return) the entry-point key of a thread ctor."""
        target = _thread_target(ctor)
        if target is None:
            return None
        attr = _self_attr(target, self.selfname)
        if attr is not None and attr in self.scope.method_names:
            self.scope.entries.add(attr)
            return attr
        if isinstance(target, ast.Name) and target.id in self.local_funcs:
            key = self.local_funcs[target.id]
            self.scope.entries.add(key)
            return key
        if isinstance(target, ast.Lambda):
            key = f"{self.func.name}.<lambda:{target.lineno}>"
            self._collect_nested(key, target.lineno, [target.body])
            self.scope.entries.add(key)
            self._claimed.add(id(target))
            return key
        return None

    def _collect_nested(self, key: str, line: int, body: List[ast.AST]) -> _Func:
        sub = _Func(name=key, line=line)
        self.scope.funcs[key] = sub
        col = _FuncCollector(self.scope, sub, self.selfname,
                             self.local_syncs, self.local_funcs)
        for stmt in body:
            col.visit(stmt)
        return sub

    # -- structure ----------------------------------------------------------
    def _visit_nested_def(self, node) -> None:
        key = f"{self.func.name}.{node.name}"
        self.local_funcs[node.name] = key
        self.func.maybe_calls.append((key, node.lineno, frozenset(self.guards)))
        self._collect_nested(key, node.lineno, node.body)

    visit_FunctionDef = _visit_nested_def
    visit_AsyncFunctionDef = _visit_nested_def

    def visit_Lambda(self, node: ast.Lambda) -> None:
        if id(node) in self._claimed:
            return
        self.generic_visit(node)  # inline into the enclosing function

    def visit_While(self, node: ast.While) -> None:
        self.while_depth += 1
        self.generic_visit(node)
        self.while_depth -= 1

    def visit_With(self, node: ast.With) -> None:
        pushed = 0
        for item in node.items:
            self.visit(item.context_expr)
            tok, kind = self._sync_ref(item.context_expr)
            if tok is not None and kind in ("lock", "condition"):
                self.func.acquires.append(
                    (tok, node.lineno, frozenset(self.guards)))
                self.guards.append(tok)
                pushed += 1
        for stmt in node.body:
            self.visit(stmt)
        del self.guards[len(self.guards) - pushed:]

    visit_AsyncWith = visit_With

    # -- accesses -----------------------------------------------------------
    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = _self_attr(node, self.selfname)
        if attr is not None:
            write = isinstance(node.ctx, (ast.Store, ast.Del))
            self.func.refs.add("self." + attr)
            self.func.accesses.append(
                (attr, write, node.lineno, frozenset(self.guards)))
        self.generic_visit(node)

    def _bind_thread(self, value: ast.Call, targets: List[ast.AST],
                     line: int) -> None:
        self._bound.add(id(value))
        entry = self._resolve_entry(value)
        kind = _THREAD_CTORS[_dotted(value.func)]
        for t in targets:
            attr = _self_attr(t, self.selfname)
            if attr is not None:
                self.scope.thread_attrs.setdefault(attr, {
                    "kind": kind, "line": line, "entry": entry,
                    "started": False, "joined": False, "cancelled": False})
            elif isinstance(t, ast.Name):
                self.func.local_threads.append((line, t.id))
                self.local_thread_names.add(t.id)

    def visit_Assign(self, node: ast.Assign) -> None:
        if (isinstance(node.value, ast.Call)
                and _dotted(node.value.func) in _THREAD_CTORS):
            self._bind_thread(node.value, node.targets, node.lineno)
        for t in node.targets:
            # `self.X[k] = v` / `self.X[k].y = v`: mutation of X
            base = t
            while isinstance(base, (ast.Subscript, ast.Attribute)) and not (
                    _self_attr(base, self.selfname)):
                base = base.value
            attr = _self_attr(base, self.selfname)
            if attr is not None and base is not t:
                self.func.accesses.append(
                    (attr, True, node.lineno, frozenset(self.guards)))
            # `self._worker = t` where t is a local Thread: track as attr
            if (isinstance(node.value, ast.Name)
                    and node.value.id in self.local_thread_names):
                a2 = _self_attr(t, self.selfname)
                if a2 is not None:
                    self.func.escapes.add(node.value.id)
                    self.scope.thread_attrs.setdefault(a2, {
                        "kind": "thread", "line": node.lineno, "entry": None,
                        "started": False, "joined": False, "cancelled": False})
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if (node.value is not None and isinstance(node.value, ast.Call)
                and _dotted(node.value.func) in _THREAD_CTORS):
            self._bind_thread(node.value, [node.target], node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        base = node.target
        while isinstance(base, (ast.Subscript, ast.Attribute)) and not (
                _self_attr(base, self.selfname)):
            base = base.value
        attr = _self_attr(base, self.selfname)
        if attr is not None and base is not node.target:
            self.func.accesses.append(
                (attr, True, node.lineno, frozenset(self.guards)))
        self.generic_visit(node)

    def visit_Return(self, node: ast.Return) -> None:
        if isinstance(node.value, ast.Name) and \
                node.value.id in self.local_thread_names:
            self.func.escapes.add(node.value.id)
        self.generic_visit(node)

    # -- calls --------------------------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        dot = _dotted(node.func)
        guards = frozenset(self.guards)
        line = node.lineno

        if dot in _THREAD_CTORS and id(node) not in self._bound:
            # unbound ctor (comprehension / chained / passed along)
            self._resolve_entry(node)
            self.func.local_threads.append((line, None))

        if dot and dot.split(".", 1)[0] in _JAX_ROOTS and "." in dot:
            self.func.jax_calls.append((dot, line))
        if "urlopen" in dot or dot.startswith("requests."):
            self.func.blockers.append(("http", dot, line, guards))

        # local sync primitives (module functions, or locals in methods)
        if dot in _SYNC_KINDS:
            pass  # binding handled via Assign below (visit order: Assign first)

        if isinstance(node.func, ast.Attribute):
            short = node.func.attr
            base = node.func.value
            base_attr = _self_attr(base, self.selfname)
            tok, kind = self._sync_ref(base)

            if short == "start":
                if isinstance(base, ast.Call) and \
                        _dotted(base.func) in _THREAD_CTORS:
                    self.func.anon_starts.append(line)
                elif base_attr is not None and \
                        base_attr in self.scope.thread_attrs:
                    self.scope.thread_attrs[base_attr]["started"] = True
                elif isinstance(base, ast.Name) and \
                        base.id in self.local_thread_names:
                    self.func.started_names.add(base.id)
                else:
                    self.func.has_any_start = True
            elif short == "cancel" and base_attr is not None and \
                    base_attr in self.scope.thread_attrs:
                self.scope.thread_attrs[base_attr]["cancelled"] = True
            elif short == "join" and not node.args and \
                    not isinstance(base, ast.Constant):
                # zero-positional-arg join: thread management (str.join and
                # os.path.join always carry positional args)
                if base_attr is not None and \
                        base_attr in self.scope.thread_attrs:
                    self.scope.thread_attrs[base_attr]["joined"] = True
                self.func.has_mgmt_join = True
                if not any(kw.arg == "timeout" for kw in node.keywords):
                    self.func.blockers.append(
                        ("join", _dotted(base) or short, line, guards))
            elif short == "set" and kind == "event":
                self.func.events_set.add(tok)
            elif short == "wait":
                has_to = bool(node.args) or any(
                    kw.arg == "timeout" for kw in node.keywords)
                if kind == "condition":
                    self.func.cond_waits.append(
                        (tok, line, has_to, self.while_depth > 0, guards))
                elif kind == "event" and not has_to:
                    self.func.blockers.append(("event_wait", tok, line, guards))
            elif short in ("get", "put") and kind == "queue":
                nonblock = any(
                    kw.arg in ("timeout", "block") for kw in node.keywords) \
                    or (short == "get" and node.args) \
                    or (short == "put" and len(node.args) > 1)
                if not nonblock:
                    self.func.blockers.append(("queue", tok, line, guards))
            elif short == "acquire" and kind in ("lock", "condition"):
                self.func.acquires.append((tok, line, guards))
            elif short in _MUTATORS and base_attr is not None:
                self.func.accesses.append((base_attr, True, line, guards))

            if base_attr is not None and short not in ("join", "cancel"):
                pass  # attribute read recorded by visit_Attribute below

            if isinstance(node.func.value, ast.Name) and \
                    node.func.value.id == self.selfname:
                self.func.calls.append((short, line, guards))

        # mgmt-by-helper: `join_or_warn(self._worker, ...)` etc.
        short_fn = dot.rsplit(".", 1)[-1] if dot else ""
        if "join" in short_fn or "cancel" in short_fn:
            for a in node.args:
                aa = _self_attr(a, self.selfname)
                if aa is not None and aa in self.scope.thread_attrs:
                    self.scope.thread_attrs[aa]["joined"] = True
                if isinstance(a, ast.Name) and \
                        a.id in self.local_thread_names:
                    self.func.has_mgmt_join = True

        # thread ctor target + `on_*` callback kwargs register entry points
        if dot in _THREAD_CTORS:
            self._resolve_entry(node)
        for kw in node.keywords:
            if kw.arg and kw.arg.startswith("on_"):
                cb = _self_attr(kw.value, self.selfname)
                if cb is not None and cb in self.scope.method_names:
                    self.scope.entries.add(cb)

        # any local thread handle passed to another call escapes tracking
        for a in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(a, ast.Name) and a.id in self.local_thread_names:
                self.func.escapes.add(a.id)

        self.generic_visit(node)


def _collect_class(node: ast.ClassDef) -> _Scope:
    scope = _Scope(name=node.name, line=node.lineno)
    for stmt in node.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scope.method_names.add(stmt.name)
    # pass 1: sync + thread attributes (any method, usually __init__)
    for sub in ast.walk(node):
        if not isinstance(sub, (ast.Assign, ast.AnnAssign)):
            continue
        value = sub.value
        if not isinstance(value, ast.Call):
            continue
        dot = _dotted(value.func)
        targets = sub.targets if isinstance(sub, ast.Assign) else [sub.target]
        for t in targets:
            attr = _self_attr(t, "self")
            if attr is None:
                continue
            if dot in _SYNC_KINDS:
                scope.sync["self." + attr] = _SYNC_KINDS[dot]
    # pass 2: per-method collection
    for stmt in node.body:
        if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        args = stmt.args.posonlyargs + stmt.args.args
        selfname = args[0].arg if args else None
        func = _Func(name=stmt.name, line=stmt.lineno)
        scope.funcs[stmt.name] = func
        col = _FuncCollector(scope, func, selfname)
        for s in stmt.body:
            col.visit(s)
    return scope


def _collect_module(tree: ast.Module) -> _Scope:
    """Module pseudo-scope: top-level functions, local locks/threads only."""
    scope = _Scope(name="<module>", line=1, module_scope=True)
    for stmt in tree.body:
        if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        func = _Func(name=stmt.name, line=stmt.lineno)
        scope.funcs[stmt.name] = func
        col = _FuncCollector(scope, func, selfname=None)
        # seed local sync vars assigned at function top level
        for s in stmt.body:
            if isinstance(s, ast.Assign) and isinstance(s.value, ast.Call):
                kind = _SYNC_KINDS.get(_dotted(s.value.func))
                if kind:
                    for t in s.targets:
                        if isinstance(t, ast.Name):
                            col.local_syncs[t.id] = kind
        for s in stmt.body:
            col.visit(s)
    return scope

# --------------------------------------------------------------------------
# analysis
# --------------------------------------------------------------------------

def _call_edges(scope: _Scope) -> Dict[str, List[Tuple[str, frozenset]]]:
    edges: Dict[str, List[Tuple[str, frozenset]]] = {}
    for fname, f in scope.funcs.items():
        out: List[Tuple[str, frozenset]] = []
        for callee, _line, g in f.calls:
            if callee in scope.funcs:
                out.append((callee, g))
        for pseudo, _line, g in f.maybe_calls:
            # nested defs/lambdas inline into the enclosing side unless
            # they are thread entry points in their own right
            if pseudo in scope.funcs and pseudo not in scope.entries:
                out.append((pseudo, g))
        edges[fname] = out
    return edges


def _contexts(roots: Iterable[str],
              edges: Dict[str, List[Tuple[str, frozenset]]]
              ) -> Dict[str, Set[frozenset]]:
    """Fixpoint: per reachable function, the lock sets held at entry."""
    ctx: Dict[str, Set[frozenset]] = {}
    work: List[Tuple[str, frozenset]] = []
    for r in roots:
        if r in edges or r in ctx or True:
            ctx.setdefault(r, set()).add(frozenset())
            work.append((r, frozenset()))
    while work:
        m, c = work.pop()
        for callee, g in edges.get(m, ()):
            nc = c | g
            got = ctx.setdefault(callee, set())
            if nc not in got and len(got) < _MAX_CONTEXTS:
                got.add(nc)
                work.append((callee, nc))
    return ctx


def _caller_roots(scope: _Scope,
                  edges: Dict[str, List[Tuple[str, frozenset]]]) -> Set[str]:
    incoming: Set[str] = set()
    for outs in edges.values():
        incoming.update(callee for callee, _g in outs)
    roots = set()
    for fname in scope.funcs:
        if fname in scope.entries or fname == "__init__":
            continue
        if "." in fname:  # pseudo (nested def/lambda): never an external root
            continue
        if fname not in incoming:
            roots.add(fname)
    return roots


def _side_accesses(scope: _Scope, ctxs: Dict[str, Set[frozenset]]
                   ) -> Dict[str, List[Tuple[str, int, bool, frozenset]]]:
    """attr -> [(func, line, is_write, effective_guards)] on one side."""
    out: Dict[str, List[Tuple[str, int, bool, frozenset]]] = {}
    for fname, f in scope.funcs.items():
        if fname == "__init__" or fname.startswith("__init__."):
            continue  # happens-before any thread start
        cs = ctxs.get(fname)
        if not cs:
            continue
        for attr, write, line, g in f.accesses:
            recs = out.setdefault(attr, [])
            for c in cs:
                recs.append((fname, line, write, c | g))
    return out


def _check_vtx200(scope: _Scope, path: str,
                  tctx: Dict[str, Set[frozenset]],
                  cctx: Dict[str, Set[frozenset]]) -> List[Finding]:
    if scope.module_scope or not scope.entries:
        return []
    t_acc = _side_accesses(scope, tctx)
    c_acc = _side_accesses(scope, cctx)
    findings: List[Finding] = []
    skip = {tok.split(".", 1)[1] for tok in scope.sync}
    skip |= set(scope.thread_attrs)  # handles are start/join protocol state
    skip |= scope.method_names
    for attr in sorted(set(t_acc) | set(c_acc)):
        if attr in skip:
            continue
        hit = None
        for wside, aside, wname, aname in (
                (t_acc, c_acc, "thread", "caller"),
                (c_acc, t_acc, "caller", "thread")):
            for fw, lw, w, gw in wside.get(attr, ()):
                if not w:
                    continue
                for fa, la, _aw, ga in aside.get(attr, ()):
                    if (fw, lw) == (fa, la):
                        continue
                    if not (gw & ga):
                        hit = (fw, lw, wname, fa, la, aname)
                        break
                if hit:
                    break
            if hit:
                break
        if hit:
            fw, lw, wname, fa, la, aname = hit
            findings.append(Finding(
                "VTX200", "ERROR", path, lw,
                f"`{scope.name}.{attr}` written on the {wname} path "
                f"(`{fw}`, line {lw}) and accessed on the {aname} path "
                f"(`{fa}`, line {la}) with no common lock — guard both "
                "sides with one lock"))
    return findings


def _check_vtx201(scope: _Scope, path: str) -> List[Finding]:
    findings = []
    for f in scope.funcs.values():
        for tok, line, _has_to, in_while, _g in f.cond_waits:
            if not in_while:
                findings.append(Finding(
                    "VTX201", "ERROR", path, line,
                    f"`{tok}.wait()` outside a `while` predicate loop in "
                    f"`{scope.name}.{f.name}` — condition waits can wake "
                    "spuriously; re-check the predicate in a while loop"))
    return findings


def _check_vtx202(scope: _Scope, path: str,
                  allctx: Dict[str, Set[frozenset]]) -> List[Finding]:
    edges: Dict[str, Dict[str, int]] = {}
    for fname, f in scope.funcs.items():
        cs = allctx.get(fname) or {frozenset()}
        for tok, line, g in f.acquires:
            for c in cs:
                for held in (c | g):
                    if held != tok:
                        edges.setdefault(held, {}).setdefault(tok, line)
    findings, seen = [], set()
    # DFS cycle detection over the small per-class lock graph
    def dfs(n: str, stack: List[str], on: Set[str]) -> None:
        on.add(n)
        stack.append(n)
        for m in edges.get(n, {}):
            if m in on:
                cyc = stack[stack.index(m):]
                key = frozenset(cyc)
                if key not in seen:
                    seen.add(key)
                    line = edges[n][m]
                    order = " -> ".join(cyc + [m])
                    findings.append(Finding(
                        "VTX202", "ERROR", path, line,
                        f"lock-order cycle in `{scope.name}`: {order} — "
                        "two threads taking these locks in opposite order "
                        "deadlock; pick one global order"))
            elif m in edges:
                dfs(m, stack, on)
        stack.pop()
        on.discard(n)
    for n in sorted(edges):
        dfs(n, [], set())
    return findings


def _check_vtx203(scope: _Scope, path: str,
                  allctx: Dict[str, Set[frozenset]]) -> List[Finding]:
    findings = []
    for fname, f in scope.funcs.items():
        cs = allctx.get(fname) or {frozenset()}
        done: Set[int] = set()
        blockers = list(f.blockers) + [
            ("cond_wait", tok, line, g) for tok, line, _t, _w, g
            in f.cond_waits]
        for kind, desc, line, g in blockers:
            if line in done:
                continue
            for c in cs:
                held = set(c | g)
                if kind == "cond_wait":
                    held.discard(desc)  # Condition.wait releases its own lock
                if held:
                    what = {"join": f"`{desc}.join()` with no timeout",
                            "queue": f"blocking `{desc}.get/put()`",
                            "event_wait": f"`{desc}.wait()` with no timeout",
                            "cond_wait": f"`{desc}.wait()`",
                            "http": f"HTTP request `{desc}`"}[kind]
                    findings.append(Finding(
                        "VTX203", "ERROR", path, line,
                        f"{what} in `{scope.name}.{fname}` while holding "
                        f"{sorted(held)} — blocks every other thread needing "
                        "that lock; release it first or bound the wait"))
                    done.add(line)
                    break
    return findings


def _check_vtx204(scope: _Scope, path: str,
                  tctx: Dict[str, Set[frozenset]]) -> List[Finding]:
    findings = []
    for fname in sorted(tctx):
        f = scope.funcs.get(fname)
        if f is None:
            continue
        for dot, line in f.jax_calls:
            findings.append(Finding(
                "VTX204", "ERROR", path, line,
                f"JAX dispatch `{dot}` on the thread path "
                f"`{scope.name}.{fname}` — only sanctioned consumer threads "
                "may touch the device (races the main dispatch thread and "
                "can deadlock the transfer guard); move it to the consumer "
                "or suppress with a reason"))
    return findings


def _check_vtx205(scope: _Scope, path: str) -> List[Finding]:
    findings = []
    events_set_anywhere: Set[str] = set()
    for f in scope.funcs.values():
        events_set_anywhere |= f.events_set
    for attr, info in sorted(scope.thread_attrs.items()):
        if not info["started"] or info["joined"] or info["cancelled"]:
            continue
        entry = info["entry"]
        stop_evented = False
        if entry is not None and entry in scope.funcs:
            refs = scope.funcs[entry].refs
            stop_evented = any(e in refs for e in events_set_anywhere)
        if not stop_evented:
            kind = "timer" if info["kind"] == "timer" else "thread"
            fix = ("`.cancel()` it on the shutdown path" if kind == "timer"
                   else "join it (or set a stop event its loop checks) on a "
                        "stop/close/drain path")
            findings.append(Finding(
                "VTX205", "ERROR", path, info["line"],
                f"{kind} `self.{attr}` in `{scope.name}` is started but "
                f"never reclaimed — {fix}, or it leaks past shutdown"))
    for f in scope.funcs.values():
        started_locals = [(line, name) for line, name in f.local_threads
                          if name is None or name in f.started_names]
        if f.has_any_start:
            started_locals += [(line, name) for line, name in f.local_threads
                               if name is not None
                               and name not in f.started_names]
        for line, name in started_locals:
            if f.has_mgmt_join or (name is not None and name in f.escapes):
                continue
            label = f"`{name}`" if name else "anonymous thread"
            findings.append(Finding(
                "VTX205", "ERROR", path, line,
                f"{label} started in `{scope.name}.{f.name}` with no join "
                "on any path and no hand-off — the thread leaks past the "
                "function; join it or store it somewhere a shutdown path "
                "reclaims"))
        for line in f.anon_starts:
            if f.has_mgmt_join:
                continue
            findings.append(Finding(
                "VTX205", "ERROR", path, line,
                f"`threading.Thread(...).start()` in `{scope.name}."
                f"{f.name}` drops the handle — nothing can ever join or "
                "stop this thread"))
    return findings


def _analyze(scope: _Scope, path: str) -> List[Finding]:
    edges = _call_edges(scope)
    tctx = _contexts(scope.entries, edges)
    cctx = _contexts(_caller_roots(scope, edges), edges)
    allctx: Dict[str, Set[frozenset]] = {}
    for src in (tctx, cctx):
        for k, v in src.items():
            allctx.setdefault(k, set()).update(v)
    findings = []
    findings += _check_vtx200(scope, path, tctx, cctx)
    findings += _check_vtx201(scope, path)
    findings += _check_vtx202(scope, path, allctx)
    findings += _check_vtx203(scope, path, allctx)
    findings += _check_vtx204(scope, path, tctx)
    findings += _check_vtx205(scope, path)
    return findings


# --------------------------------------------------------------------------
# driver (mirrors ast_lint: suppressions, paths, --json, exit code)
# --------------------------------------------------------------------------

def lint_source(source: str, path: str) -> List[Finding]:
    """Lint one file's source text; returns surviving findings.

    Bare-suppression policing (VTX100) is ast_lint's job — this pass only
    honors the same `# vtx: ignore[...]` comments, so running both passes
    over one tree never double-reports."""
    suppressed, _bare = _suppressions(source)
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError:
        return []  # ast_lint reports syntax errors; don't double up
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            findings.extend(_analyze(_collect_class(node), path))
    findings.extend(_analyze(_collect_module(tree), path))
    out = []
    for f in findings:
        if f.code in suppressed.get(f.line, ()):
            continue
        out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.code))
    return out


def _lint_file(path: str) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as fh:
        return lint_source(fh.read(), path)


def lint_paths(paths: Iterable[str]) -> List[Finding]:
    findings: List[Finding] = []
    for path in paths:
        if os.path.isdir(path):
            for dirpath, dirnames, filenames in os.walk(path):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        findings.extend(_lint_file(os.path.join(dirpath, fn)))
        else:
            findings.extend(_lint_file(path))
    return findings


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m vitax.analysis.concurrency", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("paths", nargs="*",
                        help="files or directories (default: the vitax/ package)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit findings as a JSON array")
    args = parser.parse_args(argv)

    paths = args.paths or [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
    findings = lint_paths(paths)
    if args.as_json:
        print(json.dumps([f.to_json() for f in findings], indent=2))
    else:
        for f in findings:
            print(f.format())
        if not findings:
            print("concurrency: clean")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
