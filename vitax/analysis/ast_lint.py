"""AST lint for vitax/ source: host-sync and portability bug patterns.

The compiled-program rules in :mod:`vitax.analysis.rules` catch invariant
violations *after* they reach the lowered HLO; this pass catches the Python
idioms that put them there — a `jax.device_get` inside a scanned block, a
`float()` on a traced value, an argless `jax.devices()` that pins library
code to whatever backend initialized first.

Finding codes (Error Prone style: stable ids, CI-greppable):

  VTX100  ERROR  bare `# vtx: ignore[...]` suppression with no reason text
  VTX101  ERROR  jax.device_get / .block_until_ready() inside jit-traced
                 modules (models/, ops/, parallel/, train/step.py) — forces a
                 host sync or is a tracer error at trace time
  VTX102  ERROR  float()/int()/.item() on a jnp/jax expression inside
                 jit-traced modules — concretization error under jit
  VTX103  WARN   two+ time.time()/perf_counter() calls bracketing a
                 dispatch-like call with no fence (block_until_ready,
                 device_get, np.asarray, .result(), .item()) in the same
                 function — times dispatch, not execution
  VTX104  ERROR  argless jax.devices() / jax.local_devices() in library code
                 — platform-order dependent; use vitax.platform helpers or
                 pass an explicit backend
  VTX105  ERROR  mutable default argument (list/dict/set literal or call)
  VTX106  ERROR  broad `except:` / `except Exception:` / `except
                 BaseException:` whose body only passes — swallows every
                 error silently; in a fault-tolerant trainer a swallowed
                 exception becomes an undiagnosable hang or wrong result
                 (narrow excepts like OSError are fine; so is a broad
                 except that logs, re-raises, or otherwise acts)
  VTX107  ERROR  direct `preempt.requested()` / `.escalation_requested()`
                 poll outside the control plane — a host acting on its LOCAL
                 failure flag desynchronizes the pod (one host saves while
                 the others keep stepping -> interleaved collectives ->
                 deadlock); read the AGREED word via vitax/train/control.py
                 ControlPlane.poll instead. The control plane's own two
                 polls are the sanctioned (suppressed) call sites.
  VTX109  ERROR  urllib.request.urlopen / http.client.HTTPConnection /
                 socket.create_connection without an explicit timeout —
                 the stdlib default is block-forever, so one hung peer
                 wedges the calling thread (a health poll, a dispatch, a
                 bench worker) permanently; every network call in the
                 serving/tooling paths must bound its wait
  VTX108  ERROR  `save_state(..., wait=True)` inside a loop body — a
                 synchronous checkpoint write from the step-dispatch region
                 stalls the train loop for the full serialization+write
                 (the exact stall the zero-stall snapshot pipeline exists
                 to remove, vitax/checkpoint/snapshot.py); route the save
                 through SnapshotPipeline.submit, or hoist it out of the
                 loop (the final boundary save may wait — it is not inside
                 one)

Suppression: append `# vtx: ignore[VTX101] <reason>` to the offending line.
Multiple codes: `# vtx: ignore[VTX101,VTX103] <reason>`. A suppression
without a reason is itself an error (VTX100).

Run: `python -m vitax.analysis.ast_lint [paths...] [--json]`
(default path: the vitax/ package directory). Exit 1 on any ERROR finding.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import json
import os
import re
import sys
from typing import Iterable, List, Optional, Sequence, Tuple

# Modules whose function bodies run under jit/scan tracing: host syncs and
# concretizations there are either trace-time errors or silent step stalls.
TRACED_SUBPATHS = (
    os.path.join("vitax", "models") + os.sep,
    os.path.join("vitax", "ops") + os.sep,
    os.path.join("vitax", "parallel") + os.sep,
    os.path.join("vitax", "train", "step.py"),
)

_SUPPRESS_RE = re.compile(r"#\s*vtx:\s*ignore\[([A-Za-z0-9,\s]*)\]\s*(.*)")
_TIMER_CALLS = {"time", "perf_counter", "monotonic"}
_FENCE_TOKENS = ("block_until_ready", "device_get", "asarray", ".result(",
                 ".item(", "np.array(")
_DISPATCH_NAME_RE = re.compile(
    r"(step|predict|compiled|jitted|forward|apply|_run)", re.IGNORECASE)

# VTX109: blocking network constructors/calls -> the 0-based positional
# index where the stdlib signature accepts `timeout` (a call with more
# positionals than that index passed it positionally)
_NET_TIMEOUT_POS = {
    "urlopen": 2,            # urlopen(url, data=None, timeout=...)
    "create_connection": 1,  # socket.create_connection(addr, timeout=...)
    "HTTPConnection": 2,     # HTTPConnection(host, port=..., timeout=...)
    "HTTPSConnection": 2,
}


@dataclasses.dataclass
class Finding:
    code: str
    severity: str  # "ERROR" | "WARN"
    path: str
    line: int
    message: str

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    def format(self) -> str:
        return f"{self.path}:{self.line}: {self.code} [{self.severity}] {self.message}"


def _suppressions(source: str) -> Tuple[dict, List[Finding]]:
    """Map line -> set of suppressed codes; bare suppressions are findings."""
    by_line: dict = {}
    bare: List[Finding] = []
    for lineno, line in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        codes = {c.strip() for c in m.group(1).split(",") if c.strip()}
        reason = m.group(2).strip()
        if not reason or not codes:
            bare.append(Finding(
                "VTX100", "ERROR", "", lineno,
                "bare `# vtx: ignore[...]` — suppressions must carry a reason"))
        else:
            by_line[lineno] = codes
    return by_line, bare


def _dotted(node: ast.AST) -> str:
    """Best-effort dotted name of a call target ('' if not a plain chain)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_jax_expr(node: ast.AST) -> bool:
    """Heuristic: does this expression syntactically involve jnp/jax?"""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in ("jnp", "jax", "lax"):
            return True
    return False


class _Visitor(ast.NodeVisitor):
    def __init__(self, path: str, traced: bool) -> None:
        self.path = path
        self.traced = traced
        self.findings: List[Finding] = []
        # (lineno, kind) events per function for the VTX103 timing check
        self._func_stack: List[List[Tuple[int, str]]] = []
        # loop-nesting depth for the VTX108 in-loop synchronous-save check
        self._loop_depth = 0

    def _add(self, code: str, severity: str, node: ast.AST, msg: str) -> None:
        self.findings.append(
            Finding(code, severity, self.path, node.lineno, msg))

    # -- function-scope bookkeeping -----------------------------------------
    def _visit_func(self, node) -> None:
        for default in list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None]:
            if isinstance(default, (ast.List, ast.Dict, ast.Set)) or (
                    isinstance(default, ast.Call)
                    and _dotted(default.func) in ("list", "dict", "set")):
                self._add("VTX105", "ERROR", default,
                          f"mutable default argument in `{node.name}()`")
        self._func_stack.append([])
        self.generic_visit(node)
        events = self._func_stack.pop()
        self._check_timing(node, events)

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def _visit_loop(self, node) -> None:
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    visit_For = _visit_loop
    visit_AsyncFor = _visit_loop
    visit_While = _visit_loop

    def _check_timing(self, func, events: List[Tuple[int, str]]) -> None:
        timers = [ln for ln, kind in events if kind == "timer"]
        dispatches = [ln for ln, kind in events if kind == "dispatch"]
        fences = [ln for ln, kind in events if kind == "fence"]
        if len(timers) < 2 or not dispatches:
            return
        for d in dispatches:
            before = [t for t in timers if t <= d]
            after = [t for t in timers if t > d]
            if before and after:
                span = (before[-1], after[0])
                if not any(span[0] <= f <= span[1] for f in fences):
                    self._add(
                        "VTX103", "WARN", func,
                        f"`{func.name}()` wraps a dispatch-like call (line {d}) "
                        "in timers with no fence — async dispatch means this "
                        "times submission, not execution")
                    return  # one finding per function is enough

    # -- exception-handler checks -------------------------------------------
    @staticmethod
    def _is_broad_exc(node: Optional[ast.AST]) -> bool:
        """Does this except clause catch everything (bare / Exception /
        BaseException, possibly inside a tuple)?"""
        if node is None:  # bare `except:`
            return True
        if isinstance(node, ast.Tuple):
            return any(_Visitor._is_broad_exc(e) for e in node.elts)
        return (_dotted(node) or "").split(".")[-1] in (
            "Exception", "BaseException")

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        def _noop(stmt: ast.stmt) -> bool:
            # pass, `...`, or a bare string (comment-as-docstring)
            return isinstance(stmt, ast.Pass) or (
                isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, ast.Constant)
                and (stmt.value.value is Ellipsis
                     or isinstance(stmt.value.value, str)))

        body_is_noop = all(_noop(stmt) for stmt in node.body)
        if body_is_noop and self._is_broad_exc(node.type):
            caught = _dotted(node.type) if node.type is not None else ""
            label = f"except {caught}" if caught else "bare except"
            self._add("VTX106", "ERROR", node,
                      f"`{label}` with a pass-only body swallows every error "
                      "silently — catch a narrow type, or log/act on it")
        self.generic_visit(node)

    # -- per-call checks ----------------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        name = _dotted(node.func)
        # `short` must survive chained calls (`jnp.sum(x).block_until_ready()`)
        # where the dotted chain doesn't resolve to a plain name
        if isinstance(node.func, ast.Attribute):
            short = node.func.attr
        elif isinstance(node.func, ast.Name):
            short = node.func.id
        else:
            short = ""

        if self._func_stack:
            events = self._func_stack[-1]
            if name.startswith("time.") and short in _TIMER_CALLS:
                events.append((node.lineno, "timer"))
            elif short in ("block_until_ready", "device_get", "asarray",
                           "result", "item", "array"):
                events.append((node.lineno, "fence"))
            elif _DISPATCH_NAME_RE.search(short or ""):
                events.append((node.lineno, "dispatch"))

        if (name in ("preempt.requested", "vitax.train.preempt.requested")
                or (short == "escalation_requested"
                    and isinstance(node.func, ast.Attribute))):
            self._add("VTX107", "ERROR", node,
                      f"direct `{name or short}()` failure-signal poll — a "
                      "host acting on its local flag desynchronizes the pod; "
                      "read the agreed word via vitax/train/control.py "
                      "ControlPlane.poll instead")

        if short == "save_state" and self._loop_depth > 0 and any(
                kw.arg == "wait" and isinstance(kw.value, ast.Constant)
                and kw.value.value is True for kw in node.keywords):
            self._add("VTX108", "ERROR", node,
                      "`save_state(..., wait=True)` inside a loop body — a "
                      "synchronous checkpoint write stalls the step-dispatch "
                      "region; route it through SnapshotPipeline.submit "
                      "(vitax/checkpoint/snapshot.py) or hoist it out of "
                      "the loop")

        if short in _NET_TIMEOUT_POS:
            has_timeout = (
                any(kw.arg == "timeout" or kw.arg is None  # **kwargs: assume ok
                    for kw in node.keywords)
                or len(node.args) > _NET_TIMEOUT_POS[short])
            if not has_timeout:
                self._add("VTX109", "ERROR", node,
                          f"`{name or short}()` without an explicit timeout "
                          "— the stdlib default blocks forever, so one hung "
                          "peer wedges this thread; pass timeout=")

        if short in ("devices", "local_devices") and name.startswith("jax.") \
                and not node.args and not node.keywords:
            self._add("VTX104", "ERROR", node,
                      f"argless `{name}()` in library code — platform-order "
                      "dependent; use vitax.platform helpers or pass a backend")

        if self.traced:
            if name == "jax.device_get" or short == "block_until_ready":
                self._add("VTX101", "ERROR", node,
                          f"`{name or short}` in jit-traced module — host sync "
                          "inside the step program")
            elif short in ("float", "int") and name in ("float", "int") \
                    and node.args and _is_jax_expr(node.args[0]):
                self._add("VTX102", "ERROR", node,
                          f"`{short}()` on a jax expression in a jit-traced "
                          "module — concretization error under jit")
            elif short == "item" and isinstance(node.func, ast.Attribute) \
                    and _is_jax_expr(node.func.value):
                self._add("VTX102", "ERROR", node,
                          "`.item()` on a jax expression in a jit-traced "
                          "module — concretization error under jit")
        self.generic_visit(node)


def lint_source(source: str, path: str) -> List[Finding]:
    """Lint one file's source text; returns surviving findings."""
    traced = any(sub in path for sub in TRACED_SUBPATHS)
    suppressed, bare = _suppressions(source)
    for f in bare:
        f.path = path
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding("VTX100", "ERROR", path, e.lineno or 1,
                        f"syntax error: {e.msg}")]
    visitor = _Visitor(path, traced)
    visitor.visit(tree)
    out = list(bare)
    for f in visitor.findings:
        if f.code in suppressed.get(f.line, ()):
            continue
        out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.code))
    return out


def lint_paths(paths: Iterable[str]) -> List[Finding]:
    findings: List[Finding] = []
    for path in paths:
        if os.path.isdir(path):
            for dirpath, dirnames, filenames in os.walk(path):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        findings.extend(_lint_file(os.path.join(dirpath, fn)))
        else:
            findings.extend(_lint_file(path))
    return findings


def _lint_file(path: str) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as fh:
        return lint_source(fh.read(), path)


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m vitax.analysis.ast_lint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("paths", nargs="*",
                        help="files or directories (default: the vitax/ package)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit findings as a JSON array")
    args = parser.parse_args(argv)

    paths = args.paths or [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
    findings = lint_paths(paths)
    if args.as_json:
        print(json.dumps([f.to_json() for f in findings], indent=2))
    else:
        for f in findings:
            print(f.format())
        if not findings:
            print("ast_lint: clean")
    return 1 if any(f.severity == "ERROR" for f in findings) else 0


if __name__ == "__main__":
    sys.exit(main())
