"""Parsers over lowered/partitioned XLA programs.

Generalized from the terse-HLO parser that grew inside tools/comm_audit.py
(PRs 2-3) into the shared module every program-invariant rule builds on
(vitax/analysis/rules.py, tools/check_invariants.py; comm_audit now imports
from here).

Two program artifacts, two parsers:

- the **post-`spmd-partitioning` HLO text** (captured via a per-compile
  `xla_dump_to`): collectives with dtype/shape/bytes, while-loop bodies and
  their op inventories, the prefetch-slot overlap verdict, host-transfer ops,
  and the module-header `input_output_alias` donation map. This stage — not
  the final executable — is the backend-independent ground truth: XLA:CPU's
  float normalization rewrites every bf16 collective as f32-wrapped-in-
  converts in the final module, so the final CPU HLO can never show a bf16
  gather no matter what the program asked for.

- the **StableHLO MLIR text** (`lowered.as_text()`): per-argument shardings
  (`mhlo.sharding`) and donation (`tf.aliasing_output`) straight off the
  `@main` signature — available without compiling, and the only artifact
  that still names which arguments are which.
"""

from __future__ import annotations

import collections
import glob
import os
import re
import shutil
import tempfile
from typing import Dict, List, Optional, Tuple

# `= bf16[2,32,128]{...} all-gather(` — dtype, shape, op from a partitioned-HLO
# instruction line. `-start` variants cover async collectives; `-done` halves
# carry no shape of their own and are skipped.
COLLECTIVE_RE = re.compile(
    r"= (\w+)\[([\d,]*)\][^ ]* "
    r"((?:all-gather|all-reduce|reduce-scatter|all-to-all|collective-permute)"
    r"(?:-start)?)\(")

DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f16": 2, "bf16": 2, "s16": 2, "u16": 2,
    "f32": 4, "s32": 4, "u32": 4, "f64": 8, "s64": 8, "u64": 8,
}


def collect_collectives(hlo_text: str) -> List[dict]:
    """Parse a partitioned-HLO module into aggregated collective rows.

    Returns a list of dicts {op, dtype, shape, count, numel, bytes} where
    `bytes` is count * output-shape bytes. Output-shape bytes is the honest
    per-step proxy for wire traffic: an all-gather's output is the gathered
    tensor every participant materializes, an all-reduce/reduce-scatter's
    output is what the reduction moves. (Exact wire bytes carry an extra
    (n-1)/n ring factor that is identical across policies and so cancels in
    every ratio this parser is used for.)
    """
    rows = collections.Counter()
    for m in COLLECTIVE_RE.finditer(hlo_text):
        dtype, shape_s, op = m.groups()
        shape = tuple(int(d) for d in shape_s.split(",") if d)
        rows[(op.replace("-start", ""), dtype, shape)] += 1
    out = []
    for (op, dtype, shape), count in sorted(rows.items()):
        numel = 1
        for d in shape:
            numel *= d
        out.append({
            "op": op, "dtype": dtype, "shape": list(shape), "count": count,
            "numel": numel,
            "bytes": count * numel * DTYPE_BYTES.get(dtype, 4),
        })
    return out


def summarize(rows: List[dict]) -> dict:
    """Totals per op kind, split by element type."""
    totals: dict = {}
    for r in rows:
        slot = totals.setdefault(r["op"], {"count": 0, "bytes": 0, "by_dtype": {}})
        slot["count"] += r["count"]
        slot["bytes"] += r["bytes"]
        d = slot["by_dtype"].setdefault(r["dtype"], {"count": 0, "bytes": 0})
        d["count"] += r["count"]
        d["bytes"] += r["bytes"]
    return totals


def gather_bytes(rows: List[dict], dtype: Optional[str] = None,
                 min_numel: int = 0) -> int:
    """Total all-gather bytes, optionally filtered by dtype / operand size."""
    return sum(r["bytes"] for r in rows
               if r["op"] == "all-gather"
               and (dtype is None or r["dtype"] == dtype)
               and r["numel"] >= min_numel)


def reduce_bytes(rows: List[dict], dtype: Optional[str] = None,
                 min_numel: int = 0) -> int:
    """Total reduce-scatter + all-reduce bytes, same filters as gather_bytes."""
    return sum(r["bytes"] for r in rows
               if r["op"] in ("reduce-scatter", "all-reduce")
               and (dtype is None or r["dtype"] == dtype)
               and r["numel"] >= min_numel)


# ops a value may pass through on its way to the while body's ROOT tuple and
# still count as "sitting on the carry": layout/dtype plumbing, not compute.
# A gather whose result reaches ROOT only through these feeds the next
# iteration's prefetch slot; a gather consumed by a dot/fusion first is a
# use-site gather.
TRIVIAL_OPS = frozenset({
    "copy", "convert", "bitcast", "bitcast-convert", "reshape", "transpose",
    "get-tuple-element", "tuple", "optimization-barrier", "all-gather-done",
})

# `  ROOT name = type op(a, b), attrs...` — name, op, operand list of one
# instruction line. Handles both dump styles: the verbose one (`%name = f32[2]
# add(%a, %b)`) and the terse one XLA emits for pass dumps (`add.3 = f32[2]
# add(p.1, p.2)`); the type may itself be a parenthesised tuple, so the op is
# "the first bare word directly followed by ( after the =".
INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*.*?\s([\w\-]+)\((.*)$")
_OPERAND_RE = re.compile(r"%?([\w.\-]+)")


def split_computations(hlo_text: str) -> Dict[str, List[str]]:
    """Split an HLO module dump into {computation_name: [instruction lines]}.

    Computation headers sit at column 0 and end with `{`: terse style is
    `region_0.574_spmd {` / `ENTRY main.1234_spmd {`, verbose style is
    `%fused (p: f32[2]) -> f32[2] {`. Instruction lines are indented and
    contain `=`, which the header pattern excludes."""
    comps: Dict[str, List[str]] = {}
    name, lines = None, []
    header = re.compile(r"^(?:ENTRY\s+)?%?([\w.\-]+)\b[^=]*{\s*$")
    for line in hlo_text.splitlines():
        if name is None:
            m = header.match(line)
            if m:
                name, lines = m.group(1), []
        elif line.startswith("}"):
            comps[name] = lines
            name = None
        else:
            lines.append(line)
    return comps


def while_bodies(hlo_text: str) -> List[str]:
    """Names of every while-loop body computation, in program order.

    First-occurrence order = program order of the while ops: the forward
    scan's body comes before the backward's, so consumers can key on the
    first entry for forward-schedule invariants."""
    return list(dict.fromkeys(re.findall(r"body=%?([\w.\-]+)", hlo_text)))


def parse_instructions(lines: List[str]) -> Tuple[Dict[str, Tuple[str, List[str]]], Optional[str]]:
    """Parse one computation's instruction lines into
    ({name: (op, [operand names])}, root_name)."""
    instrs: Dict[str, Tuple[str, List[str]]] = {}
    root = None
    for line in lines:
        m = INSTR_RE.match(line)
        if not m:
            continue
        iname, op, rest = m.groups()
        # operand names: %refs up to the closing paren of the operand
        # list (metadata/attrs after it may hold %refs to computations)
        depth, end = 1, len(rest)
        for i, ch in enumerate(rest):
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
                if depth == 0:
                    end = i
                    break
        instrs[iname] = (op, _OPERAND_RE.findall(rest[:end]))
        if line.lstrip().startswith("ROOT"):
            root = iname
    return instrs, root


def while_body_op_inventory(hlo_text: str) -> Dict[str, Dict[str, int]]:
    """Per while-loop body: {op name: count} over its instructions — the
    cheap structural fingerprint of what a scan iteration executes."""
    comps = split_computations(hlo_text)
    out: Dict[str, Dict[str, int]] = {}
    for body in while_bodies(hlo_text):
        lines = comps.get(body)
        if lines is None:
            continue
        instrs, _ = parse_instructions(lines)
        counter: collections.Counter = collections.Counter(
            op for op, _ in instrs.values())
        out[body] = dict(counter)
    return out


def overlap_verdict(hlo_text: str) -> dict:
    """Structural check of the --gather_overlap schedule.

    Locates every while-loop body in the partitioned module and, per body,
    counts its all-gathers and how many of them sit ON THE PREFETCH SLOT:
    their result reaches the body's ROOT tuple (the carry for the next
    iteration) through nothing but layout/dtype plumbing (TRIVIAL_OPS).
    Use-site gathers — what the plain ZeRO-3 scan has — are consumed by a
    convolution/dot/fusion before any carry, so they never qualify.

    Returns {gathers_in_scan_body, prefetch_slot_gathers,
    per_iteration_gather_count: {body: count}, prefetch_slot_by_body} — the
    `--json` overlap verdict the tier-1 suite asserts on (gather count
    unchanged between off and on; prefetch-slot gathers appear only under
    on)."""
    comps = split_computations(hlo_text)
    bodies = while_bodies(hlo_text)

    per_body = {}
    slot_by_body = {}
    for body in bodies:
        lines = comps.get(body)
        if lines is None:
            continue
        instrs, root = parse_instructions(lines)
        gathers = {n for n, (op, _) in instrs.items()
                   if op in ("all-gather", "all-gather-start")}
        per_body[body] = len(gathers)
        slot_by_body[body] = 0
        if root is None or not gathers:
            continue
        on_slot = set()
        seen = set()
        frontier = [root]
        while frontier:
            n = frontier.pop()
            if n in seen or n not in instrs:
                continue
            seen.add(n)
            op, operands = instrs[n]
            if op in ("all-gather", "all-gather-start"):
                on_slot.add(n)
                continue  # the gather IS the slot value; don't look past it
            if n == root or op in TRIVIAL_OPS:
                frontier.extend(operands)
        slot_by_body[body] = len(on_slot)

    return {
        "gathers_in_scan_body": sum(per_body.values()),
        "prefetch_slot_gathers": sum(slot_by_body.values()),
        "per_iteration_gather_count": per_body,
        "prefetch_slot_by_body": slot_by_body,
    }


# --- host transfers ---------------------------------------------------------

# custom-call targets that move data to (or synchronize with) the host: the
# Python callback family (io_callback / pure_callback / jax.debug.print all
# lower to these) on CPU/GPU; outfeed/infeed are the TPU-side carriers.
_HOST_CALLBACK_TARGET_RE = re.compile(
    r'custom_call_target="([^"]*callback[^"]*)"')
_HOST_OP_RE = re.compile(
    r"=\s*(?:\([^)]*\)|\S+)\s+(outfeed|infeed|send|send-done|recv|recv-done)\(")
_MLIR_HOST_RE = re.compile(
    r"stablehlo\.(outfeed|infeed|send|recv)\b|"
    r"stablehlo\.custom_call\s+@(\S*callback\S*)\(")


def host_transfer_ops(hlo_text: str) -> List[dict]:
    """Every host-transfer op in a partitioned-HLO module: outfeed / infeed /
    send / recv instructions and custom-calls into the host-callback family.

    Returns [{op, detail, line}] where `line` is the stripped instruction
    text (truncated) for the finding message."""
    out = []
    for i, line in enumerate(hlo_text.splitlines(), 1):
        m = _HOST_OP_RE.search(line)
        if m:
            out.append({"op": m.group(1), "detail": m.group(1),
                        "line": line.strip()[:160]})
            continue
        m = _HOST_CALLBACK_TARGET_RE.search(line)
        if m:
            out.append({"op": "custom-call", "detail": m.group(1),
                        "line": line.strip()[:160]})
    return out


def mlir_host_transfer_ops(mlir_text: str) -> List[dict]:
    """Host-transfer ops in a StableHLO module (the pre-compile view — works
    on single-device programs the partitioner never touches)."""
    out = []
    for line in mlir_text.splitlines():
        m = _MLIR_HOST_RE.search(line)
        if m:
            op = m.group(1) or "custom_call"
            out.append({"op": op, "detail": m.group(2) or m.group(1),
                        "line": line.strip()[:160]})
    return out


# --- donation (input_output_alias) ------------------------------------------

_ALIAS_ENTRY_RE = re.compile(
    r"\{([\d,\s]*)\}:\s*\((\d+),\s*\{[\d,\s]*\},\s*(may-alias|must-alias)\)")


def input_output_aliases(hlo_text: str) -> List[dict]:
    """Parse the module-header `input_output_alias={ {out}: (param, {idx},
    kind), ... }` donation map from a partitioned-HLO dump.

    Returns [{output_index, parameter, kind}] — one entry per aliased
    (donated and actually reused) buffer. An empty list under donate_argnums
    means XLA dropped every donation (shape/dtype mismatch or a backend that
    refuses aliasing) — exactly the regression the donation rule exists to
    catch."""
    header = hlo_text.splitlines()[0] if hlo_text else ""
    key = "input_output_alias={"
    start = header.find(key)
    if start < 0:
        return []
    # the alias map nests braces ({ {0}: (0, {}, may-alias), ... }): scan to
    # the balancing close instead of regexing across nesting
    i = start + len(key)
    depth, j = 1, i
    while j < len(header) and depth:
        if header[j] == "{":
            depth += 1
        elif header[j] == "}":
            depth -= 1
        j += 1
    out = []
    for om in _ALIAS_ENTRY_RE.finditer(header[i:j - 1]):
        out.append({
            "output_index": tuple(int(x) for x in om.group(1).split(",") if x.strip()),
            "parameter": int(om.group(2)),
            "kind": om.group(3),
        })
    return out


# --- MLIR @main argument table ----------------------------------------------

# dtype tail: lowercase+digits (f32, i8, bf16), an optional uppercase suffix
# for the fp8 family (f8E4M3, f8E5M2, f8E4M3FN), or the braceless i1
_MLIR_TYPE_RE = re.compile(
    r"tensor<([x\d]*?)(?:x)?([a-z]+\d+(?:[A-Z][A-Z0-9]*)?|i1)>")
_MLIR_SHARDING_RE = re.compile(r'mhlo\.sharding\s*=\s*"([^"]*)"')
_MLIR_DONOR_RE = re.compile(r"tf\.aliasing_output\s*=\s*(\d+)")

_MLIR_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "bf16": 2, "f16": 2, "f8": 1,
    "f8E4M3": 1, "f8E5M2": 1, "f8E4M3FN": 1,
    "i64": 8, "ui64": 8, "i32": 4, "ui32": 4, "i16": 2, "ui16": 2,
    "i8": 1, "ui8": 1, "i1": 1,
}


def mlir_main_args(mlir_text: str) -> List[dict]:
    """Argument table of the StableHLO `@main` signature.

    Returns [{index, dtype, shape, numel, bytes, sharding, donated_to}] in
    argument order. `sharding` is the raw OpSharding string ("{replicated}",
    "{devices=[1,8]<=[8]}", ...) or None when unannotated; `donated_to` is
    the flat output index the buffer is donated to (`tf.aliasing_output`)
    or None for non-donated args. This is the only artifact where donation
    and sharding are still attached to *arguments* rather than anonymous
    parameter numbers."""
    m = re.search(r"func\.func\s+public\s+@main\s*\((.*?)\)\s*->", mlir_text,
                  re.DOTALL)
    if not m:
        return []
    # split the signature on argument boundaries: everything between
    # `%argN:` and the next `%argM:` (type + attr dict) belongs to arg N —
    # sidesteps brace-matching the attr dict, whose sharding strings nest
    # braces inside quotes
    parts = re.split(r"%arg(\d+)\s*:", m.group(1))
    out = []
    for i in range(1, len(parts) - 1, 2):
        idx = int(parts[i])
        body = parts[i + 1]
        tm = _MLIR_TYPE_RE.search(body)
        shape: Tuple[int, ...] = ()
        dtype = "?"
        if tm:
            shape = tuple(int(d) for d in tm.group(1).split("x") if d)
            dtype = tm.group(2)
        sm = _MLIR_SHARDING_RE.search(body)
        dm = _MLIR_DONOR_RE.search(body)
        numel = 1
        for d in shape:
            numel *= d
        out.append({
            "index": idx, "dtype": dtype, "shape": list(shape),
            "numel": numel,
            "bytes": numel * _MLIR_DTYPE_BYTES.get(dtype, 4),
            "sharding": sm.group(1) if sm else None,
            "donated_to": int(dm.group(1)) if dm else None,
        })
    return out


def sharding_is_replicated(sharding: Optional[str]) -> bool:
    """Whether an OpSharding string places the value on every device whole.

    None (unannotated) counts as replicated: GSPMD's default for an
    unconstrained input is replication, which is precisely the silent
    regression the large-param rule hunts."""
    if sharding is None:
        return True
    s = sharding.strip()
    if "replicated" in s or "maximal" in s:
        return "devices=" not in s
    # "{devices=[1,1,8]<=[8] last_tile_dim_replicate}" with ALL non-trailing
    # tile dims 1 is also full replication
    m = re.search(r"devices=\[([\d,]+)\]", s)
    if m:
        dims = [int(d) for d in m.group(1).split(",")]
        if "last_tile_dim_replicate" in s:
            dims = dims[:-1]
        return all(d == 1 for d in dims)
    return False


# --- program capture --------------------------------------------------------


def capture_partitioned(lowered, module_hint: str = "train_step") -> str:
    """Compile a `jax.stages.Lowered` with a per-compile dump and return the
    HLO module text right after the SPMD partitioner.

    Why this stage and not the final executable: backend simplification
    passes may rewrite collective element types after SPMD partitioning.
    XLA:CPU's float normalization in particular rewrites every bf16
    collective as an f32 collective wrapped in converts, so the final CPU
    HLO can never show a bf16 gather no matter what the program asked for.
    The post-`spmd-partitioning` module is the backend-independent ground
    truth for what dtype each collective moves.

    Returns "" for single-device programs (the partitioner never runs, so
    there is no dump — and no collectives to audit either)."""
    dump_dir = tempfile.mkdtemp(prefix="vitax_analysis_hlo_")
    try:
        lowered.compile(
            compiler_options={"xla_dump_to": dump_dir,
                              "xla_dump_hlo_pass_re": ".*partitioning"})
        dumps = glob.glob(os.path.join(dump_dir, "*after_spmd-partitioning*"))
        preferred = [f for f in dumps if module_hint in os.path.basename(f)]
        if not preferred:  # fall back to the largest module (the step)
            preferred = sorted(dumps, key=os.path.getsize)[-1:]
        if not preferred:
            import jax
            if len(jax.devices()) == 1:  # vtx: ignore[VTX104] analysis tool probing whatever backend is live
                return ""
            raise RuntimeError(
                f"no post-partitioning HLO dump appeared in {dump_dir}; "
                "this XLA build may not honour per-compile xla_dump_to")
        with open(preferred[0], encoding="utf-8") as f:
            return f.read()
    finally:
        shutil.rmtree(dump_dir, ignore_errors=True)


def _build_train_step(cfg, max_iteration: int, donate: bool):
    """Shared builder for the AOT surfaces: returns
    (step, (state, batch, rng) abstract args, n_state_leaves)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    from vitax.models import build_model
    from vitax.ops.attention import make_attention_impl
    from vitax.parallel.mesh import batch_pspec, build_mesh
    from vitax.train.loop import _token_sharding
    from vitax.train.state import build_optimizer, make_train_state
    from vitax.train.step import make_train_step

    mesh = build_mesh(cfg)
    model = build_model(cfg, attention_impl=make_attention_impl(cfg, mesh),
                        token_sharding=_token_sharding(cfg, mesh))
    tx, schedule = build_optimizer(cfg, max_iteration=max_iteration)
    state, sspecs, _ = make_train_state(cfg, model, tx, mesh,
                                        jax.random.key(cfg.seed),
                                        materialize=False)
    step = make_train_step(cfg, model, tx, mesh, sspecs, donate=donate,
                           schedule=schedule)
    sh = NamedSharding(mesh, batch_pspec())
    batch = {
        "image": jax.ShapeDtypeStruct(
            (cfg.batch_size, cfg.image_size, cfg.image_size, 3),
            jnp.float32, sharding=sh),
        "label": jax.ShapeDtypeStruct((cfg.batch_size,), jnp.int32,
                                      sharding=sh),
    }
    args = (state, batch, jax.random.key(cfg.seed + 1))
    return step, args, len(jax.tree_util.tree_leaves(state))


def lower_train_step(cfg, max_iteration: int = 10_000, donate: bool = True):
    """AOT-lower the train step for `cfg` on the current backend.

    Returns (lowered, n_state_leaves): the `jax.stages.Lowered` step and the
    number of TrainState leaves (the donation rule's expected aliased-buffer
    count). `donate=False` builds the same program without donate_argnums —
    the deliberately-broken arm the donation rule's negative test compiles.
    """
    step, args, n_state_leaves = _build_train_step(cfg, max_iteration, donate)
    return step.lower(*args), n_state_leaves


def train_step_jaxpr(cfg, max_iteration: int = 10_000) -> str:
    """Trace the train step for `cfg` and return its closed jaxpr as text.

    The jaxpr — not StableHLO — is the artifact the fused-optimizer rule
    (VTX-R008) reads: Pallas interpret mode (the only lowering available
    off-TPU in this jax) leaves no custom-call marker in MLIR, but every
    `pallas_call` jaxpr equation prints the kernel function's name, and the
    surrounding equations still show any param-sized post-clip temporaries
    the fusion was supposed to eliminate."""
    step, args, _ = _build_train_step(cfg, max_iteration, donate=True)
    return str(step.trace(*args).jaxpr)


# `c:f32[256,96] = sqrt b` — binder dtype/shape and primitive name of a jaxpr
# equation, for the ops VTX-R008 bans at param size outside the fused kernel
JAXPR_EQN_RE = re.compile(r":f32\[([\d,]*)\] = (sqrt|select_n)\b")


def strip_bracketed(text: str, marker: str) -> str:
    """Remove every `marker[...]` block (bracket-matched, nests fine) from
    jaxpr text — used to drop `pallas_call[...]` equation params, whose
    embedded kernel jaxpr would otherwise alias the ops the fused-optimizer
    rule scans for OUTSIDE the kernel."""
    out = []
    i = 0
    while True:
        j = text.find(marker + "[", i)
        if j < 0:
            out.append(text[i:])
            return "".join(out)
        out.append(text[i:j + len(marker)])
        k = j + len(marker)
        depth = 0
        while k < len(text):
            if text[k] == "[":
                depth += 1
            elif text[k] == "]":
                depth -= 1
                if depth == 0:
                    break
            k += 1
        i = k + 1


def jaxpr_oversized_eqns(jaxpr_text: str, min_elems: int) -> List[dict]:
    """Equations (sqrt / select_n, the optax adamw + clip tell-tales) whose
    f32 output has >= min_elems elements, AFTER stripping pallas_call params.
    Returns rows {op, shape, numel} for the rule's finding details."""
    stripped = strip_bracketed(jaxpr_text, "pallas_call")
    rows = []
    for m in JAXPR_EQN_RE.finditer(stripped):
        dims, op = m.group(1), m.group(2)
        numel = 1
        for d in dims.split(","):
            if d:
                numel *= int(d)
        if numel >= min_elems:
            rows.append({"op": op, "shape": dims, "numel": numel})
    return rows


# eqn params that embed sub-jaxprs with their OWN variable namespaces: strip
# them before building a var -> dtype map, or an inner binder reusing an
# outer name would mislabel operands (the scan body restarts at `a`)
JAXPR_SUBJAXPR_MARKERS = (
    "pallas_call", "scan", "while", "cond", "remat2",
    "custom_vjp_call_jaxpr", "custom_vjp_call", "custom_jvp_call",
    "pjit", "shard_map")

# `a:i8[2,32,96]` — any binder (lambda header or eqn output), dtype + dims
_JAXPR_BINDER_RE = re.compile(r"(\w+):([a-z][a-z0-9_]*)\[([\d,]*)\]")
# `c:f32[2,32,96] = convert_element_type[new_dtype=float32 ...] a`
_JAXPR_CONVERT_RE = re.compile(
    r"\w+:f32\[([\d,]*)\] = convert_element_type\[[^\]]*\]\s+(\w+)")


def jaxpr_quant_dequant_converts(jaxpr_text: str, min_elems: int,
                                 exempt_shapes=()) -> List[dict]:
    """Weight-sized dequantizations OUTSIDE the fused kernel: f32
    `convert_element_type` equations whose operand is a quantized-dtype var
    (i8 / f8_*; u8 is excluded — uint8 images legitimately convert) with
    >= min_elems elements, after stripping every sub-jaxpr body. The
    VTX-R009 tell-tale: a fused serve program dequantizes weight blocks only
    inside pallas_call, so any such convert at the top level is a weight
    tensor round-tripping through HBM in float. `exempt_shapes` (dim tuples)
    skips the sites allowed to dequant in-graph — the patchify conv kernel,
    which no Dense-site kernel consumes. Returns rows {src_dtype, shape,
    numel} for the rule's finding details."""
    text = jaxpr_text
    for marker in JAXPR_SUBJAXPR_MARKERS:
        text = strip_bracketed(text, marker)
    dtypes = {}
    for m in _JAXPR_BINDER_RE.finditer(text):
        dtypes.setdefault(m.group(1), m.group(2))
    exempt = {tuple(s) for s in exempt_shapes}
    rows = []
    for m in _JAXPR_CONVERT_RE.finditer(text):
        dims = tuple(int(d) for d in m.group(1).split(",") if d)
        src = dtypes.get(m.group(2), "")
        if not src.startswith(("i8", "f8")):
            continue
        numel = 1
        for d in dims:
            numel *= d
        if numel >= min_elems and dims not in exempt:
            rows.append({"src_dtype": src, "shape": list(dims),
                         "numel": numel})
    return rows


def partitioned_hlo_text(cfg, max_iteration: int = 10_000) -> str:
    """AOT-lower the train step for `cfg` and return the post-partitioning
    HLO module text (the tools/comm_audit.py entry point, kept here so the
    audit and the invariant verifier share one lowering path)."""
    lowered, _ = lower_train_step(cfg, max_iteration=max_iteration)
    return capture_partitioned(lowered)
