"""vitax.analysis — static analysis of compiled SPMD programs and source.

The correctness-tooling layer for the perf invariants landed so far: every
win (bf16 collectives, overlapped ZeRO-3 gathers, host-side-only telemetry,
zero-recompile serve buckets, buffer donation) is a property of the *lowered
program*, not of any Python object a unit test can poke — so the only place
they are checkable is a static pass over the partitioner's output (the GSPMD
lineage: the partitioned module IS the real program).

Three pieces:

  hlo       terse-HLO / StableHLO-MLIR parsers: collectives with dtype/bytes,
            while-body op inventories, input_output_alias donation info,
            host-transfer ops, per-arg shardings (generalized from the parser
            previously private to tools/comm_audit.py)
  rules     declarative rule registry: each rule is (id, severity,
            applies_to(config), check(program, config) -> findings); built-ins
            cover host transfers, donation, collective dtype policy, gather
            overlap structure, replicated large params, serve recompiles
  ast_lint  AST pass over vitax/ source with VTX-coded findings (host syncs in
            jit-traced code, unfenced timing, argless jax.devices(), mutable
            default args); `# vtx: ignore[VTXnnn] <reason>` suppressions

Entry points: `python -m vitax.analysis.ast_lint` (source lint) and
`python tools/check_invariants.py` (program verifier, the CI gate).
"""
