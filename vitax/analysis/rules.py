"""Declarative SPMD program-invariant rules.

Every perf win in this repo is a property of the *lowered program* — bf16
collectives (PR 2), prefetch-slot gathers (PR 3), host-side-only telemetry
(PR 4), zero-recompile serve buckets (PR 5), buffer donation — so a future
refactor can silently regress any of them without a unit test noticing. Each
rule here turns one such folklore invariant into a checkable gate (the Error
Prone model: bug patterns as compile-time checks), run over the programs
`build_train_program` / `build_serve_program` lower across the parallelism
arms (tools/check_invariants.py is the CLI/CI entry).

A rule is declarative data: (id, severity, kinds, applies_to(config),
check(program, config) -> findings). `applies_to` filters by configuration
(e.g. the collective-dtype rule only binds when the bf16 comm-cast policy is
active); `check` parses the program artifacts via vitax.analysis.hlo. Rules
never mutate the program; findings carry enough detail for a CI log to be
actionable without rerunning.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

from vitax.analysis import hlo
from vitax.config import Config

SEVERITIES = ("ERROR", "WARN")


@dataclasses.dataclass
class Finding:
    """One rule violation in one program."""
    rule: str
    severity: str
    arm: str
    message: str
    details: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Program:
    """One lowered program plus the artifacts the rules parse.

    kind "train": `mlir` (lowered StableHLO, always present),
    `partitioned_hlo` (post-SPMD-partitioning dump; "" on single-device
    meshes where the partitioner never runs), and `jaxpr` (traced-jaxpr
    text, captured only on fused-optimizer arms — interpret-mode Pallas
    leaves no custom-call marker in MLIR, so VTX-R008 reads the jaxpr).
    kind "serve": a warmed-up InferenceEngine (the AOT bucket invariants
    are runtime properties of the executable set, not of any one module's
    text)."""
    kind: str                     # "train" | "serve"
    arm: str
    config: Config
    mlir: str = ""
    partitioned_hlo: str = ""
    jaxpr: str = ""
    mesh_shape: Dict[str, int] = dataclasses.field(default_factory=dict)
    n_state_leaves: int = 0
    engine: Any = None
    # scenario freeze evidence (vitax/programs/builder.py freeze_report,
    # captured on probe/distill arms): '/'-joined param paths the task
    # freezes, and the param subpath of every optimizer moment (mu/nu) leaf
    # that exists in the abstract opt_state — VTX-R010's inputs
    frozen_paths: Tuple[str, ...] = ()
    opt_moment_paths: Tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str                       # stable VTX-Rnnn code (CI contract)
    name: str                     # kebab-case human handle
    severity: str                 # ERROR fails CI; WARN is advisory
    kinds: Tuple[str, ...]        # program kinds the rule reads
    description: str
    applies_to: Callable[[Config], bool]
    check: Callable[[Program, Config], List[Finding]]

    def applicable(self, program: Program) -> bool:
        return (program.kind in self.kinds
                and self.applies_to(program.config))


RULES: List[Rule] = []


def rule(id: str, name: str, severity: str, kinds: Tuple[str, ...],
         description: str, applies_to: Callable[[Config], bool] = lambda cfg: True):
    """Register a check function as a Rule."""
    assert severity in SEVERITIES, severity

    def wrap(fn: Callable[[Program, Config], List[Finding]]) -> Rule:
        r = Rule(id=id, name=name, severity=severity, kinds=tuple(kinds),
                 description=description, applies_to=applies_to, check=fn)
        assert all(existing.id != id for existing in RULES), f"duplicate {id}"
        RULES.append(r)
        return r

    return wrap


def _finding(r: Rule, program: Program, message: str, **details) -> Finding:
    return Finding(rule=r.id, severity=r.severity, arm=program.arm,
                   message=message, details=details)


def large_param_threshold_bytes(cfg: Config) -> int:
    """Size above which a replicated parameter is a sharding regression: one
    f32 block matmul matrix (embed_dim^2 * 4). Everything the fsdp axis is
    meant to shard is at least this big; everything legitimately replicated
    (LN scales, cls token, small pos embeds, the step counter) is far
    smaller."""
    return cfg.embed_dim * cfg.embed_dim * 4


# --- built-in rules ---------------------------------------------------------


@rule("VTX-R001", "no-host-transfer-in-step", "ERROR", ("train",),
      "the compiled train step must not move data to the host: no outfeed/"
      "infeed/send/recv, no host-callback custom-calls (a stray jax.debug."
      "print or io_callback serializes every step on a device->host sync; "
      "telemetry is host-side by contract, PR 4)")
def check_no_host_transfer(program: Program, cfg: Config) -> List[Finding]:
    r = NO_HOST_TRANSFER
    ops = (hlo.host_transfer_ops(program.partitioned_hlo)
           if program.partitioned_hlo
           else hlo.mlir_host_transfer_ops(program.mlir))
    return [
        _finding(r, program,
                 f"host transfer in compiled step: {o['op']} ({o['detail']})",
                 instruction=o["line"])
        for o in ops
    ]


@rule("VTX-R002", "donation-honored", "ERROR", ("train",),
      "donate_argnums on the train state must survive to the executable: "
      "every state leaf aliased input->output (a dropped donation doubles "
      "the optimizer-state footprint silently)")
def check_donation(program: Program, cfg: Config) -> List[Finding]:
    r = DONATION_HONORED
    out: List[Finding] = []
    args = hlo.mlir_main_args(program.mlir)
    donated = [a for a in args if a["donated_to"] is not None]
    if len(donated) < program.n_state_leaves:
        out.append(_finding(
            r, program,
            f"only {len(donated)} of {program.n_state_leaves} state buffers "
            f"are marked donated in the lowered program (donate_argnums "
            f"dropped or not set)",
            donated=len(donated), expected=program.n_state_leaves))
    if program.partitioned_hlo:
        aliases = hlo.input_output_aliases(program.partitioned_hlo)
        if len(aliases) < program.n_state_leaves:
            out.append(_finding(
                r, program,
                f"compiler honored only {len(aliases)} of "
                f"{program.n_state_leaves} donations (input_output_alias "
                f"header) — XLA refused aliasing for the rest",
                aliased=len(aliases), expected=program.n_state_leaves))
    return out


@rule("VTX-R003", "collective-dtype-policy", "ERROR", ("train",),
      "under the bf16 comm-precision policy every block-sized param "
      "all-gather must move bf16 (and block-sized grad reductions bf16 when "
      "--grad_reduce_dtype bfloat16): an f32 collective doubles wire bytes "
      "— the PR 2 win regressing silently",
      applies_to=lambda cfg: cfg.comm_cast_active)
def check_collective_dtype(program: Program, cfg: Config) -> List[Finding]:
    r = COLLECTIVE_DTYPE
    if not program.partitioned_hlo:
        return []  # single-device program: no collectives to police
    out: List[Finding] = []
    rows = hlo.collect_collectives(program.partitioned_hlo)
    block_numel = cfg.embed_dim * cfg.embed_dim  # smallest block matmul param
    for row in rows:
        if (row["op"] == "all-gather" and row["dtype"] == "f32"
                and row["numel"] >= block_numel):
            out.append(_finding(
                r, program,
                f"f32 block-param all-gather under the bf16 gather policy: "
                f"{row['count']}x {row['shape']} ({row['bytes']:,} B/step)",
                collective=row))
        if (cfg.grad_reduce_dtype == "bfloat16"
                and row["op"] in ("reduce-scatter", "all-reduce")
                and row["dtype"] == "f32" and row["numel"] >= block_numel):
            out.append(_finding(
                r, program,
                f"f32 block-sized grad {row['op']} under --grad_reduce_dtype "
                f"bfloat16: {row['count']}x {row['shape']} "
                f"({row['bytes']:,} B/step)",
                collective=row))
    return out


def _overlap_requested(cfg: Config) -> bool:
    """Config-only restriction of sharding.gather_overlap_active: `on`, or
    `auto` with every config-side precondition met (the mesh-side fsdp>1
    condition is re-checked in the rule body against program.mesh_shape)."""
    mode = getattr(cfg, "gather_overlap", "auto")
    if mode == "off":
        return False
    if mode == "on":
        return True
    return (cfg.reshard_after_forward and not cfg.run_without_fsdp
            and cfg.scan_blocks and cfg.grad_ckpt
            and cfg.remat_policy == "none_saveable"
            and getattr(cfg, "pp_size", 1) == 1)


@rule("VTX-R004", "gather-overlap-structure", "ERROR", ("train",),
      "with --gather_overlap active, every per-iteration forward all-gather "
      "must sit on the scan carry's prefetch slot (reach the while body ROOT "
      "through layout plumbing only) — a use-site gather means the double "
      "buffering silently degraded to the serial schedule (PR 3)",
      applies_to=_overlap_requested)
def check_gather_overlap(program: Program, cfg: Config) -> List[Finding]:
    r = GATHER_OVERLAP
    if program.mesh_shape.get("fsdp", 1) <= 1:
        return []  # nothing to overlap on an unsharded fsdp axis
    verdict = hlo.overlap_verdict(program.partitioned_hlo)
    per_body = verdict["per_iteration_gather_count"]
    if not per_body:
        return [_finding(r, program,
                         "no while-loop body with gathers found — the "
                         "overlap schedule did not lower to a scanned "
                         "program at all", verdict=verdict)]
    # the first while body in program order is the forward scan
    fwd = next(iter(per_body))
    n_gathers = per_body[fwd]
    on_slot = verdict["prefetch_slot_by_body"].get(fwd, 0)
    if n_gathers == 0:
        return [_finding(r, program,
                         f"forward scan body {fwd} issues no per-iteration "
                         "gathers — ZeRO-3 per-block gathers were hoisted "
                         "or lost", verdict=verdict)]
    if on_slot != n_gathers:
        return [_finding(
            r, program,
            f"{n_gathers - on_slot} of {n_gathers} forward in-loop gathers "
            f"are use-site gathers (not on the prefetch slot): the overlap "
            f"schedule regressed to serial gather-then-compute",
            verdict=verdict)]
    return []


@rule("VTX-R005", "no-replicated-large-params", "ERROR", ("train",),
      "under fsdp arms no state buffer above one block-matrix in size may "
      "lower fully replicated: a replicated 10B tree is an instant HBM OOM "
      "at flagship scale and a silent memory regression at any scale",
      applies_to=lambda cfg: not cfg.run_without_fsdp and cfg.fsdp_size != 1)
def check_no_replicated_large_params(program: Program, cfg: Config) -> List[Finding]:
    r = NO_REPLICATED_LARGE
    if program.mesh_shape.get("fsdp", 1) <= 1:
        return []  # the resolved mesh has no sharding capacity to demand
    threshold = large_param_threshold_bytes(cfg)
    out: List[Finding] = []
    for a in hlo.mlir_main_args(program.mlir):
        if a["donated_to"] is None:
            continue  # donated args are exactly the state buffers
        if a["bytes"] >= threshold and hlo.sharding_is_replicated(a["sharding"]):
            out.append(_finding(
                r, program,
                f"state buffer arg{a['index']} ({a['dtype']}{a['shape']}, "
                f"{a['bytes']:,} B) lowers fully replicated under an fsdp "
                f"mesh (sharding={a['sharding']})",
                arg=a, threshold_bytes=threshold))
    return out


@rule("VTX-R006", "serve-no-recompile", "ERROR", ("serve",),
      "steady-state serving must never compile: after warmup, compile count "
      "== bucket count, mixed-size traffic reuses the AOT executables, and "
      "a bucket executable rejects shapes it was not compiled for (PR 5)")
def check_serve_no_recompile(program: Program, cfg: Config) -> List[Finding]:
    r = SERVE_NO_RECOMPILE
    import numpy as np
    eng = program.engine
    out: List[Finding] = []
    expected = len(eng.buckets)
    if eng.compile_count != expected:
        out.append(_finding(
            r, program,
            f"compile_count {eng.compile_count} != bucket count {expected} "
            f"after warmup",
            compile_count=eng.compile_count, buckets=list(eng.buckets)))
    s = cfg.image_size
    before = eng.compile_count
    # mixed-size traffic: exact smallest, exact largest, and one off-bucket
    # size that must pad rather than compile
    sizes = sorted({1, eng.buckets[-1], min(3, eng.buckets[-1])})
    for n in sizes:
        eng.predict(np.zeros((n, s, s, 3), np.uint8))
    if eng.compile_count != before:
        out.append(_finding(
            r, program,
            f"serving traffic of sizes {sizes} triggered "
            f"{eng.compile_count - before} recompile(s)",
            sizes=sizes, compiles=eng.compile_count - before))
    # the AOT executables must reject unseen shapes instead of silently
    # recompiling for them
    b0 = eng.buckets[0]
    try:
        import jax
        bad = np.zeros((b0, s + 1, s + 1, 3), np.uint8)
        eng._compiled[b0](
            eng.params, jax.device_put(bad, eng._batch_shardings[b0]))
        out.append(_finding(
            r, program,
            f"bucket-{b0} executable accepted an unseen input shape "
            f"{bad.shape} — recompiles are not structurally impossible"))
    except Exception:  # vtx: ignore[VTX106] rejection IS the pass condition of this probe
        pass
    return out


# how each QUANT_DTYPES entry spells in the lowered StableHLO arg table and
# as a device-resident numpy dtype (R007 audits both representations)
QUANT_MLIR_DTYPES = {"int8": "i8", "float8_e4m3": "f8E4M3"}


def _quant_np_dtype(quant_dtype: str):
    import numpy as np
    if quant_dtype == "int8":
        return np.dtype(np.int8)
    import ml_dtypes
    return np.dtype(ml_dtypes.float8_e4m3)


@rule("VTX-R007", "quant-weights-resident", "ERROR", ("serve",),
      "a quantized serve program must hold its matmul weights AT THE QUANT "
      "DTYPE: every manifested leaf int8/fp8 on device, the lowered program "
      "taking exactly one quant-dtype argument per scaled leaf, and no "
      "floating weight argument at or above block-matrix size (a dequant "
      "hoisted out of jit materializes the f32 copy the quantized export "
      "exists to avoid — 4x the HBM, silently)",
      applies_to=lambda cfg: bool(getattr(cfg, "serve_quant_dtype", "")))
def check_quant_weights_resident(program: Program, cfg: Config) -> List[Finding]:
    r = QUANT_WEIGHTS_RESIDENT
    import numpy as np
    eng = program.engine
    out: List[Finding] = []
    scales = getattr(eng, "scales", {})
    if not scales:
        return [_finding(
            r, program,
            f"--serve_quant_dtype {cfg.serve_quant_dtype} but the engine "
            f"carries no quant scales — serving full-precision weights")]
    want = cfg.serve_quant_dtype
    want_np = _quant_np_dtype(want)
    want_mlir = QUANT_MLIR_DTYPES[want]
    # (1) device residency: every scaled leaf must actually be the quant
    # dtype — a float leaf paired with a scale is a dequant that happened
    # at load time
    from vitax.checkpoint.consolidate import flatten_tree
    for key, leaf in flatten_tree(eng.params).items():
        if key in scales and np.dtype(leaf.dtype) != want_np:
            out.append(_finding(
                r, program,
                f"scaled leaf {key} is resident as {leaf.dtype}, not {want} "
                f"— dequantized outside the jitted program",
                key=key, dtype=str(leaf.dtype)))
    # (2) the lowered program's weight operands: one quant-dtype argument
    # per scaled leaf, and no block-sized floating argument (pos_embed and
    # LN leaves sit far below the threshold at every geometry; uint8 images
    # lower as ui8, which never collides with i8)
    mlir = eng.lower_bucket_mlir(eng.buckets[-1])
    args = hlo.mlir_main_args(mlir)
    n_q = sum(1 for a in args if a["dtype"] == want_mlir)
    if n_q != len(scales):
        out.append(_finding(
            r, program,
            f"lowered program has {n_q} {want_mlir} arguments for "
            f"{len(scales)} scaled leaves — quantized weights are not "
            f"entering the program as {want}",
            quant_args=n_q, scaled_leaves=len(scales)))
    threshold = large_param_threshold_bytes(cfg)
    for a in args:
        if a["dtype"] in ("f32", "f64", "bf16", "f16") and a["bytes"] >= threshold:
            out.append(_finding(
                r, program,
                f"block-sized floating argument arg{a['index']} "
                f"({a['dtype']}{a['shape']}, {a['bytes']:,} B) in the "
                f"quantized serve program — a materialized dequantized "
                f"weight",
                arg=a, threshold_bytes=threshold))
    return out


def _fused_active(cfg: Config) -> bool:
    """Config-side gate for VTX-R008: the resolved --fused_optimizer policy
    (lazy import — rules.py stays importable without pulling in jax)."""
    from vitax.ops.fused_optimizer import fused_optimizer_active
    return fused_optimizer_active(cfg)


@rule("VTX-R008", "fused-optimizer-lowered", "ERROR", ("train",),
      "with the fused optimizer active the traced train step must actually "
      "launch the fused AdamW Pallas kernel AND leave no post-clip "
      "param-sized f32 temporary chain: sqrt / select_n equations at "
      "parameter size outside the kernel are the optax adamw / per-leaf "
      "clip tell-tales of the one-pass update silently regressing to the "
      "tree-of-ops chain (same perf-properties-are-CI discipline as "
      "R004/R007)",
      applies_to=_fused_active)
def check_fused_optimizer(program: Program, cfg: Config) -> List[Finding]:
    r = FUSED_OPTIMIZER
    from vitax.ops.fused_optimizer import FUSED_KERNEL_NAME
    if not program.jaxpr:
        return [_finding(
            r, program,
            "fused-optimizer arm lowered without a traced-jaxpr artifact — "
            "the rule has nothing to audit (build_train_program captures "
            "Program.jaxpr whenever the fused policy resolves on)")]
    out: List[Finding] = []
    n_launches = program.jaxpr.count(FUSED_KERNEL_NAME)
    if n_launches == 0:
        out.append(_finding(
            r, program,
            f"traced train step contains no {FUSED_KERNEL_NAME} pallas_call "
            f"— the fused optimizer did not enter the compiled program",
            kernel=FUSED_KERNEL_NAME))
    min_elems = large_param_threshold_bytes(cfg) // 4  # f32 elements
    for row in hlo.jaxpr_oversized_eqns(program.jaxpr, min_elems):
        out.append(_finding(
            r, program,
            f"param-sized f32 {row['op']} over [{row['shape']}] "
            f"({row['numel']:,} elems) outside the fused kernel — an "
            f"optimizer temporary the one-pass update should have "
            f"eliminated",
            eqn=row, min_elems=min_elems))
    return out


def _fused_dequant_cfg(cfg: Config) -> bool:
    """Config-side gate for VTX-R009: the resolved --fused_dequant policy
    (lazy import, same shape as VTX-R008's gate)."""
    from vitax.ops.dequant_matmul import fused_dequant_active
    return (bool(getattr(cfg, "serve_quant_dtype", ""))
            and fused_dequant_active(cfg))


@rule("VTX-R009", "fused-dequant-lowered", "ERROR", ("serve",),
      "with the fused dequant-matmul active the traced serve program must "
      "actually launch the Pallas kernel AND materialize no weight-sized "
      "float tensor sourced from a quantized dtype outside it: a top-level "
      "i8/fp8 -> f32 convert at block size is a dequantized weight round-"
      "tripping through HBM — the fusion silently regressing to the "
      "convert+dot chain (the serve twin of VTX-R008)",
      applies_to=_fused_dequant_cfg)
def check_fused_dequant(program: Program, cfg: Config) -> List[Finding]:
    r = FUSED_DEQUANT
    from vitax.ops.dequant_matmul import DEQUANT_KERNEL_NAME
    eng = program.engine
    jaxpr = eng.trace_bucket_jaxpr(eng.buckets[-1])
    out: List[Finding] = []
    n_launches = jaxpr.count(DEQUANT_KERNEL_NAME)
    if n_launches == 0:
        out.append(_finding(
            r, program,
            f"traced serve program contains no {DEQUANT_KERNEL_NAME} "
            f"pallas_call — the fused dequant-matmul did not enter the "
            f"compiled program",
            kernel=DEQUANT_KERNEL_NAME))
    min_elems = large_param_threshold_bytes(cfg) // 4  # f32 elements
    # the patchify conv kernel is the one quantized leaf no Dense site
    # consumes: it legitimately dequantizes in-graph (XLA fuses the convert
    # into the conv's operand read) and is exempt by its exact shape
    p = cfg.patch_size
    exempt = ((p, p, 3, cfg.embed_dim),)
    for row in hlo.jaxpr_quant_dequant_converts(jaxpr, min_elems, exempt):
        out.append(_finding(
            r, program,
            f"weight-sized dequant outside the fused kernel: "
            f"{row['src_dtype']} -> f32 over {row['shape']} "
            f"({row['numel']:,} elems) at the top level of the serve "
            f"program",
            eqn=row, min_elems=min_elems))
    return out


def _frozen_task(cfg: Config) -> bool:
    """Config-side gate for VTX-R010: scenarios that freeze parameters."""
    return getattr(cfg, "task", "train") in ("probe", "distill")


@rule("VTX-R010", "frozen-params-not-updated", "ERROR", ("train",),
      "a scenario that freezes parameters (--task probe: the backbone; "
      "--task distill: the whole teacher tower) must not give any frozen "
      "leaf optimizer moments — optax.masked drops masked-out positions to "
      "leafless MaskedNodes, so a frozen leaf acquiring a mu/nu slot means "
      "the mask silently stopped covering it and AdamW is stepping a "
      "'frozen' parameter; distill programs must additionally carry the "
      "teacher forward under stop_gradient in the traced jaxpr",
      applies_to=_frozen_task)
def check_frozen_not_updated(program: Program, cfg: Config) -> List[Finding]:
    r = FROZEN_NOT_UPDATED
    out: List[Finding] = []
    if not program.frozen_paths:
        out.append(_finding(
            r, program,
            "frozen-scenario program carries no frozen-path evidence — "
            "build_train_program captures freeze_report() on probe/distill "
            "arms; nothing to audit",
            task=getattr(cfg, "task", "train")))
        return out
    frozen = program.frozen_paths
    for m in program.opt_moment_paths:
        if any(m == f or m.startswith(f + "/") for f in frozen):
            out.append(_finding(
                r, program,
                f"optimizer moment exists for frozen leaf {m!r}: the "
                f"freeze mask does not cover it and AdamW will step it",
                moment_path=m))
    if getattr(cfg, "task", "train") == "distill":
        if not program.jaxpr:
            out.append(_finding(
                r, program,
                "distill arm lowered without a traced-jaxpr artifact — "
                "the teacher's stop_gradient marker cannot be audited"))
        elif "stop_gradient" not in program.jaxpr:
            out.append(_finding(
                r, program,
                "distill step's traced jaxpr contains no stop_gradient — "
                "the teacher tower is not severed from autodiff and "
                "teacher cotangents may be computed"))
    return out


NO_HOST_TRANSFER = RULES[0]
DONATION_HONORED = RULES[1]
COLLECTIVE_DTYPE = RULES[2]
GATHER_OVERLAP = RULES[3]
NO_REPLICATED_LARGE = RULES[4]
SERVE_NO_RECOMPILE = RULES[5]
QUANT_WEIGHTS_RESIDENT = RULES[6]
FUSED_OPTIMIZER = RULES[7]
FUSED_DEQUANT = RULES[8]
FROZEN_NOT_UPDATED = RULES[9]


def rules_for(program: Program) -> List[Rule]:
    return [r for r in RULES if r.applicable(program)]


def run_rules(program: Program) -> Tuple[List[str], List[Finding]]:
    """Run every applicable rule over one program.

    Returns (rule ids run, findings). An empty findings list from a rule
    means the invariant holds in this program."""
    ran, findings = [], []
    for r in rules_for(program):
        ran.append(r.id)
        findings.extend(r.check(program, program.config))
    return ran, findings


# --- program builders (the parallelism arms the CI gate lowers) -------------

# Small geometry, CPU-loweable on the 8-virtual-device mesh. batch_size 64
# keeps B*N above the GSPMD partial-dot threshold (see
# tests/test_gather_overlap.py geometry note) so the arms exercise the real
# weight-gather strategies the rules police.
BASE_GEOMETRY = dict(
    image_size=16, patch_size=8, embed_dim=32, num_heads=2, num_blocks=2,
    num_classes=4, batch_size=64, warmup_steps=2,
)

# arm name -> Config overrides on top of BASE_GEOMETRY. dtype defaults to
# bfloat16, so the bf16 comm-cast policy (and with it VTX-R003) is active on
# every fsdp arm; "dp" pins float32 as the no-policy baseline.
TRAIN_ARMS: Dict[str, dict] = {
    "dp": dict(run_without_fsdp=True, dtype="float32"),
    "zero2": dict(reshard_after_forward=False),
    "zero3": dict(gather_overlap="off"),
    "zero3_overlap": dict(gather_overlap="on"),
    "accum": dict(batch_size=128, grad_accum_steps=2),
    "moe": dict(moe_experts=4, gather_overlap="off"),
    # forced fused optimizer (interpret-mode Pallas on CPU) — the arm that
    # activates VTX-R008 and captures the traced-jaxpr artifact
    "fused": dict(gather_overlap="off", fused_optimizer="on"),
    # scenario arms (vitax/programs/registry.py): the probe's masked-frozen
    # backbone and the distill two-tower step, lowered through the unified
    # builder (vitax/programs/builder.py) — the arms that activate VTX-R010
    "probe": dict(task="probe", gather_overlap="off"),
    "distill": dict(task="distill", gather_overlap="off"),
}

SERVE_ARM = "serve"
# quantized serving: same geometry with the params int8-quantized in memory
# (vitax/serve/quant.py quantize_params_for_serve); runs R006 (the AOT
# contract is dtype-blind) plus R007
SERVE_QUANT_ARM = "serve_quant"
# the fp8 weight arm: same machinery with float8_e4m3 leaves — R007's
# residency/arg checks are dtype-keyed, so the arm pins the second
# QUANT_DTYPES slot end to end
SERVE_FP8_ARM = "serve_fp8"
# int8 weights + dynamic activation quant + forced fused dequant-matmul
# (interpret-mode Pallas on CPU) — the serve twin of the "fused" train arm;
# activates VTX-R009 and reads the traced-jaxpr artifact
SERVE_ACTQUANT_ARM = "serve_actquant"
SERVE_ARMS = (SERVE_ARM, SERVE_QUANT_ARM, SERVE_FP8_ARM, SERVE_ACTQUANT_ARM)
ALL_ARMS = tuple(TRAIN_ARMS) + SERVE_ARMS
# the lint.sh / pre-push subset: one train arm covering R001-R005 (the
# overlap arm applies every train rule), the fused arm for R008, the
# scenario arms for R010, plus the serve arms for R006/R007 (all quant
# dtypes) and R009 (forced fused)
FAST_ARMS = ("zero3_overlap", "fused", "probe", "distill") + SERVE_ARMS


def arm_config(arm: str, **overrides) -> Config:
    kw = dict(BASE_GEOMETRY)
    if arm == SERVE_ARM:
        kw.update(serve_max_batch=4)
    elif arm == SERVE_QUANT_ARM:
        kw.update(serve_max_batch=4, serve_quant_dtype="int8")
    elif arm == SERVE_FP8_ARM:
        kw.update(serve_max_batch=4, serve_quant_dtype="float8_e4m3")
    elif arm == SERVE_ACTQUANT_ARM:
        kw.update(serve_max_batch=4, serve_quant_dtype="int8",
                  serve_act_quant="int8", fused_dequant="on")
    else:
        kw.update(TRAIN_ARMS[arm])
    kw.update(overrides)
    return Config(**kw).validate()


def build_train_program(cfg: Config, arm: str = "custom",
                        donate: bool = True) -> Program:
    """Lower the scenario's step program for `cfg` and capture the rule
    artifacts. --task train takes the historical hlo.lower_train_step path
    byte-for-byte (its identity is pinned by tests); other scenarios lower
    through the unified builder, which additionally captures the
    freeze-report evidence VTX-R010 reads."""
    from vitax.parallel.mesh import build_mesh
    task = getattr(cfg, "task", "train")
    frozen_paths: Tuple[str, ...] = ()
    opt_moment_paths: Tuple[str, ...] = ()
    if task == "train":
        lowered, n_state_leaves = hlo.lower_train_step(cfg, donate=donate)
        # the traced-jaxpr artifact only exists where a rule reads it
        jaxpr = hlo.train_step_jaxpr(cfg) if _fused_active(cfg) else ""
    else:
        from vitax.programs import builder as B
        lowered, n_state_leaves = B.lower_step(cfg, donate=donate)
        frozen_paths, opt_moment_paths = B.freeze_report(cfg)
        jaxpr = (B.step_jaxpr(cfg)
                 if (_fused_active(cfg) or task == "distill") else "")
    mesh = build_mesh(cfg)
    return Program(
        kind="train", arm=arm, config=cfg,
        mlir=lowered.as_text(),
        partitioned_hlo=hlo.capture_partitioned(lowered),
        jaxpr=jaxpr,
        mesh_shape=dict(mesh.shape),
        n_state_leaves=n_state_leaves,
        frozen_paths=frozen_paths,
        opt_moment_paths=opt_moment_paths,
    )


def build_serve_program(cfg: Config, arm: str = SERVE_ARM) -> Program:
    """Build and warm an InferenceEngine over randomly-initialized sharded
    params (the AOT bucket invariants do not depend on the weights)."""
    import jax
    import jax.numpy as jnp

    from vitax.parallel.mesh import build_mesh
    from vitax.parallel.sharding import init_sharded_params
    from vitax.serve.engine import InferenceEngine, _build_model

    mesh = build_mesh(cfg)
    # init always uses the plain-Dense model: a QuantDense model cannot
    # init (its act path asserts int8 weights), and the param paths are
    # identical, so the quant-aware engine model binds the same tree
    init_model = _build_model(cfg, mesh, quantized=False)
    model = _build_model(cfg, mesh)
    sample_b = mesh.shape["dp"] * mesh.shape["fsdp"]
    sample = jnp.zeros((sample_b, cfg.image_size, cfg.image_size, 3),
                       jnp.float32)
    params, _ = init_sharded_params(
        lambda rng: init_model.init(rng, sample, True),
        jax.random.key(cfg.seed), cfg, mesh)
    scales, quant_dtype = None, ""
    if getattr(cfg, "serve_quant_dtype", ""):
        # in-memory quantization — the arm exercises the quantized serve
        # program without a checkpoint on disk (random weights: the
        # residency and AOT invariants do not depend on the values)
        from vitax.serve.quant import quantize_params_for_serve
        params, scales = quantize_params_for_serve(
            params, cfg, mesh, dtype=cfg.serve_quant_dtype)
        quant_dtype = cfg.serve_quant_dtype
    engine = InferenceEngine(cfg, mesh, model, params,
                             scales=scales, quant_dtype=quant_dtype)
    engine.warmup()
    return Program(kind="serve", arm=arm, config=cfg,
                   mesh_shape=dict(mesh.shape), engine=engine)


def build_program(arm: str, **overrides) -> Program:
    cfg = arm_config(arm, **overrides)
    if arm in SERVE_ARMS:
        return build_serve_program(cfg, arm=arm)
    return build_train_program(cfg, arm=arm)
