"""vitax.tune — the self-driving performance loop.

Subsystem map:
  knobs     the ONE definition of the bench/profiler/autotuner knob surface:
            a dataclass + the shared argparse group + the resolved-knob
            payload every measured number records (bench.py, tools/
            profile_step.py, tools/aot_topology.py and tools/autotune.py all
            import it, so knob names and defaults cannot drift)
  preset    committable winning-knob JSON under presets/ — emitted by the
            autotuner per (model preset, topology), loaded back via
            --preset_file by bench.py, tools/profile_step.py and
            python -m vitax.train
  cost      compile-only cost model: analytic step-time decomposition
            (compute + remat recompute + exposed collective bytes +
            optimizer traffic) plus the AOT compile probe (partitioned-HLO
            collective bytes, compiler memory_analysis) and the
            known-ordered knob pairs CPU CI pins the ranking on
  space     deterministic candidate enumeration over the knob space,
            filtered through Config.validate()
  driver    the search driver: analytic rank -> compile prune -> (on TPU)
            successive-halving measured windows, every trial a schema'd
            JSONL record (kind:"autotune_trial")

Entry points: tools/autotune.py (search + preset emit) and
tools/perf_gate.py (regression gate + schema validation + ranking pins).
"""

from vitax.tune.knobs import (  # noqa: F401
    KNOB_PAYLOAD_KEYS, Knobs, add_knob_args, knob_payload, knobs_from_args)
from vitax.tune.preset import (  # noqa: F401
    PRESET_SCHEMA, apply_preset_to_args, config_defaults_from_preset,
    load_preset, make_preset, preset_path, save_preset)
