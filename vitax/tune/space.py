"""Deterministic candidate enumeration over the knob space.

The train-side grid is the cross product the ISSUE names — remat policy x
batch per chip x scan unroll / remat window x --gather_overlap x
--fused_optimizer x comm dtypes — filtered through Config.validate() so the
driver never compiles a combination the trainer would reject (rejected
combinations are counted, not silently dropped: the driver records one
pruned_by:"invalid" trial per filtered candidate when asked).

Enumeration order is fixed (nested loops over tuples declared here), so the
ranked shortlist is bit-reproducible run to run — the acceptance contract
for the off-TPU degradation path.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from vitax.tune.knobs import REMAT_POLICIES

# (scan_blocks, scan_unroll, remat_window) arms: the scan-geometry lattice.
# window > 1 subsumes unroll (Config.validate); unrolled path has no window.
SCAN_ARMS = (
    (True, 1, 0),      # scanned, per-block remat
    (True, 2, 0),      # partial unroll
    (False, 1, 0),     # fully unrolled
    (True, 1, 2),      # window-2 group remat
)

# (param_gather_dtype, grad_reduce_dtype) comm-precision arms
COMM_ARMS = (
    (None, "float32"),            # Config defaults (gather follows --dtype)
    ("bfloat16", "bfloat16"),     # full bf16 comm
)

BATCH_LADDER_PER_CHIP = {
    "tiny": (32, 64, 128),
    "b16": (32, 64, 128),
    "b16_moe": (32, 64),
    "l14": (16, 32, 64),
    "10b": (4, 8),
    "10b_slice": (32, 64),
}

GATHER_OVERLAP_ARMS = ("auto", "off")
FUSED_OPTIMIZER_ARMS = ("auto", "off")

# serve bucket geometry: (serve_max_batch, max_batch_wait_ms)
SERVE_GEOMETRY_ARMS = (
    (4, 2.0), (8, 2.0), (8, 5.0), (16, 5.0), (16, 10.0), (32, 10.0),
)


def candidate_space(model_preset: str, n_dev: int, preset_kw: dict,
                    max_candidates: int = 0,
                    batches: Optional[Tuple[int, ...]] = None,
                    ) -> Tuple[List[dict], int]:
    """Enumerate valid train-knob candidates for (model preset, topology).

    Returns (candidates, n_invalid). Each candidate is a Config-kwargs
    dict (preset shape + knobs, validated); n_invalid counts combinations
    Config.validate() rejected. `max_candidates` > 0 truncates the
    deterministic enumeration (the cap is logged by the driver — silent
    truncation must not read as full coverage)."""
    from vitax.config import Config

    batches = batches or BATCH_LADDER_PER_CHIP.get(model_preset, (32, 64))
    out, n_invalid = [], 0
    for bpc in batches:
        for policy in REMAT_POLICIES:
            for scan_blocks, unroll, window in SCAN_ARMS:
                for overlap in GATHER_OVERLAP_ARMS:
                    for fused in FUSED_OPTIMIZER_ARMS:
                        for gather_dt, reduce_dt in COMM_ARMS:
                            kw = dict(preset_kw)
                            kw.update(
                                num_classes=1000, warmup_steps=0,
                                batch_size=bpc * n_dev,
                                remat_policy=policy,
                                scan_blocks=scan_blocks,
                                scan_unroll=unroll,
                                remat_window=window,
                                gather_overlap=overlap,
                                fused_optimizer=fused,
                                param_gather_dtype=gather_dt,
                                grad_reduce_dtype=reduce_dt)
                            try:
                                Config(**kw).validate()
                            except AssertionError:
                                n_invalid += 1
                                continue
                            out.append(kw)
                            if max_candidates and len(out) >= max_candidates:
                                return out, n_invalid
    return out, n_invalid


def serve_space() -> Tuple[Tuple[int, float], ...]:
    """Serve bucket-geometry candidates (validated power-of-two buckets)."""
    return SERVE_GEOMETRY_ARMS


def serve_geometry_cost(serve_max_batch: int, max_batch_wait_ms: float,
                        target_rps: float = 200.0,
                        image_s: float = 0.004) -> float:
    """Analytic serve score (lower = better) at an assumed arrival rate:
    expected per-request latency = batching wait (a request waits ~half the
    window unless the bucket fills first) + padded-bucket compute, where
    padding waste falls as the expected batch approaches the bucket size.
    Deterministic — used only to RANK geometries off-TPU; measured serve
    numbers ride the first tunnel-up run."""
    expected_batch = min(max(target_rps * max_batch_wait_ms / 1e3, 1.0),
                         float(serve_max_batch))
    # padded power-of-two bucket the expected batch lands in
    bucket = 1
    while bucket < expected_batch:
        bucket *= 2
    bucket = min(bucket, serve_max_batch)
    fill = expected_batch / bucket
    wait_s = (max_batch_wait_ms / 1e3) * 0.5 * \
        (1.0 - min(expected_batch / serve_max_batch, 1.0))
    compute_s = image_s * bucket / expected_batch  # per-request share
    return wait_s + compute_s + (1.0 - fill) * image_s


def rank_serve_geometries() -> List[dict]:
    """Serve geometries ranked by the analytic score, deterministic."""
    scored = [{"serve_max_batch": b, "max_batch_wait_ms": w,
               "score": serve_geometry_cost(b, w)}
              for b, w in serve_space()]
    scored.sort(key=lambda r: (r["score"], r["serve_max_batch"],
                               r["max_batch_wait_ms"]))
    return scored
