"""Committable winning-knob presets under presets/.

The autotuner emits one JSON file per (model preset, topology):

    {"schema": 1, "kind": "vitax_preset", "model_preset": "l14",
     "topology": "v5e:1",
     "knobs": {<KNOB_PAYLOAD_KEYS, resolved — see vitax/tune/knobs.py>},
     "serve": {"serve_max_batch": 8, "max_batch_wait_ms": 5.0},
     "source": {"mode": "compile_only" | "measured", "trial_id": ...,
                "cost_step_s": ..., "images_per_sec_chip": ...,
                "created": "<iso8601>"}}

Loaded back via --preset_file by bench.py, tools/profile_step.py and
python -m vitax.train. Application rule everywhere: the preset fills every
knob still at its sentinel default; an explicit CLI flag wins. Because the
preset stores the RESOLVED knob set, applying it pins every knob explicitly
— TUNED.json defaults cannot leak under a preset, so
`bench.py --preset_file <emitted preset>` reproduces the winning knob set
exactly (the acceptance contract, pinned in tests/test_autotune.py).
"""

from __future__ import annotations

import json
import os
import re
from typing import Optional

from vitax.tune.knobs import KNOB_PAYLOAD_KEYS

PRESET_SCHEMA = 1
PRESET_KIND = "vitax_preset"


def preset_path(root: str, model_preset: str, topology: str) -> str:
    """Canonical committable location: presets/<model>_<topology>.json with
    the topology sanitized for filenames (v5e:2x4 -> v5e-2x4)."""
    safe = re.sub(r"[^A-Za-z0-9_.-]", "-", topology)
    return os.path.join(root, f"{model_preset}_{safe}.json")


def make_preset(model_preset: str, topology: str, knobs: dict,
                serve: Optional[dict] = None,
                source: Optional[dict] = None) -> dict:
    missing = [k for k in KNOB_PAYLOAD_KEYS if k not in knobs]
    assert not missing, f"preset knobs missing {missing}"
    return {
        "schema": PRESET_SCHEMA,
        "kind": PRESET_KIND,
        "model_preset": model_preset,
        "topology": topology,
        "knobs": {k: knobs[k] for k in KNOB_PAYLOAD_KEYS},
        "serve": dict(serve or {}),
        "source": dict(source or {}),
    }


def save_preset(path: str, preset: dict) -> str:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(preset, f, indent=1, sort_keys=True)
        f.write("\n")
    return path


def load_preset(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        preset = json.load(f)
    if not isinstance(preset, dict) or preset.get("kind") != PRESET_KIND:
        raise ValueError(f"{path}: not a vitax preset "
                         f"(kind={preset.get('kind') if isinstance(preset, dict) else type(preset).__name__!r})")
    if preset.get("schema") != PRESET_SCHEMA:
        raise ValueError(f"{path}: preset schema {preset.get('schema')!r}, "
                         f"expected {PRESET_SCHEMA}")
    knobs = preset.get("knobs")
    if not isinstance(knobs, dict):
        raise ValueError(f"{path}: missing knobs object")
    missing = [k for k in KNOB_PAYLOAD_KEYS if k not in knobs]
    if missing:
        raise ValueError(f"{path}: preset knobs missing {missing}")
    return preset


def apply_preset_to_args(preset: dict, args, n_dev: int) -> list:
    """Fill bench/profiler-style knob args (add_knob_args surface) from a
    loaded preset. Only knobs still at their sentinel default are touched —
    an explicit CLI flag wins. Returns the list of fields applied.

    batch: the preset stores PER-CHIP batch; --batch_size is global, so the
    translation needs the live device count (call after backend init)."""
    k = preset["knobs"]
    applied = []

    def setd(attr, sentinel, value):
        if hasattr(args, attr) and getattr(args, attr) == sentinel:
            setattr(args, attr, value)
            applied.append(attr)

    setd("batch_size", 0, int(k["batch_per_chip"]) * max(n_dev, 1))
    setd("remat_policy", None, k["remat_policy"])
    setd("scan_blocks", None, bool(k["scan_blocks"]))
    if k["scan_blocks"]:
        # unroll is a scan knob; with scan off the resolved value is the
        # model default and pinning it would contradict --no_scan_blocks
        setd("scan_unroll", 0, int(k["scan_unroll"]))
    setd("remat_window", -1, int(k["remat_window"]))
    setd("grad_ckpt", True, bool(k["grad_ckpt"]))
    setd("use_flash_attention", True, bool(k["use_flash_attention"]))
    setd("grad_accum_steps", 1, int(k["grad_accum_steps"]))
    setd("param_gather_dtype", None, k["param_gather_dtype"])
    setd("grad_reduce_dtype", "float32", k["grad_reduce_dtype"])
    setd("gather_overlap", "auto", k["gather_overlap"])
    setd("fused_optimizer", "auto", k["fused_optimizer"])
    return applied


def config_defaults_from_preset(preset: dict) -> dict:
    """Config-field defaults from a preset, for python -m vitax.train:
    parse_config() re-parses with these as parser defaults, so explicit
    CLI flags still win. batch_per_chip is deliberately NOT mapped —
    --batch_size is the global batch and the trainer's device count is not
    known at parse time; set it explicitly for multi-host runs."""
    k = preset["knobs"]
    out = {
        "remat_policy": k["remat_policy"],
        "grad_ckpt": bool(k["grad_ckpt"]),
        "scan_blocks": bool(k["scan_blocks"]),
        "scan_unroll": max(int(k["scan_unroll"]), 1),
        "remat_window": max(int(k["remat_window"]), 0),
        "use_flash_attention": bool(k["use_flash_attention"]),
        "grad_accum_steps": int(k["grad_accum_steps"]),
        "param_gather_dtype": k["param_gather_dtype"],
        "grad_reduce_dtype": k["grad_reduce_dtype"],
        "gather_overlap": k["gather_overlap"],
        "fused_optimizer": k["fused_optimizer"],
    }
    serve = preset.get("serve") or {}
    if "serve_max_batch" in serve:
        out["serve_max_batch"] = int(serve["serve_max_batch"])
    if "max_batch_wait_ms" in serve:
        out["max_batch_wait_ms"] = float(serve["max_batch_wait_ms"])
    return out
