"""Compile-only cost model for the knob autotuner.

Two tiers, both deterministic:

1. ``analytic_cost(cfg, n_dev, peak_tflops)`` — a closed-form step-time
   decomposition built on the analytic FLOPs model
   (vitax/telemetry/flops.py): useful compute + remat recompute FLOPs,
   exposed collective bytes (ZeRO gather/reduce traffic at the knobbed comm
   dtypes, discounted when the gather-overlap schedule hides them),
   optimizer-state HBM traffic (fused = one pass), and a fixed per-step
   dispatch overhead that makes per-image cost favor larger per-chip
   batches. No jax import, no tracing — this ranks the WHOLE candidate
   space in microseconds and is what the CPU CI ranking pins run against.

2. ``compile_probe(cfg, devices)`` — the AOT ground truth for shortlisted
   candidates: ``step.lower().compile()`` with a per-compile HLO dump, so
   one compile yields (a) bytes moved per collective from the
   post-SPMD-partitioning module (vitax/analysis/hlo.py parsers — the
   backend-independent dtype truth) and (b) the compiler's own live-buffer
   accounting via ``memory_analysis()`` (argument + temp bytes vs the HBM
   bound, exactly tools/aot_topology.py's fits_hbm check).

The analytic constants (interconnect/HBM bandwidth, overlap hiding
fraction, recompute fractions) are ORDER-OF-MAGNITUDE priors chosen for
ranking, not prediction: tools/perf_gate.py --check_ranking pins the model
against KNOWN_ORDERED_PAIRS (measured or provable orderings, e.g.
`gather_overlap off` must not out-rank `auto` on ZeRO-3) so a constant
edit that flips a known ordering fails CI.
"""

from __future__ import annotations

import glob
import os
import shutil
import tempfile
import time

from vitax.telemetry.flops import model_flops_per_step

DTYPE_BYTES = {"float32": 4, "bfloat16": 2}

# ranking priors (NOT predictions — see module docstring)
ICI_BYTES_PER_S = 9.0e10      # per-chip interconnect bandwidth
HBM_BYTES_PER_S = 8.0e11      # per-chip HBM bandwidth
FIXED_OVERHEAD_S = 3.0e-4     # per-step host dispatch / launch tail
OVERLAP_EXPOSED_FRAC = 0.3    # gather time still exposed when prefetched
                              # through the scan carry (the rest hides
                              # under block matmuls)
UNFUSED_OPT_PASSES = 3.0      # optax tree-of-ops re-reads state ~3x vs the
                              # one-pass fused Pallas update

# fraction of forward FLOPs recomputed in the backward under each remat
# policy (grad_ckpt on). Ordering is the contract: none > dots > dots_attn.
RECOMPUTE_FRAC = {
    "none_saveable": 1.0,
    "dots_saveable": 0.55,
    "dots_attn_saveable": 0.35,
}
# windowed group remat saves the per-block residual stacking boundary
WINDOW_DISCOUNT = 0.9


def param_count(cfg) -> int:
    """Analytic parameter count from the Config shape (weights + biases +
    LN/pos/cls; MoE experts counted per expert)."""
    d, L = cfg.embed_dim, cfg.num_blocks
    h = cfg.mlp_hidden_dim
    n = cfg.num_patches
    patchify = 3 * cfg.patch_size ** 2 * d + d
    attn = 3 * (d * d + d) + d * d + d          # qkv + proj
    if getattr(cfg, "moe_experts", 0) > 0:
        mlp = cfg.moe_experts * (d * h + h + h * d + d) + d * cfg.moe_experts
    else:
        mlp = d * h + h + h * d + d
    block = attn + mlp + 4 * d                   # + 2 LayerNorms
    head = d * cfg.num_classes + cfg.num_classes
    embed = (n + 1) * d + d                      # pos embed + cls token
    return patchify + L * block + head + embed + 2 * d  # final LN


def fsdp_shards(cfg, n_dev: int) -> int:
    """Resolved size of the fsdp mesh axis for `n_dev` devices."""
    if cfg.run_without_fsdp:
        return 1
    fixed = (cfg.dp_size if cfg.dp_size > 0 else 1) \
        * cfg.tp_size * cfg.sp_size * cfg.pp_size * cfg.ep_size
    if cfg.fsdp_size > 0:
        return cfg.fsdp_size
    return max(n_dev // max(fixed, 1), 1)


def overlap_active(cfg, shards: int) -> bool:
    """Whether the double-buffered gather prefetch schedule runs — mirrors
    Config.gather_overlap 'auto' semantics (vitax/models/vit.py)."""
    if cfg.gather_overlap == "off":
        return False
    eligible = (shards > 1 and cfg.reshard_after_forward
                and not cfg.run_without_fsdp and cfg.scan_blocks
                and cfg.grad_ckpt and cfg.remat_policy == "none_saveable"
                and cfg.pp_size == 1)
    return cfg.gather_overlap == "on" or eligible


def live_bytes_estimate(cfg, n_dev: int) -> int:
    """Rough per-chip resident bytes (state + saved activations) for the
    analytic HBM prune. compile_probe()'s memory_analysis overrides this
    for shortlisted candidates — this only needs to catch obvious
    can't-possibly-fit candidates early."""
    shards = fsdp_shards(cfg, n_dev)
    params = param_count(cfg)
    # f32 master params + Adam mu/nu, sharded over fsdp
    state = params * 12 // shards
    bpc = max(cfg.batch_size // max(n_dev, 1), 1)
    n = cfg.num_patches + 1
    d = cfg.embed_dim
    act_dtype = DTYPE_BYTES.get(cfg.dtype, 2)
    if not cfg.grad_ckpt:
        saved_per_block = 8.0 * n * d          # every intermediate lives
    else:
        saved_per_block = {
            "none_saveable": 1.0,              # block inputs only
            "dots_saveable": 4.0,
            "dots_attn_saveable": 6.0,
        }.get(cfg.remat_policy, 1.0) * n * d
    acts = int(bpc * saved_per_block * cfg.num_blocks * act_dtype)
    # transient working set: one block's gathered params + activations
    transient = (params // max(cfg.num_blocks, 1)) * 4 + bpc * n * d * 4
    return state + acts + transient


def analytic_cost(cfg, n_dev: int, peak_tflops: float) -> dict:
    """Deterministic step-time decomposition; rank candidates by
    ``sec_per_image_chip`` ascending (ties broken by the caller on the
    knob tuple, never on wall-clock measurements)."""
    shards = fsdp_shards(cfg, n_dev)
    params = param_count(cfg)
    bpc = max(cfg.batch_size // max(n_dev, 1), 1)

    useful_flops = model_flops_per_step(cfg) / max(n_dev, 1)
    fwd_flops = useful_flops / 3.0
    recompute_flops = 0.0
    if cfg.grad_ckpt:
        recompute_flops = RECOMPUTE_FRAC.get(cfg.remat_policy, 1.0) * fwd_flops
        if cfg.remat_window > 1:
            recompute_flops *= WINDOW_DISCOUNT
    compute_s = (useful_flops + recompute_flops) / (peak_tflops * 1e12)

    # ZeRO collective traffic per chip per step (ring factor (s-1)/s)
    gather_dtype_bytes = DTYPE_BYTES.get(cfg.resolved_param_gather_dtype, 4)
    reduce_dtype_bytes = DTYPE_BYTES.get(cfg.grad_reduce_dtype, 4)
    ring = (shards - 1) / shards if shards > 1 else 0.0
    zero3 = shards > 1 and cfg.reshard_after_forward \
        and not cfg.run_without_fsdp
    zero2 = shards > 1 and not cfg.reshard_after_forward \
        and not cfg.run_without_fsdp
    gather_passes = 2.0 if zero3 else (1.0 if zero2 else 0.0)
    gather_bytes = params * gather_dtype_bytes * ring * gather_passes
    reduce_bytes = params * reduce_dtype_bytes * ring \
        if (zero3 or zero2) else 0.0
    # backward recompute under the overlap schedule re-gathers each block:
    # already covered by the 2-pass zero3 factor
    exposed = OVERLAP_EXPOSED_FRAC if overlap_active(cfg, shards) else 1.0
    comm_s = (gather_bytes * exposed + reduce_bytes) / ICI_BYTES_PER_S

    # optimizer-state HBM traffic: f32 params + mu + nu, read + write
    state_bytes = params * 12 / shards * 2
    fused = cfg.fused_optimizer in ("on", "auto")
    opt_s = state_bytes * (1.0 if fused else UNFUSED_OPT_PASSES) \
        / HBM_BYTES_PER_S

    step_s = compute_s + comm_s + opt_s + FIXED_OVERHEAD_S
    return {
        "step_s": step_s,
        "sec_per_image_chip": step_s / bpc,
        "compute_s": compute_s,
        "recompute_flops": recompute_flops,
        "comm_s_exposed": comm_s,
        "gather_bytes": int(gather_bytes),
        "reduce_bytes": int(reduce_bytes),
        "opt_s": opt_s,
        "live_bytes_estimate": live_bytes_estimate(cfg, n_dev),
        "fsdp_shards": shards,
        "overlap_active": overlap_active(cfg, shards),
        "params": params,
    }


def compile_probe(cfg, devices=None, hbm_bound_bytes: float = 0.0) -> dict:
    """AOT-compile `cfg` (against `devices` — a topology's device list, or
    the live backend) and return the compile-backed cost facts: per-op
    collective bytes from the partitioned HLO, memory_analysis live bytes,
    and compile/lower seconds. Raises on compile failure — the driver
    records it as pruned_by:"compile_error"."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    from vitax.analysis import hlo
    from vitax.models import build_model
    from vitax.ops.attention import make_attention_impl
    from vitax.parallel.mesh import batch_pspec, build_mesh
    from vitax.train.state import build_optimizer, make_train_state
    from vitax.train.step import make_train_step

    mesh = build_mesh(cfg, devices=devices)
    n_dev = mesh.devices.size
    model = build_model(cfg, attention_impl=make_attention_impl(cfg, mesh))
    tx, schedule = build_optimizer(cfg, max_iteration=10_000)
    state, sspecs, _ = make_train_state(cfg, model, tx, mesh,
                                        jax.random.key(0), materialize=False)
    step = make_train_step(cfg, model, tx, mesh, sspecs, schedule=schedule)
    sh = NamedSharding(mesh, batch_pspec())
    batch = {
        "image": jax.ShapeDtypeStruct(
            (cfg.batch_size, cfg.image_size, cfg.image_size, 3),
            jnp.float32, sharding=sh),
        "label": jax.ShapeDtypeStruct((cfg.batch_size,), jnp.int32,
                                      sharding=sh),
    }
    t0 = time.perf_counter()
    lowered = step.lower(state, batch,
                         jax.eval_shape(lambda: jax.random.key(0)))
    lower_s = time.perf_counter() - t0

    # one compile, two artifacts: the partitioned-HLO dump (collective
    # bytes at their true dtypes) and the executable's memory analysis
    dump_dir = tempfile.mkdtemp(prefix="vitax_tune_probe_")
    try:
        t0 = time.perf_counter()
        compiled = lowered.compile(
            compiler_options={"xla_dump_to": dump_dir,
                              "xla_dump_hlo_pass_re": ".*partitioning"})
        compile_s = time.perf_counter() - t0
        text = ""
        if n_dev > 1:
            dumps = glob.glob(
                os.path.join(dump_dir, "*after_spmd-partitioning*"))
            preferred = [f for f in dumps if "train_step" in
                         os.path.basename(f)]
            if not preferred:
                preferred = sorted(dumps, key=os.path.getsize)[-1:]
            if preferred:
                with open(preferred[0], encoding="utf-8") as f:
                    text = f.read()
    finally:
        shutil.rmtree(dump_dir, ignore_errors=True)

    rows = hlo.collect_collectives(text) if text else []
    out = {
        "lower_s": round(lower_s, 3),
        "compile_s": round(compile_s, 3),
        "collective_bytes": {op: t["bytes"]
                             for op, t in hlo.summarize(rows).items()},
        "gather_bytes_hlo": hlo.gather_bytes(rows),
        "reduce_bytes_hlo": hlo.reduce_bytes(rows),
        "n_devices": int(n_dev),
    }
    try:
        ma = compiled.memory_analysis()
        resident = int(ma.argument_size_in_bytes + ma.temp_size_in_bytes)
        out.update({
            "argument_bytes": int(ma.argument_size_in_bytes),
            "temp_bytes": int(ma.temp_size_in_bytes),
            "output_bytes": int(ma.output_size_in_bytes),
            "live_bytes": resident,
            "fits_hbm": (resident < hbm_bound_bytes
                         if hbm_bound_bytes else None),
        })
    except Exception:  # noqa: BLE001 — some backends expose no analysis
        out.update({"live_bytes": None, "fits_hbm": None})
    return out


# ---------------------------------------------------------------------------
# known-ordered knob pairs: the CPU CI contract on the cost model's ranking.
# Each entry: cost(a-knobs) must be <= cost(b-knobs) at the given shape.
# ---------------------------------------------------------------------------

_PIN_SHAPE = dict(image_size=224, patch_size=16, embed_dim=384, num_heads=6,
                  num_blocks=12, batch_size=256, num_classes=1000,
                  warmup_steps=0, fsdp_size=-1, scan_blocks=True,
                  scan_unroll=1, remat_policy="none_saveable", grad_ckpt=True)

KNOWN_ORDERED_PAIRS = (
    {"name": "gather_overlap_auto_beats_off_on_zero3",
     "n_dev": 8, "base": _PIN_SHAPE,
     "a": {"gather_overlap": "auto"}, "b": {"gather_overlap": "off"},
     "why": "the prefetch schedule hides gather time under block matmuls; "
            "turning it off must never rank better on ZeRO-3"},
    {"name": "bf16_comm_beats_f32_comm",
     "n_dev": 8, "base": _PIN_SHAPE,
     "a": {"param_gather_dtype": "bfloat16",
           "grad_reduce_dtype": "bfloat16"},
     "b": {"param_gather_dtype": "float32"},
     "why": "half the collective bytes on both gathers and reductions"},
    {"name": "fused_optimizer_beats_optax_chain",
     "n_dev": 8, "base": _PIN_SHAPE,
     "a": {"fused_optimizer": "on"}, "b": {"fused_optimizer": "off"},
     "why": "one HBM pass over the sharded state vs the optax tree-of-ops"},
    {"name": "dots_attn_saveable_beats_none_saveable_when_fits",
     "n_dev": 8, "base": _PIN_SHAPE,
     "a": {"remat_policy": "dots_attn_saveable", "gather_overlap": "off"},
     "b": {"remat_policy": "none_saveable", "gather_overlap": "off"},
     "why": "less backward recompute (overlap pinned off on both sides so "
            "the none_saveable-only prefetch schedule cannot mask it)"},
    {"name": "larger_per_chip_batch_amortizes_overhead",
     "n_dev": 8, "base": _PIN_SHAPE,
     "a": {"batch_size": 512}, "b": {"batch_size": 128},
     "why": "fixed per-step dispatch overhead and collective traffic "
            "amortize over more images"},
)


def check_ranking(pairs=KNOWN_ORDERED_PAIRS,
                  peak_tflops: float = 197.0) -> list:
    """Evaluate every known-ordered pair; returns [{name, ok, a, b, why}]
    with the two sec-per-image-per-chip scores. Pure analytic — safe (and
    fast) on any box, no jax import."""
    from vitax.config import Config
    out = []
    for pair in pairs:
        cfg_a = Config(**{**pair["base"], **pair["a"]}).validate()
        cfg_b = Config(**{**pair["base"], **pair["b"]}).validate()
        a = analytic_cost(cfg_a, pair["n_dev"], peak_tflops)
        b = analytic_cost(cfg_b, pair["n_dev"], peak_tflops)
        out.append({
            "name": pair["name"],
            "ok": a["sec_per_image_chip"] <= b["sec_per_image_chip"],
            "a_sec_per_image_chip": a["sec_per_image_chip"],
            "b_sec_per_image_chip": b["sec_per_image_chip"],
            "why": pair["why"],
        })
    return out
