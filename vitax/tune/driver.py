"""The autotune search driver: analytic rank -> compile prune -> (on TPU)
successive-halving measured windows.

Every trial — including the ones a stage prunes — is one schema'd JSONL
record (kind:"autotune_trial", vitax/telemetry/schema.py) with monotone
trial ids, so the whole search replays from its log. Budget allocation for
the measured stage follows successive halving (Jamieson & Talwalkar,
AISTATS 2016 — see PAPERS.md): every survivor gets the same step budget per
round, the better half advances, and the per-candidate window doubles as
the field halves, so the budget concentrates on contenders while every
candidate gets at least a short fenced window.

Measured windows reuse bench.py's fenced-timing idiom exactly: sync via
``float(jax.device_get(metrics["loss"]))`` — block_until_ready is not a
reliable fence on every PJRT transport (axon tunnel), fetching the value
is.
"""

from __future__ import annotations

import json
import math
import time
from typing import List, Optional

from vitax.telemetry.flops import model_flops_per_image
from vitax.tune import cost as cost_mod
from vitax.tune.knobs import knob_payload
from vitax.tune.preset import make_preset
from vitax.tune.space import candidate_space, rank_serve_geometries

TRIAL_KIND = "autotune_trial"
TRIAL_SCHEMA = 1


class TrialLog:
    """Append-only JSONL trial log with monotone trial ids."""

    def __init__(self, path: str):
        self.path = path
        self._next_id = 0
        self._f = open(path, "a", encoding="utf-8")

    def write(self, model_preset: str, topology: str, phase: str,
              knobs: dict, pruned_by: Optional[str] = None,
              **payload) -> dict:
        rec = {
            "schema": TRIAL_SCHEMA,
            "kind": TRIAL_KIND,
            "trial_id": self._next_id,
            "time": time.time(),
            "model_preset": model_preset,
            "topology": topology,
            "phase": phase,
            "knobs": knobs,
            "pruned_by": pruned_by,
            **payload,
        }
        self._next_id += 1
        self._f.write(json.dumps(rec, sort_keys=True) + "\n")
        self._f.flush()
        return rec

    def close(self) -> None:
        self._f.close()


def plan_successive_halving(n_candidates: int, total_steps: int,
                            min_steps: int = 10, eta: int = 2) -> List[tuple]:
    """Budget plan [(survivors_i, steps_each_i), ...]: R = floor(log_eta n)+1
    rounds, equal per-round budget, field divided by eta each round. When
    min_steps does not bind, total usage is <= total_steps exactly."""
    assert n_candidates >= 1 and total_steps >= 1 and eta >= 2
    rounds = int(math.floor(math.log(n_candidates, eta))) + 1
    per_round = total_steps // rounds
    plan, n = [], n_candidates
    for _ in range(rounds):
        steps = max(min_steps, per_round // n)
        plan.append((n, steps))
        if n == 1:
            break
        n = max(1, n // eta)
    return plan


class _Runner:
    """One candidate's compiled program + device-resident batch, reusable
    across halving rounds (no recompile between rounds)."""

    def __init__(self, cfg, devices=None):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import NamedSharding

        from vitax.models import build_model
        from vitax.ops.attention import make_attention_impl
        from vitax.parallel.mesh import batch_pspec, build_mesh
        from vitax.train.state import build_optimizer, make_train_state
        from vitax.train.step import make_train_step

        self.cfg = cfg
        mesh = build_mesh(cfg, devices=devices)
        self.n_dev = int(mesh.devices.size)
        model = build_model(cfg, attention_impl=make_attention_impl(cfg, mesh))
        tx, schedule = build_optimizer(cfg, max_iteration=10_000)
        self.state, sspecs, _ = make_train_state(cfg, model, tx, mesh,
                                                 jax.random.key(0))
        self.step_fn = make_train_step(cfg, model, tx, mesh, sspecs,
                                       schedule=schedule)
        sh = NamedSharding(mesh, batch_pspec())
        rng = np.random.default_rng(0)
        self.batch = {
            "image": jax.device_put(jnp.asarray(rng.normal(
                size=(cfg.batch_size, cfg.image_size, cfg.image_size, 3)),
                jnp.float32), sh),
            "label": jax.device_put(jnp.asarray(rng.integers(
                0, cfg.num_classes, size=(cfg.batch_size,)), jnp.int32), sh),
        }
        self.rng_key = jax.random.key(1)
        self._warm = False

    def measure(self, steps: int, warmup: int) -> dict:
        """bench.py's fenced window: device_get is the fence (see module
        docstring), warmup covers compile on the first round only."""
        import jax
        import numpy as np

        from vitax.telemetry.record import memory_stats_bytes

        n_warm = max(warmup, 1) if not self._warm else 1
        for _ in range(n_warm):
            self.state, metrics = self.step_fn(self.state, self.batch,
                                               self.rng_key)
        float(jax.device_get(metrics["loss"]))
        self._warm = True

        t0 = time.perf_counter()
        for _ in range(steps):
            self.state, metrics = self.step_fn(self.state, self.batch,
                                               self.rng_key)
        final_loss = float(jax.device_get(metrics["loss"]))
        dt = time.perf_counter() - t0
        assert np.isfinite(final_loss), f"non-finite loss {final_loss}"
        step_time = dt / steps
        return {
            "step_time_s": step_time,
            "images_per_sec_chip": self.cfg.batch_size / step_time
            / self.n_dev,
            "mem": memory_stats_bytes(),
        }


def _rank_key(scored: dict) -> tuple:
    """Deterministic order: analytic score, then the knob payload text —
    never a wall-clock measurement (compile_s varies run to run)."""
    return (round(scored["cost"]["sec_per_image_chip"], 12),
            json.dumps(scored["knobs"], sort_keys=True))


def run_search(model_preset: str, topology: str, preset_kw: dict,
               n_dev: int, log: TrialLog, *, peak_tflops: float,
               devices=None, hbm_bound_bytes: float = 0.0,
               max_candidates: int = 0, shortlist: int = 8,
               compile_top: int = 0, measure: bool = False,
               budget_steps: int = 240, min_steps: int = 10,
               warmup: int = 3, log_fn=print) -> dict:
    """One (model preset, topology) search. Returns {ranked, winner,
    n_candidates, n_invalid, serve} where `ranked` is the surviving
    shortlist best-first and `winner` a committable preset dict."""
    from vitax.config import Config

    candidates, n_invalid = candidate_space(model_preset, n_dev, preset_kw,
                                            max_candidates=max_candidates)
    log_fn(f"[autotune] {model_preset}@{topology}: {len(candidates)} valid "
           f"candidates ({n_invalid} rejected by Config.validate"
           + (f", enumeration capped at {max_candidates}"
              if max_candidates else "") + ")")

    # stage 1: analytic cost over the whole space (deterministic)
    scored = []
    for kw in candidates:
        cfg = Config(**kw).validate()
        c = cost_mod.analytic_cost(cfg, n_dev, peak_tflops)
        entry = {"cfg": cfg, "kw": kw, "knobs": knob_payload(cfg, n_dev),
                 "cost": c}
        if hbm_bound_bytes and c["live_bytes_estimate"] > hbm_bound_bytes:
            entry["pruned_by"] = "hbm_estimate"
        scored.append(entry)
    scored.sort(key=_rank_key)

    survivors = []
    for rank, entry in enumerate(scored):
        pruned = entry.get("pruned_by")
        if pruned is None and len(survivors) >= shortlist:
            pruned = "cost_rank"
        trial_cost = {k: v for k, v in entry["cost"].items()
                      if k != "params"}
        log.write(model_preset, topology, "analytic", entry["knobs"],
                  pruned_by=pruned, rank=rank, cost=trial_cost)
        if pruned is None:
            survivors.append(entry)

    # stage 2: AOT compile probe on the shortlist head (cost-model ground
    # truth: collective bytes from the partitioned HLO + compiler live
    # bytes); compile failures and HBM overflows drop out here
    if compile_top > 0:
        kept = []
        for entry in survivors:
            if len(kept) >= compile_top:
                kept.append(entry)  # beyond the probe budget: keep unprobed
                continue
            try:
                probe = cost_mod.compile_probe(
                    entry["cfg"], devices=devices,
                    hbm_bound_bytes=hbm_bound_bytes)
            except Exception as e:  # noqa: BLE001 — a failed compile is a pruned trial
                log.write(model_preset, topology, "compile", entry["knobs"],
                          pruned_by="compile_error",
                          error=f"{type(e).__name__}: {e}")
                continue
            pruned = "hbm" if probe.get("fits_hbm") is False else None
            entry["compile"] = probe
            log.write(model_preset, topology, "compile", entry["knobs"],
                      pruned_by=pruned, compile_s=probe["compile_s"],
                      compile=probe)
            if pruned is None:
                kept.append(entry)
        survivors = kept

    # stage 3: measured successive halving (real backend only)
    if measure and survivors:
        plan = plan_successive_halving(len(survivors), budget_steps,
                                       min_steps=min_steps)
        log_fn(f"[autotune] halving plan {plan} "
               f"(budget {budget_steps} steps)")
        field = survivors
        runners = {}
        for rnd, (n_keep, steps) in enumerate(plan):
            field = field[:n_keep]
            results = []
            for entry in field:
                key = id(entry)
                try:
                    if key not in runners:
                        runners[key] = _Runner(entry["cfg"], devices=devices)
                    m = runners[key].measure(steps, warmup)
                except Exception as e:  # noqa: BLE001 — a crashed window is a pruned trial
                    log.write(model_preset, topology, "measure",
                              entry["knobs"], pruned_by="run_error",
                              round=rnd, error=f"{type(e).__name__}: {e}")
                    continue
                mfu = (m["images_per_sec_chip"]
                       * model_flops_per_image(entry["cfg"])
                       / (peak_tflops * 1e12))
                entry["measured"] = {**m, "mfu": mfu}
                log.write(model_preset, topology, "measure", entry["knobs"],
                          round=rnd, steps=steps,
                          step_time_s=m["step_time_s"],
                          images_per_sec_chip=m["images_per_sec_chip"],
                          mfu=mfu, mem=m["mem"])
                results.append(entry)
            # best measured first; losers of this round are recorded pruned
            results.sort(
                key=lambda e: e["measured"]["images_per_sec_chip"],
                reverse=True)
            if rnd + 1 < len(plan):
                for entry in results[plan[rnd + 1][0]:]:
                    log.write(model_preset, topology, "measure",
                              entry["knobs"], pruned_by="halving", round=rnd)
            field = results
        survivors = field or survivors

    serve_ranked = rank_serve_geometries()
    winner = None
    if survivors:
        best = survivors[0]
        src = {
            "mode": "measured" if best.get("measured") else "compile_only",
            "cost_step_s": best["cost"]["step_s"],
            "images_per_sec_chip": (best.get("measured") or {}).get(
                "images_per_sec_chip"),
            "created": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        }
        winner = make_preset(model_preset, topology, best["knobs"],
                             serve={k: serve_ranked[0][k] for k in
                                    ("serve_max_batch", "max_batch_wait_ms")},
                             source=src)
    return {
        "ranked": [{"knobs": e["knobs"],
                    "sec_per_image_chip": e["cost"]["sec_per_image_chip"],
                    "measured": e.get("measured")}
                   for e in survivors],
        "winner": winner,
        "n_candidates": len(candidates),
        "n_invalid": n_invalid,
        "serve": serve_ranked,
    }
