"""The shared perf-knob surface: one dataclass, one argparse group, one
resolved-knob payload.

Historically bench.py, tools/profile_step.py and tools/aot_topology.py each
carried their own copy of the knob flags, and BENCH_r04's `knobs` payload
predates the gather-overlap / fused-optimizer / comm-dtype knobs entirely —
so a trajectory entry could not say what actually ran. This module is the
single definition all of them (and tools/autotune.py) import:

  - ``Knobs``: the CLI-level knob set with bench's exact sentinel defaults
    (0 / -1 / None = "resolve per preset"), serializable via ``to_json``.
  - ``add_knob_args``: the argparse group, flag names and defaults verbatim
    from the historical bench.py surface (they are a contract: ladder rows
    in LADDER_*.jsonl replay these flags).
  - ``knob_payload``: the RESOLVED knob set a measured number records —
    ground truth for tools/apply_ladder.py and tools/perf_gate.py. Batch is
    PER-CHIP: img/s/chip only compares at equal per-chip batch.
"""

from __future__ import annotations

import argparse
import dataclasses
from typing import Optional

REMAT_POLICIES = ("none_saveable", "dots_saveable", "dots_attn_saveable")

# the payload contract: every measured BENCH number and every autotune trial
# records exactly these keys (tools/apply_ladder.py reads a subset; the
# telemetry schema validator requires the full set)
KNOB_PAYLOAD_KEYS = (
    "batch_per_chip", "remat_policy", "scan_blocks", "scan_unroll",
    "remat_window", "grad_ckpt", "use_flash_attention", "grad_accum_steps",
    "param_gather_dtype", "grad_reduce_dtype", "gather_overlap",
    "fused_optimizer",
)


@dataclasses.dataclass
class Knobs:
    """CLI-level knob values, sentinel defaults = "resolve per preset"."""

    batch_size: int = 0                 # GLOBAL batch; 0 = preset default
    remat_policy: Optional[str] = None
    grad_ckpt: bool = True
    scan_blocks: Optional[bool] = None  # None = per-preset default
    scan_unroll: int = 0                # 0 = per-preset default
    remat_window: int = -1              # -1 = per-preset default
    use_flash_attention: bool = True
    moe_impl: Optional[str] = None
    att_dropout: Optional[float] = None
    grad_accum_steps: int = 1
    param_gather_dtype: Optional[str] = None  # None = follow --dtype
    grad_reduce_dtype: str = "float32"
    gather_overlap: str = "auto"
    fused_optimizer: str = "auto"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, d: dict) -> "Knobs":
        names = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in names})

    def other_explicit(self) -> bool:
        """Whether any non-scan A/B lever was given explicitly — the
        resolve_bench_knobs() purity rule: tuned defaults must not leak
        into a run that differs from its reference by an explicit knob."""
        return (not self.grad_ckpt or not self.use_flash_attention
                or bool(self.batch_size)
                or self.moe_impl is not None
                or self.att_dropout is not None
                or self.grad_accum_steps > 1
                or self.param_gather_dtype is not None
                or self.grad_reduce_dtype != "float32"
                or self.gather_overlap != "auto"
                or self.fused_optimizer != "auto")

    def apply_to_preset_kw(self, kw: dict) -> dict:
        """Overlay the explicit (non-sentinel) knobs onto a train_presets()
        kwargs dict — the exact historical bench.py merge order."""
        if self.batch_size:
            kw["batch_size"] = self.batch_size
        if self.moe_impl:
            kw["moe_impl"] = self.moe_impl
        if self.att_dropout is not None:
            kw["att_dropout"] = self.att_dropout
        if self.grad_accum_steps > 1:
            kw["grad_accum_steps"] = self.grad_accum_steps
        if self.param_gather_dtype:
            kw["param_gather_dtype"] = self.param_gather_dtype
        if self.grad_reduce_dtype != "float32":
            kw["grad_reduce_dtype"] = self.grad_reduce_dtype
        if self.gather_overlap != "auto":
            kw["gather_overlap"] = self.gather_overlap
        if self.fused_optimizer != "auto":
            kw["fused_optimizer"] = self.fused_optimizer
        return kw


def knobs_from_args(ns: argparse.Namespace) -> Knobs:
    """Knobs from a namespace parsed with add_knob_args (tolerant of flags a
    tool chose not to add — missing attrs keep the dataclass default)."""
    kw = {}
    for f in dataclasses.fields(Knobs):
        if hasattr(ns, f.name):
            kw[f.name] = getattr(ns, f.name)
    return Knobs(**kw)


def add_knob_args(p: argparse.ArgumentParser,
                  preset_file: bool = True) -> argparse.ArgumentParser:
    """The shared knob-flag group. Names, defaults and choices are a
    contract (historical bench.py surface; LADDER rows replay them)."""
    p.add_argument("--batch_size", type=int, default=0)
    # default resolved per preset (bench.default_remat_policy):
    # dots_attn_saveable measured fastest on v5e where activations fit
    # (192.9 > dots_saveable 190.2 on l14); the 10B flagship keeps
    # none_saveable (minimal HBM residency is what makes it fit)
    p.add_argument("--remat_policy", default=None,
                   choices=list(REMAT_POLICIES))
    p.add_argument("--no_grad_ckpt", action="store_false", dest="grad_ckpt")
    p.add_argument("--no_scan_blocks", action="store_false",
                   dest="scan_blocks", default=None,
                   help="unroll blocks instead of lax.scan (the scan's "
                        "dus-stacking constrains wgrad fusion layouts; "
                        "default resolves per preset — see "
                        "default_scan_blocks; --scan_unroll forces the scan)")
    p.add_argument("--scan_unroll", type=int, default=0,
                   help="blocks per scan step (0 = preset default); keeps "
                        "the stacked param tree, frees cross-block fusion")
    p.add_argument("--remat_window", type=int, default=-1,
                   help=">1: remat around groups of this many blocks "
                        "(functional scan; residuals dus-stack once per "
                        "group — the wgrad stacking experiment); 0 = "
                        "explicit per-block remat; -1 = tuned/preset default")
    p.add_argument("--moe_impl", default=None, choices=["gather", "einsum"],
                   help="MoE dispatch/combine A/B (vitax/models/moe.py): "
                        "einsum (GShard one-hot, default — measured fastest "
                        "on v5e) vs gather (slot-index scatter+gathers)")
    p.add_argument("--grad_accum_steps", type=int, default=1,
                   help="K > 1: accumulate grads over K microbatches inside "
                        "the jitted step (images/sec vs K trade on the train "
                        "presets; an explicit A/B knob like --batch_size)")
    p.add_argument("--att_dropout", type=float, default=None,
                   help="attention-dropout A/B arm (in-kernel dropout path)")
    p.add_argument("--param_gather_dtype", default=None,
                   choices=["bfloat16", "float32"],
                   help="comm-precision A/B arm: dtype the FSDP param "
                        "collectives move (None = Config default: follow "
                        "--dtype, i.e. bf16 gathers on the bf16 presets)")
    p.add_argument("--grad_reduce_dtype", default="float32",
                   choices=["float32", "bfloat16"],
                   help="comm-precision A/B arm: dtype the grad "
                        "reduce-scatter/all-reduce moves (float32 = exact "
                        "pre-policy numerics)")
    p.add_argument("--gather_overlap", default="auto",
                   choices=["auto", "off", "on"],
                   help="overlap A/B arm: double-buffered ZeRO-3 block-param "
                        "gathers prefetched through the layer-scan carry "
                        "(off = exact pre-overlap schedule; auto = on "
                        "whenever ZeRO-3 + scanned blocks + per-block remat "
                        "are active)")
    p.add_argument("--fused_optimizer", default="auto",
                   choices=["auto", "off", "on"],
                   help="optimizer A/B arm: one-pass Pallas fused clip+AdamW "
                        "update over the sharded state (off = exact optax "
                        "chain; auto = on where the kernels lower to real "
                        "Mosaic, i.e. TPU)")
    p.add_argument("--no_flash_attention", action="store_false",
                   dest="use_flash_attention")
    if preset_file:
        p.add_argument("--preset_file", default="",
                       help="load a committed autotune preset JSON "
                            "(presets/<model>_<topology>.json); its knobs "
                            "fill every knob still at its default — "
                            "explicit flags on the command line win")
    return p


def knob_payload(cfg, n_dev: int) -> dict:
    """The RESOLVED knob set a measured number was taken under — the
    `knobs` object in the bench JSON result line and in every autotune
    trial record. Keys are KNOB_PAYLOAD_KEYS exactly."""
    return {
        "batch_per_chip": cfg.batch_size // max(n_dev, 1),
        "remat_policy": cfg.remat_policy,
        "scan_blocks": cfg.scan_blocks,
        "scan_unroll": cfg.scan_unroll,
        "remat_window": cfg.remat_window,
        "grad_ckpt": cfg.grad_ckpt,
        "use_flash_attention": cfg.use_flash_attention,
        "grad_accum_steps": cfg.grad_accum_steps,
        "param_gather_dtype": cfg.resolved_param_gather_dtype,
        "grad_reduce_dtype": cfg.grad_reduce_dtype,
        "gather_overlap": cfg.gather_overlap,
        "fused_optimizer": cfg.fused_optimizer,
    }
