"""Pod launcher: run the training command on every TPU-VM worker.

Parity with `torch_xla.distributed.xla_dist` (reference README.md:99-118;
SURVEY.md section 3.5) built on the same mechanism the reference's own install
step uses — `gcloud compute tpus tpu-vm ssh --worker=all` (reference
README.md:29-31). JAX autodetects pod topology from TPU metadata, so the same
command runs unmodified on every host; there is no per-core process fan-out and
no XRT server to restart.

Usage (from any machine with gcloud configured):
    python -m vitax.launch --tpu=my-pod --zone=us-central2-b \
        --env PYTHONUNBUFFERED=1 -- python3 run_vit_training.py --fake_data ...

Features mirrored from xla_dist:
    --env KEY=VAL ...   environment passthrough to every worker
    --restart           kill stale python processes on workers first
                        (--restart-tpuvm-pod-server parity)
    --logfile PATH      tee combined output to a local file (README.md:118 parity)
    --max_restarts N    monitor the launch; on a nonzero worker exit, re-run
                        the kill-stale + launch rounds up to N times
                        (xla_dist's worker restart-on-failure, README.md:99-101)
"""

from __future__ import annotations

import argparse
import shlex
import subprocess
import sys


def _quote_workdir(workdir: str) -> str:
    # keep a leading ~/ unquoted so the remote shell expands it
    if workdir.startswith("~/"):
        return "~/" + shlex.quote(workdir[2:])
    if workdir == "~":
        return "~"
    return shlex.quote(workdir)


# Bracketed first char so the pattern does not match the pkill-carrying shell's
# own command line (which contains this literal string).
RESTART_CMD = "sudo pkill -f '[r]un_vit_training.py' || true; sleep 1"


def build_remote_command(cmd: list, env: list, workdir: str) -> str:
    exports = " ".join(f"export {shlex.quote(e)};" for e in env)
    remote = " ".join(shlex.quote(c) for c in cmd)
    return f"cd {_quote_workdir(workdir)} && {exports} {remote}"


def _run_launch(gcloud: list, logfile) -> int:
    """Run one launch round to completion, optionally teeing output."""
    if logfile:
        with open(logfile, "ab") as log:
            proc = subprocess.Popen(gcloud, stdout=subprocess.PIPE,
                                    stderr=subprocess.STDOUT)
            assert proc.stdout is not None
            for line in proc.stdout:
                sys.stdout.buffer.write(line)
                sys.stdout.buffer.flush()
                log.write(line)
            return proc.wait()
    return subprocess.call(gcloud)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__,
                                formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--tpu", required=True, help="TPU pod name")
    p.add_argument("--zone", default=None)
    p.add_argument("--project", default=None)
    p.add_argument("--env", action="append", default=[], metavar="KEY=VAL")
    p.add_argument("--restart", action="store_true",
                   help="kill stale training processes on all workers first")
    p.add_argument("--max_restarts", type=int, default=3,
                   help="relaunch rounds after a nonzero worker exit "
                        "(0 disables monitoring-based restart)")
    p.add_argument("--workdir", default="~/vitax")
    p.add_argument("--logfile", default=None)
    p.add_argument("--dry_run", action="store_true",
                   help="print the gcloud command(s) without executing")
    p.add_argument("cmd", nargs=argparse.REMAINDER,
                   help="-- command to run on every worker")
    args = p.parse_args(argv)

    cmd = args.cmd[1:] if args.cmd and args.cmd[0] == "--" else args.cmd
    if not cmd:
        p.error("no command given (append: -- python3 run_vit_training.py ...)")

    def gcloud_ssh(command: str) -> list:
        g = ["gcloud", "compute", "tpus", "tpu-vm", "ssh", args.tpu,
             "--worker=all", f"--command={command}"]
        if args.zone:
            g.append(f"--zone={args.zone}")
        if args.project:
            g.append(f"--project={args.project}")
        return g

    gcloud = gcloud_ssh(build_remote_command(cmd, args.env, args.workdir))

    rc = 1
    for attempt in range(args.max_restarts + 1):
        if args.restart or attempt > 0:
            # separate SSH round so the kill pattern cannot match (and
            # terminate) the shell carrying the training command itself;
            # re-run before every relaunch so stale half-dead workers from the
            # failed round can't hold the TPU
            restart = gcloud_ssh(RESTART_CMD)
            print("restarting: " + " ".join(shlex.quote(g) for g in restart),
                  flush=True)
            if not args.dry_run:
                subprocess.call(restart)

        print("launching:", " ".join(shlex.quote(g) for g in gcloud), flush=True)
        if args.dry_run:
            return 0
        rc = _run_launch(gcloud, args.logfile)
        if rc == 0:
            return 0
        print(f"worker exited with rc={rc} "
              f"(attempt {attempt + 1}/{args.max_restarts + 1})", flush=True)
    print(f"giving up after {args.max_restarts + 1} attempts", flush=True)
    return rc


if __name__ == "__main__":
    sys.exit(main())
