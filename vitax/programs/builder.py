"""Unified program builder: one `build_program(task, geometry)` entry.

ROADMAP item 3's first half. The three independent assembly paths —
train/loop.py hand-wiring mesh+model+optimizer+step, analysis/hlo.py
rebuilding the same stack for the AOT surfaces, serve/engine.py assembling
its own forward — converge here:

- `Geometry` is the shared substrate (cfg, mesh, model, optimizer, schedule,
  state specs) every program is built against. The training loop constructs
  its geometry from live objects (non-owned: nothing cached, programs bound
  to the loop's exact model/optimizer — the lowered bytes are pinned
  identical to the pre-builder direct calls); analysis/tools call
  `Geometry.from_config(cfg)`, which memoizes (owned) so an arm's lower +
  jaxpr + freeze-report probes share one traced stack instead of three.
- `build_program(task, geom)` dispatches to the per-task constructors
  (train/train/step.py, eval, opt_probe, distill in programs/workloads.py,
  serve buckets on an InferenceEngine) and caches built programs per owned
  geometry — the shared compile cache.
- `build_engine(cfg, ...)` is the registry's engine constructor: every CLI
  that boots a serving engine (vitax.serve.__main__, arbiter-provisioned
  replicas) routes through it, so scenario validation runs before any
  checkpoint IO.

The scenario registry (programs/registry.py) names which tasks each --task
may build; unknown combinations fail here with the scenario's program set.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

from vitax.config import Config
from vitax.programs.registry import Scenario, get_scenario

PyTree = Any

# program kinds build_program understands (each scenario declares a subset)
PROGRAM_KINDS = ("train", "eval", "opt_probe", "distill", "serve_bucket")


@dataclasses.dataclass
class Geometry:
    """Everything a program is built against: the resolved mesh/model/
    optimizer/spec stack for one Config. `owned=True` (Geometry.from_config)
    marks a geometry the builder materialized itself — those carry the
    abstract state for AOT lowering and participate in the program cache.
    Loop-constructed geometries wrap live objects and cache nothing."""
    cfg: Config
    mesh: Any
    model: Any
    tx: Any
    schedule: Any
    state_specs: PyTree
    abstract_state: Optional[PyTree] = None   # ShapeDtypeStruct TrainState
    max_iteration: int = 10_000
    owned: bool = False
    _programs: Dict[Tuple, Any] = dataclasses.field(default_factory=dict)

    @property
    def scenario(self) -> Scenario:
        return get_scenario(self.cfg.task)

    @classmethod
    def from_config(cls, cfg: Config, max_iteration: int = 10_000) -> "Geometry":
        """Materialize the full (abstract) stack for one Config — the exact
        assembly the training loop performs (train/loop.py:166-182), shared
        by the analysis arms and AOT tools. Memoized per (cfg, max_iteration)
        so one arm's multiple probes trace the stack once."""
        key = (dataclasses.astuple(cfg), max_iteration)
        hit = _GEOMETRY_CACHE.get(key)
        if hit is not None:
            return hit

        import jax
        from vitax.models import build_model
        from vitax.ops.attention import make_attention_impl
        from vitax.parallel.mesh import build_mesh
        from vitax.train.loop import _moe_dispatch_sharding, _token_sharding
        from vitax.train.state import make_train_state

        mesh = build_mesh(cfg)
        model = build_model(
            cfg, attention_impl=make_attention_impl(cfg, mesh),
            token_sharding=_token_sharding(cfg, mesh),
            moe_dispatch_sharding=_moe_dispatch_sharding(cfg, mesh))
        tx, schedule = get_scenario(cfg.task).make_optimizer(
            cfg, max_iteration)
        abstract, sspecs, _ = make_train_state(
            cfg, model, tx, mesh, jax.random.key(cfg.seed),
            materialize=False)
        geom = cls(cfg=cfg, mesh=mesh, model=model, tx=tx, schedule=schedule,
                   state_specs=sspecs, abstract_state=abstract,
                   max_iteration=max_iteration, owned=True)
        _GEOMETRY_CACHE[key] = geom
        return geom


# owned geometries, memoized by (cfg fields, max_iteration) — Config is a
# flat dataclass of scalars/strings, so astuple is hashable
_GEOMETRY_CACHE: Dict[Tuple, Geometry] = {}


def build_program(task: str, geom: Geometry, donate: bool = True,
                  bucket: Optional[int] = None, engine=None):
    """Build (or fetch from the owned-geometry cache) one program.

    task        one of PROGRAM_KINDS, and a member of the scenario's declared
                program set (registry.py) — the registry is the contract for
                what each --task may assemble
    donate      train/distill only: donate the state buffers (production);
                False builds the analysis negative arm
    bucket      serve_bucket only: the batch bucket to lower
    engine      serve_bucket only: the InferenceEngine holding the params
                (serve programs are bound to concrete weights, not abstract
                geometry — build one with build_engine)
    """
    scenario = geom.scenario
    if task not in PROGRAM_KINDS:
        raise ValueError(
            f"unknown program kind {task!r}; builder knows {PROGRAM_KINDS}")
    if task not in scenario.programs:
        raise ValueError(
            f"--task {scenario.name} does not build {task!r} programs "
            f"(declared set: {scenario.programs}; vitax/programs/registry.py)")

    key = (task, donate, bucket)
    if geom.owned and key in geom._programs:
        return geom._programs[key]

    cfg, mesh, model = geom.cfg, geom.mesh, geom.model
    if task == "train":
        from vitax.train.step import make_train_step
        program = make_train_step(cfg, model, geom.tx, mesh,
                                  geom.state_specs, donate=donate,
                                  schedule=geom.schedule)
    elif task == "eval":
        from vitax.train.step import make_eval_step
        program = make_eval_step(cfg, model, mesh, geom.state_specs)
    elif task == "opt_probe":
        from vitax.train.step import make_opt_probe
        program = make_opt_probe(cfg, geom.tx, mesh, geom.state_specs,
                                 schedule=geom.schedule)
    elif task == "distill":
        from vitax.programs.workloads import (load_teacher_params,
                                              make_distill_step)
        if cfg.teacher_npz:
            teacher = load_teacher_params(cfg, mesh)
        else:
            # no file: lower against the ABSTRACT teacher (analysis arms,
            # AOT probes) — requires an owned geometry's abstract state
            assert geom.abstract_state is not None, (
                "--task distill needs --teacher_npz to build a runnable "
                "program (abstract lowering needs Geometry.from_config)")
            teacher = geom.abstract_state.params
        program = make_distill_step(cfg, model, geom.tx, mesh,
                                    geom.state_specs, teacher,
                                    donate=donate, schedule=geom.schedule)
    else:  # serve_bucket
        assert engine is not None and bucket is not None, (
            "serve_bucket programs are built on an InferenceEngine: pass "
            "engine=build_engine(cfg, ...) and bucket=<batch size>")
        lowered, _ = engine._lower_bucket(bucket)
        program = lowered

    if geom.owned:
        geom._programs[key] = program
    return program


def build_engine(cfg: Config, npz: str = "", epoch: Optional[int] = None):
    """The registry's serving-engine constructor: scenario-checked, then the
    engine source is picked exactly like vitax.serve.__main__ historically
    did — a consolidated npz export (quantized exports load their int8
    leaves as int8, the arbiter's warm-on-borrowed-host path) or the latest/
    requested Orbax epoch checkpoint."""
    scenario = get_scenario(cfg.task)
    assert "serve_bucket" in scenario.programs, (
        f"--task {scenario.name} declares no serving programs "
        f"(vitax/programs/registry.py)")
    from vitax.serve.engine import InferenceEngine
    if npz:
        return InferenceEngine.from_npz(cfg, npz)
    return InferenceEngine.from_checkpoint(cfg, cfg.ckpt_dir, epoch)


# --- AOT / analysis surfaces -------------------------------------------------
# Scenario-aware mirrors of analysis/hlo.py's lower_train_step family: the
# invariant arms for --task probe/distill lower through these. hlo.py's own
# builders are untouched — the train-task identity pins compare against them.


def _build_step(cfg: Config, max_iteration: int, donate: bool):
    """(step, (state, batch, rng) abstract args, n_state_leaves) for the
    scenario's step program — the same return contract as
    analysis/hlo.py:_build_train_step, for any --task."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding
    from vitax.parallel.mesh import batch_pspec

    geom = Geometry.from_config(cfg, max_iteration=max_iteration)
    step = build_program(geom.scenario.step_program, geom, donate=donate)
    sh = NamedSharding(geom.mesh, batch_pspec())
    batch = {
        "image": jax.ShapeDtypeStruct(
            (cfg.batch_size, cfg.image_size, cfg.image_size, 3),
            jnp.float32, sharding=sh),
        "label": jax.ShapeDtypeStruct((cfg.batch_size,), jnp.int32,
                                      sharding=sh),
    }
    args = (geom.abstract_state, batch, jax.random.key(cfg.seed + 1))
    return step, args, len(jax.tree_util.tree_leaves(geom.abstract_state))


def lower_step(cfg: Config, max_iteration: int = 10_000, donate: bool = True):
    """AOT-lower the scenario's step program; returns
    (lowered, n_state_leaves) like hlo.lower_train_step."""
    step, args, n_state_leaves = _build_step(cfg, max_iteration, donate)
    return step.lower(*args), n_state_leaves


def step_jaxpr(cfg: Config, max_iteration: int = 10_000) -> str:
    """Traced jaxpr text of the scenario's step program (the VTX-R008 /
    VTX-R010 artifact — stop_gradient and pallas_call markers survive only
    here, not in StableHLO)."""
    step, args, _ = _build_step(cfg, max_iteration, donate=True)
    return str(step.trace(*args).jaxpr)


def freeze_report(cfg: Config,
                  max_iteration: int = 10_000) -> Tuple[Tuple[str, ...],
                                                        Tuple[str, ...]]:
    """(frozen_param_paths, optimizer_moment_paths) for the scenario, read
    off the ABSTRACT state — the VTX-R010 evidence.

    frozen paths: '/'-joined param-tree paths the scenario freezes ("head"
    excluded for probe; every teacher leaf, prefixed "teacher/", for
    distill). moment paths: the param subpath of every mu/nu leaf that
    EXISTS in the optimizer state — optax.masked replaces masked-out
    positions with leafless MaskedNodes, so a frozen leaf acquiring moments
    shows up here as a path collision."""
    import jax
    from vitax.parallel.rules import _leaf_path_names

    geom = Geometry.from_config(cfg, max_iteration=max_iteration)
    param_paths = [
        "/".join(_leaf_path_names(path))
        for path, _ in jax.tree_util.tree_leaves_with_path(
            geom.abstract_state.params)
    ]

    task = cfg.task
    if task == "probe":
        frozen = tuple(p for p in param_paths
                       if "head" not in p.split("/"))
    elif task == "distill":
        frozen = tuple("teacher/" + p for p in param_paths)
    else:
        frozen = ()

    moments = []
    for path, _ in jax.tree_util.tree_leaves_with_path(
            geom.abstract_state.opt_state):
        names = _leaf_path_names(path)
        for marker in ("mu", "nu"):
            if marker in names:
                moments.append("/".join(names[names.index(marker) + 1:]))
                break
    return frozen, tuple(sorted(set(moments)))
