"""Workload ingredients for the scenario registry (vitax/programs/registry.py).

Three things live here, shared by the builder and the training loop:

- masked optimizers: the probe's frozen backbone (updates set_to_zero, so
  AdamW moments exist for the HEAD ONLY — `optax.masked` replaces masked-out
  leaves with leafless MaskedNodes, so the opt_state tree itself shrinks)
  and the finetune backbone-lr multiplier (a masked `optax.scale` appended
  AFTER AdamW: a true lr multiplier on the final update, with no state);
- `warm_start_from_npz`: consolidated single-file export -> the live sharded
  TrainState, through the same flatten/unflatten key convention serving uses
  (vitax/checkpoint/consolidate.py), with head re-init for a new
  --num_classes and loud failure on any other key/shape mismatch;
- `make_distill_step`: the first program that needs both halves of the stack
  — a frozen engine-style teacher forward and the student train step — in
  ONE jitted program. Teacher params enter as an extra NON-donated argument
  at the student's param shardings; teacher logits sit under stop_gradient
  (VTX-R010 reads the marker off the traced jaxpr).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from vitax.config import Config
from vitax.parallel.mesh import Mesh, batch_pspec
from vitax.parallel.rules import _leaf_path_names
from vitax.parallel.sharding import make_comm_precision, shardings_of
from vitax.train.schedule import warmup_cosine_schedule
from vitax.train.state import ADAMW_HPARAMS, TrainState, build_optimizer
from vitax.train.step import (_forward_fn, _make_logits_anchor,
                              _make_update_fn, _needs_dropout, prepare_images)
from vitax.utils.logging import master_print

PyTree = Any

# the classifier head's module name in the param tree (vitax/models/vit.py):
# the one partition every transfer workload splits on
HEAD_NAME = "head"


def _is_head(path) -> bool:
    return HEAD_NAME in _leaf_path_names(path)


def head_mask(params: PyTree) -> PyTree:
    """Bool tree: True on classifier-head leaves."""
    return jax.tree_util.tree_map_with_path(
        lambda p, _: _is_head(p), params)


def backbone_mask(params: PyTree) -> PyTree:
    """Bool tree: True on every non-head (backbone) leaf."""
    return jax.tree_util.tree_map_with_path(
        lambda p, _: not _is_head(p), params)


def frozen_fraction(params: PyTree) -> float:
    """Fraction of parameter ELEMENTS in the backbone (the frozen partition
    under --task probe) — the frozen-frac the telemetry events report."""
    frozen = total = 0
    for path, leaf in jax.tree_util.tree_leaves_with_path(params):
        n = int(jnp.size(leaf)) if hasattr(leaf, "size") else 0
        total += n
        if not _is_head(path):
            frozen += n
    return frozen / total if total else 0.0


# --- optimizers --------------------------------------------------------------


def make_finetune_optimizer(cfg: Config, max_iteration: int):
    """The train optimizer, plus a masked `optax.scale(backbone_lr_mult)`
    appended when the multiplier != 1: scaling AFTER AdamW multiplies the
    final update (a true per-partition lr), and `scale` carries no state, so
    the opt_state tree — and with it state_specs, checkpoints, donation —
    matches the train task's exactly."""
    tx, schedule = build_optimizer(cfg, max_iteration)
    if cfg.backbone_lr_mult != 1.0:
        tx = optax.chain(
            tx, optax.masked(optax.scale(cfg.backbone_lr_mult),
                             backbone_mask))
    return tx, schedule


def make_probe_optimizer(cfg: Config, max_iteration: int):
    """Linear-probe optimizer: backbone updates zeroed, AdamW over the head
    ONLY.

    Mirrors build_optimizer's chain shape (identity in the clip's historical
    slot — the clip itself is applied in the step off the shared grad-norm
    reduction, vitax/train/step.py:_make_update_fn), with the AdamW wrapped
    in `optax.masked(head_mask)`: masked-out leaves become leafless
    MaskedNodes in the opt_state, so the moments tree holds head leaves only
    (tests/test_programs.py pins this by tree inspection) and state_specs /
    donation follow with no extra rules. `set_to_zero` runs FIRST: masked
    transforms pass unmasked updates through untouched, so backbone grads
    reach `optax.apply_updates` as exact zeros — params stay bitwise-frozen
    (x + 0.0 is bitwise-identity for every value the init produces)."""
    schedule = warmup_cosine_schedule(cfg.lr, cfg.warmup_steps, max_iteration)
    parts = []
    if cfg.clip_grad_norm > 0:
        parts.append(optax.identity())
    parts.append(optax.masked(optax.set_to_zero(), backbone_mask))
    parts.append(optax.masked(
        optax.adamw(schedule, weight_decay=cfg.weight_decay,
                    **ADAMW_HPARAMS),
        head_mask))
    return optax.chain(*parts), schedule


# --- finetune warm start -----------------------------------------------------


def warm_start_from_npz(cfg: Config, state: TrainState,
                        mesh: Mesh) -> Tuple[TrainState, Dict[str, Any]]:
    """Overwrite a freshly-initialized sharded TrainState's params from a
    consolidated npz export (--init_npz), leaf by leaf.

    - Non-head leaves MUST match by key and shape (quantized exports are
      dequantized to f32 by load_npz; values are cast to the fresh leaf's
      dtype). A missing key, a shape mismatch, or an unknown export key is
      a hard error — silently training from a half-loaded tree is the
      failure mode this loudness exists for.
    - Head leaves keep their fresh initialization when --reinit_head is set
      or the export's shape disagrees (a new --num_classes); otherwise they
      load like everything else.
    - The optimizer state is left at its fresh init: AdamW moments are
      zeros + a step count, value-independent, so the fresh born-sharded
      init IS the correct warm-start opt state.

    Returns (state, info) where info is the kind:"finetune" telemetry
    payload (loaded/reinit key counts, frozen fraction, source path)."""
    from vitax.checkpoint.consolidate import (flatten_tree, load_npz,
                                              unflatten_tree)
    from vitax.parallel.sharding import param_specs

    flat_npz = load_npz(cfg.init_npz)
    flat_fresh = flatten_tree(state.params)
    # flatten_tree np.asarray()s its leaves, which would destroy sharding
    # objects — walk the spec tree by path with the same key convention
    flat_shard = {
        "/".join(_leaf_path_names(path)): NamedSharding(mesh, spec)
        for path, spec in jax.tree_util.tree_flatten_with_path(
            param_specs(state.params, cfg, mesh),
            is_leaf=lambda x: isinstance(x, P))[0]}

    unknown = sorted(set(flat_npz) - set(flat_fresh))
    if unknown:
        raise ValueError(
            f"--init_npz {cfg.init_npz} carries keys absent from this "
            f"model: {unknown[:5]}{'...' if len(unknown) > 5 else ''} — "
            f"the export was consolidated from a different architecture "
            f"(check the model shape flags)")

    new_flat, loaded, reinit = {}, [], []
    for key, fresh in flat_fresh.items():
        src = flat_npz.get(key)
        is_head = HEAD_NAME in key.split("/")
        if is_head and (cfg.reinit_head or src is None
                        or tuple(src.shape) != tuple(fresh.shape)):
            reinit.append(key)
            # keep the fresh head init (flatten_tree coerced it to numpy;
            # put it back at its sharding)
            new_flat[key] = jax.device_put(fresh, flat_shard[key])
            continue
        if src is None:
            raise ValueError(
                f"--init_npz {cfg.init_npz} is missing param {key!r}: a "
                f"partial export cannot warm-start a finetune (re-export "
                f"with vitax.checkpoint.consolidate --params_only)")
        if tuple(src.shape) != tuple(fresh.shape):
            raise ValueError(
                f"--init_npz {cfg.init_npz} param {key!r} has shape "
                f"{tuple(src.shape)}, model expects {tuple(fresh.shape)} "
                f"(only the head may differ — pass --reinit_head for a "
                f"new --num_classes)")
        new_flat[key] = jax.device_put(src.astype(fresh.dtype),
                                       flat_shard[key])
        loaded.append(key)

    state = state.replace(params=unflatten_tree(new_flat))
    info = {
        "init_npz": cfg.init_npz,
        "loaded": len(loaded),
        "reinit": sorted(reinit),
        "frozen_frac": (frozen_fraction(state.params)
                        if cfg.task == "probe" else 0.0),
    }
    master_print(
        f"warm start: {info['loaded']} leaves from {cfg.init_npz}"
        + (f", head re-initialized ({len(reinit)} leaves)" if reinit else ""))
    return state, info


def load_teacher_params(cfg: Config, mesh: Mesh) -> PyTree:
    """Teacher tree for --task distill: consolidated npz (--teacher_npz,
    dequantized to f32 — the teacher forward is full-precision compute),
    device_put into the same param_specs layout the student uses, so the
    two towers share one sharding story inside the jitted program."""
    from vitax.checkpoint.consolidate import load_npz, unflatten_tree
    from vitax.parallel.sharding import param_specs

    params = unflatten_tree(load_npz(cfg.teacher_npz))
    shardings = shardings_of(mesh, param_specs(params, cfg, mesh))
    master_print(f"distill: teacher params from {cfg.teacher_npz}")
    return jax.tree.map(jax.device_put, params, shardings)


# --- distillation step -------------------------------------------------------


def make_distill_step(cfg: Config, model, tx, mesh: Mesh, state_specs: PyTree,
                      teacher_params: PyTree, donate: bool = True,
                      schedule=None):
    """Jitted distillation step: (state, batch, rng) -> (state, metrics),
    with the frozen teacher closed in as a non-donated program argument.

    Mirrors make_train_step's structure (vitax/train/step.py) minus the
    paths the distill validator forbids (pp / grad-accum / MoE / ZeRO-2):
    shared forward assembly, shared optimizer phase (_make_update_fn, one
    grad-norm reduction feeding clip + metric), same donation and sharding
    story for the student state. The teacher half is the engine-style
    eval-mode forward (det=True) under jax.lax.stop_gradient — no teacher
    grads, no teacher optimizer state, and the marker VTX-R010 greps the
    traced jaxpr for.

    Loss: (1 - alpha) * CE(student, labels)
          + alpha * T^2 * KL(softmax(teacher/T) || softmax(student/T))
    (Hinton et al.; the T^2 factor keeps the soft-target gradient scale
    comparable across temperatures).
    """
    state_shardings = shardings_of(mesh, state_specs)
    teacher_shardings = state_shardings.params
    batch_sharding = NamedSharding(mesh, batch_pspec())
    rng_sharding = NamedSharding(mesh, P())
    dropout = _needs_dropout(cfg)
    forward = _forward_fn(cfg, model, mesh, state_specs)
    comm = make_comm_precision(cfg, mesh, state_specs.params)
    update_fn = _make_update_fn(cfg, tx, mesh, state_specs, schedule)
    anchor_logits = _make_logits_anchor(mesh)
    alpha = cfg.distill_alpha
    temp = cfg.distill_temp

    def distill_step(state: TrainState, teacher, batch, rng):
        step_rng = jax.random.fold_in(rng, state.step)
        images = prepare_images(batch["image"])
        labels = batch["label"]
        # teacher tower: eval-mode, grad-free — stop_gradient severs the
        # (already-absent) path so no cotangent ever reaches teacher leaves.
        # The comm cast applies to the teacher too: its FSDP gathers must
        # move bf16 under the policy exactly like the student's (VTX-R003
        # polices both towers in the one lowered program)
        t_params = comm.cast(teacher) if comm is not None else teacher
        t_logits = jax.lax.stop_gradient(
            anchor_logits(forward(t_params, images, True)))
        t_soft = jax.nn.softmax(t_logits.astype(jnp.float32) / temp, axis=-1)

        def loss_fn(params):
            p = comm.cast(params) if comm is not None else params
            det = not dropout
            r = step_rng if dropout else None
            logits = anchor_logits(forward(p, images, det, rng=r))
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits, labels).mean()
            s_log_soft = jax.nn.log_softmax(
                logits.astype(jnp.float32) / temp, axis=-1)
            # KL(teacher || student) up to the teacher-entropy constant,
            # scaled by T^2; the constant is added back for the metric so
            # the reported kl is a true divergence (>= 0, -> 0 at match)
            kl = (temp * temp) * jnp.mean(jnp.sum(
                t_soft * (jnp.log(t_soft + 1e-20) - s_log_soft), axis=-1))
            loss = (1.0 - alpha) * ce + alpha * kl
            return loss, (ce, kl, logits)

        (loss, (ce, kl, s_logits)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        if comm is not None:
            grads = comm.finalize_grads(grads)
        new_params, new_opt_state, grad_norm = update_fn(
            grads, state.opt_state, state.params)
        new_state = TrainState(
            step=state.step + 1, params=new_params, opt_state=new_opt_state)
        metrics = {
            "loss": loss,
            "ce": ce,
            "kl": kl,
            "grad_norm": grad_norm,
            "lr_step": new_state.step,
            "teacher_top1": jnp.mean(
                (jnp.argmax(t_logits, axis=-1) == labels).astype(jnp.float32)),
            "student_top1": jnp.mean(
                (jnp.argmax(s_logits, axis=-1) == labels).astype(jnp.float32)),
        }
        return new_state, metrics

    jitted = jax.jit(
        distill_step,
        in_shardings=(state_shardings, teacher_shardings, batch_sharding,
                      rng_sharding),
        out_shardings=(state_shardings, None),
        # the student state is donated exactly like the train step's; the
        # teacher is NOT — it is reused verbatim every step
        donate_argnums=(0,) if donate else (),
    )

    images_per_step = cfg.batch_size
    tokens_per_step = cfg.batch_size * cfg.num_patches

    def step_with_teacher(state, batch, rng):
        new_state, metrics = jitted(state, teacher_params, batch, rng)
        metrics = dict(metrics, images=images_per_step,
                       tokens=tokens_per_step)
        return new_state, metrics

    # AOT/jaxpr surfaces keep the loop's (state, batch, rng) signature and
    # splice the teacher in — same shape as make_train_step's attachments
    step_with_teacher.lower = lambda state, batch, rng: jitted.lower(
        state, teacher_params, batch, rng)
    step_with_teacher.trace = lambda state, batch, rng: jitted.trace(
        state, teacher_params, batch, rng)
    return step_with_teacher
