"""Scenario registry + unified program builder (ROADMAP item 3).

`registry` maps --task names to Scenario declarations (programs, optimizer,
validator, sharding rules); `builder` turns (task, geometry) into jitted/AOT
programs through a shared compile cache; `workloads` holds the finetune /
linear-probe / distillation ingredients the scenarios are spent on.
"""

from vitax.programs.registry import SCENARIOS, TASKS, Scenario, get_scenario

__all__ = [
    "SCENARIOS",
    "TASKS",
    "Scenario",
    "get_scenario",
    # heavy (jax-importing) surfaces are reached via their modules:
    #   vitax.programs.builder   Geometry, build_program, build_engine,
    #                            lower_step, step_jaxpr, freeze_report
    #   vitax.programs.workloads masks, optimizers, warm starts, distill step
]
