"""Scenario registry: `--task` name -> programs, optimizer, validator, rules.

ROADMAP item 3 ("one build_program(task, geometry) entry; scenarios become
registry entries"). A Scenario is declarative data: which step program the
training loop runs, which programs the task may build, how its optimizer is
assembled, and a SELF-CONTAINED validator holding the task's pairwise flag
checks — `config.py:validate` dispatches here instead of accreting another
block per workload, so adding a scenario touches this file, not the shared
validator.

This module is deliberately jax-free (it is imported from Config.validate,
which tools call before any backend setup): optimizers and sharding tables
are reached through lazy imports at use time.

The registry entries:

    train     the reference pretraining loop (vitax/train/loop.py)
    finetune  warm start from a consolidated npz export (--init_npz), head
              re-initialized for a new --num_classes (--reinit_head or a
              shape mismatch), optional --backbone_lr_mult update scaling
    probe     linear probe: backbone frozen via optax masking (updates
              set_to_zero; optimizer moments exist for the head ONLY), the
              classifier head trained as usual
    distill   knowledge distillation: a frozen teacher (--teacher_npz,
              engine-style eval forward under stop_gradient) and the student
              train step in ONE jitted program; loss = (1-alpha)*CE +
              alpha*KL(teacher||student) at --distill_temp

How to add a workload: write a validator + optimizer builder (or reuse), add
a Scenario below, and (if it needs a new step program) teach
vitax/programs/builder.py:build_program the new task name. The analysis arms
(vitax/analysis/rules.py) and `--task` choices pick it up from SCENARIOS.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Tuple


@dataclasses.dataclass(frozen=True)
class Scenario:
    """One registry entry: everything a workload declares about itself."""
    name: str
    description: str
    step_program: str            # builder task the training loop steps with
    programs: Tuple[str, ...]    # program kinds build_program accepts for it
    make_optimizer: Callable     # (cfg, max_iteration) -> (tx, schedule)
    validate: Callable           # (cfg) -> None; raises on bad flag combos

    def sharding_rules(self):
        """The declarative path->PartitionSpec table this scenario shards
        with (vitax/parallel/rules.py). One shared table today; a scenario
        needing a different layout overrides this."""
        from vitax.parallel.rules import RULE_TABLE
        return RULE_TABLE


# --- optimizer builders (lazy: registry stays importable without jax) -------


def _train_optimizer(cfg, max_iteration: int):
    from vitax.train.state import build_optimizer
    return build_optimizer(cfg, max_iteration)


def _finetune_optimizer(cfg, max_iteration: int):
    from vitax.programs.workloads import make_finetune_optimizer
    return make_finetune_optimizer(cfg, max_iteration)


def _probe_optimizer(cfg, max_iteration: int):
    from vitax.programs.workloads import make_probe_optimizer
    return make_probe_optimizer(cfg, max_iteration)


# --- validators: the task-specific pairwise checks, absorbed from
# config.py:validate's growth path. Each sees a fully type-checked Config and
# raises AssertionError with an actionable message, exactly like validate().


def _validate_train(cfg) -> None:
    assert not cfg.init_npz, (
        "--init_npz is a finetune/probe warm-start flag; --task train "
        "initializes from seed (use --task finetune to resume params from "
        "a consolidated export)")
    assert not cfg.teacher_npz, (
        "--teacher_npz is a distillation flag; use --task distill")
    assert not cfg.reinit_head, (
        "--reinit_head only applies to --task finetune (train initializes "
        "every leaf fresh anyway)")
    assert cfg.backbone_lr_mult == 1.0, (
        f"--backbone_lr_mult {cfg.backbone_lr_mult} only applies to "
        f"--task finetune; train updates every leaf at the schedule lr")


def _validate_finetune(cfg) -> None:
    assert cfg.init_npz, (
        "--task finetune resumes params from a consolidated export: pass "
        "--init_npz <file> (produce one with vitax.checkpoint.consolidate)")
    assert not cfg.teacher_npz, (
        "--teacher_npz is a distillation flag; use --task distill")
    assert cfg.pp_size <= 1, (
        "--task finetune runs the non-pipelined step; restore with "
        "--pp_size 1 (the consolidated export is topology-free)")
    assert cfg.backbone_lr_mult >= 0, (
        f"--backbone_lr_mult must be >= 0, got {cfg.backbone_lr_mult} "
        f"(0 freezes the backbone — consider --task probe, which also "
        f"drops the backbone optimizer moments)")
    if cfg.backbone_lr_mult != 1.0:
        assert cfg.fused_optimizer != "on", (
            "--fused_optimizer on is incompatible with --backbone_lr_mult: "
            "the fused clip+AdamW kernel applies one lr to every leaf "
            "(vitax/ops/fused_optimizer.py); the optax path handles the "
            "masked scaling")


def _validate_probe(cfg) -> None:
    assert not cfg.teacher_npz, (
        "--teacher_npz is a distillation flag; use --task distill")
    assert cfg.pp_size <= 1, (
        "--task probe runs the non-pipelined step; use --pp_size 1")
    assert cfg.fused_optimizer != "on", (
        "--fused_optimizer on is incompatible with --task probe: the fused "
        "clip+AdamW kernel updates every leaf in place, but the probe "
        "freezes the backbone via optax masking (VTX-R010 pins that frozen "
        "leaves receive no optimizer moments)")
    assert cfg.backbone_lr_mult == 1.0, (
        "--backbone_lr_mult has no effect under --task probe (the backbone "
        "is frozen outright); use --task finetune for a reduced backbone lr")


def _validate_distill(cfg) -> None:
    # --teacher_npz itself is enforced at program-build time, not here: the
    # analysis arms lower the distill program against an ABSTRACT teacher
    # with no file on disk (vitax/programs/builder.py)
    assert not cfg.init_npz, (
        "--init_npz warm starts are not wired for --task distill (the "
        "student trains from seed); distill from a finetuned teacher via "
        "--teacher_npz instead")
    assert not cfg.reinit_head, (
        "--reinit_head only applies to --task finetune")
    assert cfg.backbone_lr_mult == 1.0, (
        "--backbone_lr_mult only applies to --task finetune")
    assert cfg.pp_size <= 1, (
        "--task distill runs the non-pipelined two-tower step; use "
        "--pp_size 1")
    assert cfg.moe_experts == 0, (
        "--task distill does not support MoE models yet: the teacher "
        "forward would need the aux-loss plumbing threaded through the "
        "frozen tower")
    assert cfg.grad_accum_steps <= 1, (
        "--grad_accum_steps > 1 is not wired for --task distill: the "
        "two-tower step computes teacher logits once per loader batch")
    assert cfg.reshard_after_forward, (
        "--no_reshard_after_forward (ZeRO-2) is not wired for --task "
        "distill: the step-top gather path covers the student tower only")


SCENARIOS = {
    "train": Scenario(
        name="train",
        description="reference pretraining loop (CE over labels)",
        step_program="train",
        programs=("train", "eval", "opt_probe", "serve_bucket"),
        make_optimizer=_train_optimizer,
        validate=_validate_train,
    ),
    "finetune": Scenario(
        name="finetune",
        description="fine-tune from a consolidated npz export "
                    "(--init_npz; head re-init, --backbone_lr_mult)",
        step_program="train",
        programs=("train", "eval", "opt_probe", "serve_bucket"),
        make_optimizer=_finetune_optimizer,
        validate=_validate_finetune,
    ),
    "probe": Scenario(
        name="probe",
        description="linear probe: frozen backbone (optax-masked), "
                    "head-only optimizer state",
        step_program="train",
        programs=("train", "eval", "opt_probe", "serve_bucket"),
        make_optimizer=_probe_optimizer,
        validate=_validate_probe,
    ),
    "distill": Scenario(
        name="distill",
        description="knowledge distillation: frozen teacher "
                    "(--teacher_npz) + student in one jitted program",
        step_program="distill",
        programs=("distill", "eval", "opt_probe", "serve_bucket"),
        make_optimizer=_train_optimizer,  # plain AdamW over the student
        validate=_validate_distill,
    ),
}

TASKS = tuple(SCENARIOS)


def get_scenario(task: str) -> Scenario:
    """Resolve a --task name; unknown names fail with the valid set."""
    if task not in SCENARIOS:
        raise ValueError(
            f"unknown --task {task!r}; registered scenarios: "
            f"{', '.join(sorted(SCENARIOS))} (vitax/programs/registry.py)")
    return SCENARIOS[task]
