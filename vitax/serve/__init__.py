"""vitax.serve — TPU-native batched inference: checkpoint -> jitted
eval-mode forward -> dynamic micro-batcher -> HTTP front end.

    python -m vitax.serve --ckpt_dir /ckpts --epoch 10 --serve_port 8000 ...
    python -m vitax.serve --npz full.npz ...

See vitax/serve/engine.py (bucketed AOT forward), batcher.py (dynamic
micro-batching), server.py (HTTP + telemetry), and the README "Serving"
section. The horizontal tier — N replicas behind a least-loaded router
with admission control — lives in vitax/serve/fleet/ (python -m
vitax.serve.fleet --replicas N ...).
"""

from vitax.serve.batcher import BatchResult, DynamicBatcher, QueueFull  # noqa: F401
from vitax.serve.engine import (  # noqa: F401
    InferenceEngine,
    bucket_sizes,
    next_bucket,
)
from vitax.serve.server import (  # noqa: F401
    REQUIRED_SERVE_KEYS,
    ServeMetrics,
    drain,
    serve_forever,
    start_server,
    stop_server,
)
