"""Cross-host placement: provision serve replicas on remote hosts over HTTP.

Makes the router genuinely horizontal. One **placement agent** (python -m
vitax.serve.fleet.agent) runs per serving host and wraps its own
ReplicaManager, so a provisioned replica gets the exact lifecycle local
fleet replicas get — spawn, health sweeps, restart with capped backoff,
SIGTERM drain — through the same `vitax.supervise` seams (backoff_delay,
terminate_child) the training supervisor uses. The router-side fleet
adopts the returned URL: `adopt()` health-checks but never restarts,
because the agent owns the lifecycle — exactly the adopt() contract.

Agent endpoints:
    GET  /healthz       liveness + replica count + slot accounting
    GET  /replicas      per-replica manager snapshot
    POST /provision     {"argv": [serve flags...], "name": ..., "port": 0}
                        -> {"name", "url", "port"}  (port 0 = agent picks);
                        409 when every slot is taken (--agent_max_replicas)
    POST /release       {"name": ...} -> drain + terminate that replica

The router-side **PlacementClient** is a thin urllib wrapper; the fleet
CLI round-robins initial replicas and autoscaler scale-outs across
`--placement_agents`, and the autoscaler's scale-in release path calls
`release()` after the drain so remote processes never leak.

The agent trusts its callers with an argv tail (it execs
`python -m vitax.serve <argv> --serve_port N`), so it must only ever bind
on infrastructure networks — same threat model as the chaos endpoint,
minus the opt-in because there is no production fleet without placement.

Both halves are stdlib-only and jax-free; the replicas an agent spawns
are separate `python -m vitax.serve` processes.
"""

from __future__ import annotations

import json
import sys
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional, Sequence

from vitax.serve.fleet.replica import ReplicaManager

DEFAULT_AGENT_PORT = 7070
DEFAULT_BASE_PORT = 8100
DEFAULT_CLIENT_TIMEOUT_S = 30.0


class AgentFullError(RuntimeError):
    """Every replica slot on this agent's host is taken (max_slots).
    Maps to HTTP 409 on the wire; the fleet CLI tries the next agent and
    a fleet with NO free agent anywhere escalates to the chip arbiter."""


class PlacementAgent:
    """Per-host replica factory over a private ReplicaManager."""

    def __init__(self, advertise_host: str = "127.0.0.1",
                 base_port: int = DEFAULT_BASE_PORT,
                 manager: Optional[ReplicaManager] = None,
                 recorder=None, max_slots: int = 0, **manager_kw):
        self.advertise_host = advertise_host
        self.base_port = base_port
        self.manager = manager if manager is not None else ReplicaManager(
            recorder=recorder, **manager_kw)
        self.recorder = recorder
        # a host has a fixed chip/memory budget: max_slots caps live
        # replicas (0 = unbounded, the historical behavior)
        self.max_slots = max_slots
        self.provisions_total = 0
        self.releases_total = 0
        self._next_port = 0
        self._lock = threading.Lock()

    def slots(self) -> dict:
        return {"used": len(self.manager.snapshot()),
                "max": self.max_slots}

    def provision(self, argv: Sequence[str], name: Optional[str] = None,
                  port: int = 0) -> dict:
        """Spawn one `python -m vitax.serve` replica on this host; the
        manager owns it from here (health, restart-with-backoff, drain)."""
        if not isinstance(argv, (list, tuple)) or not all(
                isinstance(a, str) for a in argv):
            raise ValueError("argv must be a list of strings")
        if self.max_slots and len(self.manager.snapshot()) >= self.max_slots:
            raise AgentFullError(
                f"agent at capacity: {self.max_slots} slot(s) in use")
        with self._lock:
            if port == 0:
                port = self.base_port + self._next_port
                self._next_port += 1
            count = self.provisions_total
            self.provisions_total += 1
        name = name or f"agent_replica_{count}"
        if self.manager.find(name) is not None:
            raise ValueError(f"replica {name!r} already exists on this agent")
        url = f"http://{self.advertise_host}:{port}"
        full_argv = ([sys.executable, "-m", "vitax.serve"] + list(argv)
                     + ["--serve_port", str(port)])
        self.manager.manage(full_argv, url, name=name)
        self._event(event="provision", replica=name, port=port, url=url)
        return {"name": name, "url": url, "port": port}

    def release(self, name: str) -> bool:
        """Retire + drain + terminate one replica; False if unknown."""
        replica = self.manager.find(name)
        if replica is None:
            return False
        self.manager.retire(replica)
        self.manager.discard(replica)   # terminate_child SIGTERM-drains
        with self._lock:
            self.releases_total += 1
        self._event(event="release", replica=name)
        return True

    def snapshot(self) -> dict:
        with self._lock:
            out = {"provisions_total": self.provisions_total,
                   "releases_total": self.releases_total}
        out["replicas"] = self.manager.snapshot()
        return out

    def _event(self, **payload) -> None:
        if self.recorder is not None:
            try:
                self.recorder.event("placement", **payload)
            except Exception:  # noqa: BLE001 # vtx: ignore[VTX106] telemetry must not kill placement
                pass


def _make_handler(agent: PlacementAgent):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # noqa: A003
            pass

        def _reply(self, code: int, payload: dict) -> None:
            body = json.dumps(payload).encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
            if self.path == "/healthz":
                self._reply(200, {
                    "status": "ok",
                    "replicas": len(agent.manager.snapshot()),
                    "ready": agent.manager.ready_count(),
                    "slots": agent.slots()})
            elif self.path == "/replicas":
                self._reply(200, agent.snapshot())
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})

        def do_POST(self):  # noqa: N802
            length = int(self.headers.get("Content-Length", 0))
            try:
                payload = json.loads(self.rfile.read(length) or b"{}")
            except ValueError as e:
                self._reply(400, {"error": f"bad JSON body: {e}"})
                return
            if self.path == "/provision":
                try:
                    out = agent.provision(payload.get("argv", []),
                                          name=payload.get("name"),
                                          port=int(payload.get("port", 0)))
                except AgentFullError as e:
                    # 409: capacity, not a malformed request — callers
                    # try their next agent (or escalate to the arbiter)
                    self._reply(409, {"error": str(e),
                                      "slots": agent.slots()})
                    return
                except ValueError as e:
                    self._reply(400, {"error": str(e)})
                    return
                self._reply(200, out)
            elif self.path == "/release":
                name = payload.get("name", "")
                if agent.release(name):
                    self._reply(200, {"released": name})
                else:
                    self._reply(404, {"error": f"unknown replica {name!r}"})
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})

    return Handler


def start_agent(agent: PlacementAgent, port: int = DEFAULT_AGENT_PORT):
    """Bind the agent API (background thread) and start the manager's
    health loop. Returns the httpd; server_address[1] is the bound port."""
    httpd = ThreadingHTTPServer(("0.0.0.0", port), _make_handler(agent))
    httpd.daemon_threads = True
    thread = threading.Thread(  # vtx: ignore[VTX205] stop_agent's httpd.shutdown() ends serve_forever
        target=httpd.serve_forever, daemon=True, name="vitax-placement-agent")
    thread.start()
    agent.manager.start()
    return httpd


def stop_agent(httpd, agent: PlacementAgent) -> None:
    """Stop the API, then SIGTERM-drain every replica this agent owns."""
    httpd.shutdown()
    httpd.server_close()
    agent.manager.stop()


class PlacementClient:
    """Router-side handle on one agent. Injectable transport for tests."""

    def __init__(self, agent_url: str,
                 timeout_s: float = DEFAULT_CLIENT_TIMEOUT_S,
                 http_json=None):
        self.agent_url = agent_url.rstrip("/")
        self.timeout_s = timeout_s
        self._http_json = http_json or self._default_http_json

    @staticmethod
    def _default_http_json(url: str, payload: Optional[dict],
                           timeout: float) -> dict:
        data = (json.dumps(payload).encode("utf-8")
                if payload is not None else None)
        req = urllib.request.Request(
            url, data=data,
            headers={"Content-Type": "application/json"} if data else {})
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.load(resp)

    def healthz(self) -> dict:
        return self._http_json(self.agent_url + "/healthz", None,
                               self.timeout_s)

    def replicas(self) -> dict:
        return self._http_json(self.agent_url + "/replicas", None,
                               self.timeout_s)

    def provision(self, argv: List[str], name: Optional[str] = None,
                  port: int = 0) -> dict:
        """{"name", "url", "port"} of a freshly spawned remote replica —
        adopt() the url into the local fleet to route to it. Raises
        AgentFullError on the agent's 409 (every slot taken) so callers
        can distinguish "try another host" from a real failure."""
        try:
            return self._http_json(
                self.agent_url + "/provision",
                {"argv": list(argv), "name": name, "port": port},
                self.timeout_s)
        except urllib.error.HTTPError as e:
            if e.code == 409:
                raise AgentFullError(
                    f"agent {self.agent_url} at capacity") from e
            raise

    def release(self, name: str) -> dict:
        return self._http_json(self.agent_url + "/release", {"name": name},
                               self.timeout_s)
