"""Content-addressed prediction cache: identical bytes never touch a TPU twice.

Classification over an AOT-pinned engine is fully deterministic — the same
preprocessed request bytes produce the same top-k every time (no sampling,
no temperature, buckets compiled once at startup). That makes a router
cache EXACT, not approximate: the cache key is the SHA-256 of the raw
request body plus the requested topk, and a hit replays the first miss's
200 response verbatim (byte-identical JSON). There is nothing to
invalidate short of swapping the served weights, which restarts the fleet.

Semantics:
- **bounded LRU**: at most `max_entries` responses; inserting past the
  bound evicts the least-recently-used entry. A hit refreshes recency.
- **TTL**: entries older than `ttl_s` answer as misses and are dropped
  (0 = no expiry). The TTL is a freshness valve for operators doing
  in-place weight swaps behind the fleet, not a correctness need.
- **hits bypass dispatch entirely**: the router answers a hit before
  admission control, replica pick, or any network hop — a hit costs one
  hash + one dict lookup and never counts against fleet capacity.

Thread-safe (handler threads share one cache); `clock` is injectable so
TTL expiry is testable without real time (tests/test_cache.py). Every hit
emits a `kind:"cache"` telemetry event; misses are counted but only
sampled into telemetry via snapshot() — at planet-scale request rates a
per-miss event would dominate the JSONL.

Stdlib-only: the router tier must run on a box with no jax.
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict
from typing import Callable, Optional

DEFAULT_TTL_S = 300.0


class PredictionCache:
    """Bounded LRU + TTL map: SHA-256(body) + topk -> verbatim 200 bytes."""

    def __init__(self, max_entries: int, ttl_s: float = DEFAULT_TTL_S,
                 recorder=None,
                 clock: Callable[[], float] = time.monotonic):
        assert max_entries >= 0, max_entries
        assert ttl_s >= 0, ttl_s
        self.max_entries = max_entries
        self.ttl_s = ttl_s
        self.recorder = recorder
        self._clock = clock
        self._lock = threading.Lock()
        # key -> (payload bytes, expiry clock time or 0.0 = never)
        self._entries: "OrderedDict[str, tuple]" = OrderedDict()
        self.hits_total = 0
        self.misses_total = 0
        self.evictions_total = 0
        self.expirations_total = 0

    @property
    def enabled(self) -> bool:
        return self.max_entries > 0

    @staticmethod
    def key(body: bytes, topk) -> str:
        """Content address: the raw request bytes hash plus the requested
        topk. Distinct topk values never alias — the same image at topk 1
        and topk 5 are different responses."""
        return f"{hashlib.sha256(body).hexdigest()}:{topk}"

    def get(self, body: bytes, topk) -> Optional[bytes]:
        """Cached 200 payload for this request, or None (miss/expired/off)."""
        if not self.enabled:
            return None
        k = self.key(body, topk)
        with self._lock:
            entry = self._entries.get(k)
            if entry is None:
                self.misses_total += 1
                return None
            payload, expires = entry
            if expires and self._clock() >= expires:
                del self._entries[k]
                self.expirations_total += 1
                self.misses_total += 1
                return None
            self._entries.move_to_end(k)
            self.hits_total += 1
            hits, misses = self.hits_total, self.misses_total
        # running totals ride along so tools/metrics_report.py can compute
        # the hit rate from the JSONL alone (misses emit no events)
        self._event(decision="hit", key=k[:16], bytes=len(payload),
                    hits_total=hits, misses_total=misses)
        return payload

    def put(self, body: bytes, topk, payload: bytes) -> None:
        """Store one 200 response verbatim, evicting LRU past the bound."""
        if not self.enabled:
            return
        k = self.key(body, topk)
        expires = (self._clock() + self.ttl_s) if self.ttl_s else 0.0
        with self._lock:
            self._entries[k] = (payload, expires)
            self._entries.move_to_end(k)
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
                self.evictions_total += 1

    def size(self) -> int:
        with self._lock:
            return len(self._entries)

    def snapshot(self) -> dict:
        with self._lock:
            hits, misses = self.hits_total, self.misses_total
            total = hits + misses
            return {
                "enabled": self.enabled,
                "max_entries": self.max_entries,
                "ttl_s": self.ttl_s,
                "size": len(self._entries),
                "hits_total": hits,
                "misses_total": misses,
                "hit_rate": (round(hits / total, 4) if total else None),
                "evictions_total": self.evictions_total,
                "expirations_total": self.expirations_total,
            }

    def _event(self, **payload) -> None:
        if self.recorder is not None:
            try:
                self.recorder.event("cache", **payload)
            except Exception:  # noqa: BLE001 # vtx: ignore[VTX106] telemetry must not kill the hot path
                pass
