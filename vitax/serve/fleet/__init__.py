"""vitax.serve.fleet — replica fleet, least-loaded router, admission control.

The horizontal tier over vitax.serve (ROADMAP north star: planet-scale
serving): N single-engine replicas behind one front door.

    python -m vitax.serve.fleet --replicas 2 --ckpt_dir /ckpts \\
        --embed_dim 5120 ... --serve_port 8000 --slo_p99_ms 500

Three layers, bottom up:
- replica.py   — ReplicaManager: spawn/adopt replicas, health-driven
                 rotation (eject on failure or ready: false, re-admit
                 after re-warm), restart-with-backoff via the
                 vitax.supervise seams;
- router.py    — Router + stdlib HTTP front door: least-loaded dispatch,
                 one retry on a different replica, fleet-wide /metrics;
- breaker.py   — CircuitBreaker (per-replica closed/open/half-open over
                 consecutive dispatch failures) + RetryBudget (token
                 bucket capping retries+hedges at a fraction of traffic);
- admission.py — AdmissionController: predicted-wait 429 shedding with
                 Retry-After against the --slo_p99_ms deadline.

The growth tier (this PR) composes on top:
- autoscale.py — Autoscaler: hysteretic scale-out on sustained sheds /
                 predicted-wait overshoot / brownout, scale-in (retire ->
                 drain -> discard) on sustained idleness, clamped to
                 [--min_replicas, --max_replicas];
- placement.py — PlacementAgent (per-host replica factory over its own
                 ReplicaManager, python -m vitax.serve.fleet.agent) +
                 PlacementClient: cross-host provisioning the router
                 adopts over the adopt() contract;
- cache.py     — PredictionCache: router-side content-addressed response
                 cache (SHA-256 of bytes + topk), exact under
                 deterministic AOT classification; hits bypass dispatch;
- router.py    — BatchComposer: cross-replica continuous batching —
                 concurrent /predict bodies compose into one
                 /predict_batch so one replica's batcher fills a bucket
                 instead of N batchers timing out at size 1.

Clients see the single-engine contract unchanged; tests/test_fleet.py,
test_autoscale.py, and test_cache.py pin the behaviors.
"""

from vitax.serve.fleet.admission import AdmissionController  # noqa: F401
from vitax.serve.fleet.autoscale import Autoscaler  # noqa: F401
from vitax.serve.fleet.cache import PredictionCache  # noqa: F401
from vitax.serve.fleet.placement import (  # noqa: F401
    PlacementAgent,
    PlacementClient,
    start_agent,
    stop_agent,
)
from vitax.serve.fleet.breaker import (  # noqa: F401
    CircuitBreaker,
    RetryBudget,
)
from vitax.serve.fleet.replica import (  # noqa: F401
    DEAD,
    EJECTED,
    READY,
    STARTING,
    Replica,
    ReplicaManager,
)
from vitax.serve.fleet.router import (  # noqa: F401
    BatchComposer,
    Router,
    RouterMetrics,
    start_router,
    stop_router,
)
