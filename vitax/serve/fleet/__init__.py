"""vitax.serve.fleet — replica fleet, least-loaded router, admission control.

The horizontal tier over vitax.serve (ROADMAP north star: planet-scale
serving): N single-engine replicas behind one front door.

    python -m vitax.serve.fleet --replicas 2 --ckpt_dir /ckpts \\
        --embed_dim 5120 ... --serve_port 8000 --slo_p99_ms 500

Three layers, bottom up:
- replica.py   — ReplicaManager: spawn/adopt replicas, health-driven
                 rotation (eject on failure or ready: false, re-admit
                 after re-warm), restart-with-backoff via the
                 vitax.supervise seams;
- router.py    — Router + stdlib HTTP front door: least-loaded dispatch,
                 one retry on a different replica, fleet-wide /metrics;
- breaker.py   — CircuitBreaker (per-replica closed/open/half-open over
                 consecutive dispatch failures) + RetryBudget (token
                 bucket capping retries+hedges at a fraction of traffic);
- admission.py — AdmissionController: predicted-wait 429 shedding with
                 Retry-After against the --slo_p99_ms deadline.

Clients see the single-engine contract unchanged; tests/test_fleet.py
pins the rotation, retry, and overload behaviors.
"""

from vitax.serve.fleet.admission import AdmissionController  # noqa: F401
from vitax.serve.fleet.breaker import (  # noqa: F401
    CircuitBreaker,
    RetryBudget,
)
from vitax.serve.fleet.replica import (  # noqa: F401
    DEAD,
    EJECTED,
    READY,
    STARTING,
    Replica,
    ReplicaManager,
)
from vitax.serve.fleet.router import (  # noqa: F401
    Router,
    RouterMetrics,
    start_router,
    stop_router,
)
