"""Admission control: shed load the fleet cannot answer inside its deadline.

Orca-style (OSDI '22) continuous admission, adapted to the request level:
instead of queueing every request and letting the slow ones blow the tail,
the router predicts each arrival's queue delay —

    predicted_wait = in_flight_depth * EWMA(service time) / ready_replicas

— and when that prediction exceeds the configured p99 deadline
(--slo_p99_ms), answers **429 Too Many Requests** with a `Retry-After`
header sized from the prediction overshoot. Clients that honor Retry-After
form a closed loop: offered load converges to what the fleet can serve
inside the SLO, and nobody waits in a queue for an answer that would
arrive too late to matter.

Sheds are contract behavior, not errors: tools/serve_bench.py counts them
separately from failures, and every shed emits a `kind:"admission"`
telemetry event so tools/metrics_report.py can report the shed count.

Deadline <= 0 disables shedding (every request admitted) — the fleet then
degrades to pure least-loaded routing with queue-full backpressure.
"""

from __future__ import annotations

import math
import threading
from typing import Optional


class AdmissionController:
    """Predictive bounded-queue admission for one router. Thread-safe:
    `observe` and `check` are called from concurrent handler threads."""

    def __init__(self, deadline_ms: float, recorder=None,
                 ewma_alpha: float = 0.2,
                 warming_capacity_frac: float = 0.5):
        assert 0.0 <= warming_capacity_frac <= 1.0, warming_capacity_frac
        self.deadline_s = deadline_ms / 1000.0
        self.recorder = recorder
        self.ewma_alpha = ewma_alpha
        self.warming_capacity_frac = warming_capacity_frac
        self.ewma_service_s: Optional[float] = None
        self.admitted_total = 0
        self.shed_total = 0
        self._lock = threading.Lock()

    def observe(self, service_s: float) -> None:
        """Fold one successful dispatch's end-to-end service time into the
        EWMA the wait prediction is built on."""
        with self._lock:
            prev = self.ewma_service_s
            self.ewma_service_s = (
                service_s if prev is None else
                self.ewma_alpha * service_s + (1.0 - self.ewma_alpha) * prev)

    def check(self, depth: int, ready_replicas: int,
              warming_replicas: int = 0) -> Optional[int]:
        """Admit (None) or shed (int seconds for Retry-After).

        Capacity counts live-but-warming replicas at
        `warming_capacity_frac` (they will be serving within one warmup,
        so mid-scale-out the prediction relaxes toward the NEW capacity
        instead of shedding at the old estimate until the first replica
        flips ready).

        Admits unconditionally while shedding is off (deadline <= 0), before
        the first observation (no basis for a prediction), or with no ready
        replicas (the router's 503 path owns that case)."""
        with self._lock:
            ewma = self.ewma_service_s
            if self.deadline_s <= 0 or ewma is None or ready_replicas <= 0:
                self.admitted_total += 1
                return None
            capacity = (ready_replicas
                        + self.warming_capacity_frac * max(warming_replicas, 0))
            predicted = depth * ewma / max(capacity, 1e-9)
            if predicted <= self.deadline_s:
                self.admitted_total += 1
                return None
            self.shed_total += 1
            retry_after = max(int(math.ceil(predicted - self.deadline_s)), 1)
        self._event(decision="shed", depth=depth,
                    predicted_wait_s=round(predicted, 6),
                    deadline_ms=self.deadline_s * 1000.0,
                    warming_replicas=warming_replicas,
                    retry_after_s=retry_after)
        return retry_after

    def record_shed(self, **payload) -> None:
        """Count a shed decided elsewhere (a replica answered queue_full and
        the router mapped it to 429) so fleet shed accounting is complete."""
        with self._lock:
            self.shed_total += 1
        self._event(decision="shed", **payload)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "deadline_ms": self.deadline_s * 1000.0,
                "ewma_service_s": (round(self.ewma_service_s, 6)
                                   if self.ewma_service_s is not None
                                   else None),
                "warming_capacity_frac": self.warming_capacity_frac,
                "admitted_total": self.admitted_total,
                "shed_total": self.shed_total,
            }

    def _event(self, **payload) -> None:
        if self.recorder is not None:
            try:
                self.recorder.event("admission", **payload)
            except Exception:  # noqa: BLE001 # vtx: ignore[VTX106] telemetry must not kill admission
                pass
