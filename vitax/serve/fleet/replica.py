"""ReplicaManager: spawn/adopt N serve replicas, health-driven rotation.

The process layer of the fleet (Clipper-style layered serving, NSDI '17):
each replica is one `python -m vitax.serve` engine on its own port — its
adaptive batching, AOT buckets, and telemetry are untouched — and this
module decides which replicas are routable:

- **spawn or adopt**: `manage()` launches a replica subprocess and owns its
  lifecycle (restart-with-backoff on death, SIGTERM drain on shutdown —
  both through the vitax.supervise seams: `backoff_delay`,
  `terminate_child`); `adopt()` registers an externally started endpoint
  (another host, or an in-process stub in tests) that is health-checked
  but never restarted.
- **rotation**: a replica is dispatched to only while READY. The health
  loop polls `GET /healthz`; `ready: false` (warming after restart, or
  draining) or `fail_threshold` consecutive failed polls EJECT it from
  rotation, and a later live-and-ready poll re-admits it. A managed
  replica whose process died is respawned after capped exponential
  backoff and re-enters rotation only once its warmup completes — the
  router never sees a cold replica.
- **load accounting**: the router's least-loaded pick reads the per-replica
  in-flight counter and EWMA latency maintained here via
  `acquire()`/`release()`.

All state transitions emit schema-1 telemetry events (kinds
"replica_spawn" / "replica_exit" / "replica_restart" / "replica_eject" /
"replica_admit") through the shared Recorder when one is attached, so
`tools/metrics_report.py` can fold restart counts out of serve.jsonl.

Stdlib-only by design: the router tier must run on a box with no jax.
"""

from __future__ import annotations

import json
import random
import subprocess
import sys
import threading
import time
import urllib.request
from typing import Callable, Dict, List, Optional, Sequence

from vitax import faults
from vitax.supervise import backoff_delay, terminate_child

# rotation states
STARTING = "starting"   # spawned/adopted, live but not yet warmed
READY = "ready"         # in rotation: healthz answered ready: true
EJECTED = "ejected"     # live but out of rotation (failing or not ready)
DEAD = "dead"           # managed process exited; awaiting backoff + respawn

DEFAULT_HEALTH_INTERVAL_S = 0.5
DEFAULT_HEALTH_TIMEOUT_S = 5.0
DEFAULT_FAIL_THRESHOLD = 2
DEFAULT_BACKOFF_S = 0.5
DEFAULT_BACKOFF_MAX_S = 30.0
DEFAULT_MAX_RESTARTS = 10
DEFAULT_TERM_GRACE_S = 30.0
DEFAULT_EWMA_ALPHA = 0.2
DEFAULT_HEALTH_JITTER = 0.2  # +-20% per-sweep jitter on the health interval


def http_get_json(url: str, timeout: float) -> dict:
    """Default health/metrics probe (injectable for tests)."""
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.load(resp)


class Replica:
    """One serve endpoint and its rotation/load state. All mutable fields
    are guarded by the owning ReplicaManager's lock."""

    def __init__(self, name: str, url: str,
                 argv: Optional[Sequence[str]] = None, proc=None):
        self.name = name
        self.url = url.rstrip("/")
        self.argv = list(argv) if argv is not None else None
        self.proc = proc                 # None for adopted replicas
        self.state = STARTING
        self.retired = False             # scale-in: out of rotation for good
        self.in_flight = 0
        self.ewma_latency_s: Optional[float] = None
        self.requests_total = 0
        self.dispatch_failures = 0       # router-side failed dispatches
        self.health_failures = 0         # consecutive failed health polls
        self.restarts = 0
        self.exit_code: Optional[int] = None
        self.restart_not_before = 0.0    # monotonic clock gate (backoff)
        self.last_health: dict = {}

    @property
    def managed(self) -> bool:
        return self.argv is not None

    def snapshot(self) -> dict:
        return {
            "url": self.url,
            "state": self.state,
            "retired": self.retired,
            "managed": self.managed,
            "in_flight": self.in_flight,
            "ewma_latency_s": (round(self.ewma_latency_s, 6)
                               if self.ewma_latency_s is not None else None),
            "requests_total": self.requests_total,
            "dispatch_failures": self.dispatch_failures,
            "health_failures": self.health_failures,
            "restarts": self.restarts,
            "exit_code": self.exit_code,
        }


class ReplicaManager:
    """Fleet rotation + lifecycle. `spawn`, `http_get`, `sleep` and `clock`
    are injectable so ejection/re-admission/restart logic is unit-testable
    with no real processes or sockets (tests/test_fleet.py)."""

    def __init__(self, recorder=None,
                 health_interval_s: float = DEFAULT_HEALTH_INTERVAL_S,
                 health_timeout_s: float = DEFAULT_HEALTH_TIMEOUT_S,
                 fail_threshold: int = DEFAULT_FAIL_THRESHOLD,
                 backoff_s: float = DEFAULT_BACKOFF_S,
                 backoff_max_s: float = DEFAULT_BACKOFF_MAX_S,
                 max_restarts: int = DEFAULT_MAX_RESTARTS,
                 term_grace_s: float = DEFAULT_TERM_GRACE_S,
                 ewma_alpha: float = DEFAULT_EWMA_ALPHA,
                 health_jitter: float = DEFAULT_HEALTH_JITTER,
                 spawn: Optional[Callable] = None,
                 http_get: Optional[Callable[[str, float], dict]] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 clock: Callable[[], float] = time.monotonic,
                 rng: Optional[random.Random] = None):
        assert fail_threshold >= 1, fail_threshold
        assert max_restarts >= 0, max_restarts
        assert 0.0 <= health_jitter < 1.0, health_jitter
        self.recorder = recorder
        self.health_interval_s = health_interval_s
        self.health_timeout_s = health_timeout_s
        self.fail_threshold = fail_threshold
        self.backoff_s = backoff_s
        self.backoff_max_s = backoff_max_s
        self.max_restarts = max_restarts
        self.term_grace_s = term_grace_s
        self.ewma_alpha = ewma_alpha
        self.health_jitter = health_jitter
        self._rng = rng or random.Random()
        self.replicas: List[Replica] = []
        self._name_seq = 0               # monotonic: discard never recycles names
        self.restart_total = 0
        self.started = time.time()
        self._lock = threading.Lock()
        self._spawn = spawn or (lambda argv: subprocess.Popen(argv))
        self._http_get = http_get or http_get_json
        self._sleep = sleep
        self._clock = clock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- registration --------------------------------------------------------

    def manage(self, argv: Sequence[str], url: str,
               name: Optional[str] = None) -> Replica:
        """Spawn a replica subprocess and own its lifecycle (restart on
        death, SIGTERM drain on stop)."""
        with self._lock:
            name = name or f"replica_{self._name_seq}"
            self._name_seq += 1
        replica = Replica(name, url, argv=argv, proc=self._spawn(list(argv)))
        with self._lock:
            self.replicas.append(replica)
        self._event("replica_spawn", replica=name, url=url)
        return replica

    def adopt(self, url: str, name: Optional[str] = None) -> Replica:
        """Register an externally started replica: health-checked and
        rotated, never restarted (its lifecycle belongs to someone else)."""
        with self._lock:
            name = name or f"replica_{self._name_seq}"
            self._name_seq += 1
        replica = Replica(name, url)
        with self._lock:
            self.replicas.append(replica)
        self._event("replica_spawn", replica=name, url=url, adopted=True)
        return replica

    # -- rotation / load accounting ------------------------------------------

    def ready_replicas(self) -> List[Replica]:
        with self._lock:
            return [r for r in self.replicas
                    if r.state == READY and not r.retired]

    def ready_count(self) -> int:
        return len(self.ready_replicas())

    def warming_count(self) -> int:
        """Live-but-warming replicas: spawned/adopted/restarted, not yet
        admitting traffic (STARTING until their own /healthz turns ready).
        Admission control counts these at --warming_capacity_frac so an
        in-progress scale-out relieves the predicted wait instead of the
        fleet shedding at the old capacity estimate."""
        with self._lock:
            return sum(1 for r in self.replicas
                       if r.state == STARTING and not r.retired)

    def active_count(self) -> int:
        """Fleet size for scaling decisions: every replica not retired
        (STARTING/READY/EJECTED/DEAD-awaiting-restart all count — they are
        capacity the fleet still owns or will recover)."""
        with self._lock:
            return sum(1 for r in self.replicas if not r.retired)

    def total_in_flight(self) -> int:
        with self._lock:
            return sum(r.in_flight for r in self.replicas)

    def in_flight_of(self, replica: Replica) -> int:
        with self._lock:
            return replica.in_flight

    def find(self, name: str) -> Optional[Replica]:
        with self._lock:
            for r in self.replicas:
                if r.name == name:
                    return r
            return None

    def degraded_count(self) -> int:
        """Replicas whose last /healthz advertised brownout (degraded:
        true) — serving, but shedding optional work. The router folds this
        into the fleet aggregate."""
        with self._lock:
            return sum(1 for r in self.replicas
                       if bool(r.last_health.get("degraded")))

    def degraded_seconds(self) -> float:
        """Fleet-wide brownout time: sum of each replica's advertised
        degraded_seconds (its BrownoutController odometer) at last poll."""
        with self._lock:
            return round(sum(
                float(r.last_health.get("degraded_seconds") or 0.0)
                for r in self.replicas), 3)

    def acquire(self, exclude: Sequence[str] = ()) -> Optional[Replica]:
        """Least-loaded pick: the READY replica with the fewest in-flight
        requests, ties broken by EWMA latency. Increments its in-flight
        count — pair every acquire with a release()."""
        with self._lock:
            candidates = [r for r in self.replicas
                          if r.state == READY and not r.retired
                          and r.name not in exclude]
            if not candidates:
                return None
            best = min(candidates,
                       key=lambda r: (r.in_flight, r.ewma_latency_s or 0.0))
            best.in_flight += 1
            return best

    def release(self, replica: Replica, latency_s: Optional[float] = None,
                ok: bool = True, counted: bool = True) -> None:
        """Pair of acquire(). `counted=False` undoes the acquire without
        charging a success or failure (the router's breaker uses it when it
        returns a picked replica unused — e.g. losing a half-open probe
        race — so accounting reflects only real dispatches)."""
        with self._lock:
            replica.in_flight = max(replica.in_flight - 1, 0)
            if not counted:
                return
            if ok:
                replica.requests_total += 1
                if latency_s is not None:
                    prev = replica.ewma_latency_s
                    replica.ewma_latency_s = (
                        latency_s if prev is None else
                        self.ewma_alpha * latency_s
                        + (1.0 - self.ewma_alpha) * prev)
            else:
                replica.dispatch_failures += 1

    # -- scale-in lifecycle ----------------------------------------------------

    def retire(self, replica: Replica) -> None:
        """Take a replica out of rotation for good (scale-in step 1): no
        new dispatches, and the health loop will never re-admit it. Its
        in-flight requests keep draining — pair with discard() once
        in_flight reaches zero."""
        with self._lock:
            if replica.retired:
                return
            replica.retired = True
            if replica.state == READY:
                replica.state = EJECTED
        self._event("replica_retire", replica=replica.name)

    def discard(self, replica: Replica) -> Optional[int]:
        """Remove a replica from the fleet (scale-in step 2). A managed
        process still alive is SIGTERM-drained through terminate_child —
        the replica's own drain contract answers anything left in flight
        before it exits. Returns the exit code (None for adopted
        replicas, whose processes belong to someone else)."""
        rc = None
        if replica.proc is not None and replica.proc.poll() is None:
            rc = terminate_child(replica.proc, self.term_grace_s,
                                 sleep=self._sleep)
        with self._lock:
            replica.state = DEAD
            replica.retired = True
            if rc is not None:
                replica.exit_code = rc
            if replica in self.replicas:
                self.replicas.remove(replica)
        self._event("replica_discard", replica=replica.name, exit_code=rc)
        return rc

    # -- health loop ----------------------------------------------------------

    def poll_once(self, now: Optional[float] = None) -> None:
        """One health sweep over the fleet (the background loop calls this
        every health_interval_s; tests call it directly). Retired replicas
        are skipped: they are draining toward discard() and must never be
        re-admitted or respawned."""
        now = self._clock() if now is None else now
        with self._lock:  # manage()/adopt() append concurrently
            fleet = [r for r in self.replicas if not r.retired]
        for replica in fleet:
            self._poll_replica(replica, now)

    def _poll_replica(self, r: Replica, now: float) -> None:
        if r.proc is not None:
            rc = r.proc.poll()
            if rc is not None:
                self._handle_dead(r, rc, now)
                return
        try:
            # chaos hook: `oserror` here is one flaky probe — probes sweep
            # the fleet in registration order, so with N replicas index
            # k*N + i targets replica i deterministically
            faults.fire("replica_health")
            payload = self._http_get(r.url + "/healthz",
                                     self.health_timeout_s)
            live = payload.get("status") == "ok"
            # replicas predating the liveness/readiness split have no
            # "ready" key: live implies routable for them
            ready = bool(payload.get("ready", True))
        except Exception:  # noqa: BLE001 — any probe failure means not live
            payload, live, ready = {}, False, False
        if live and ready:
            with self._lock:
                previous, r.state = r.state, READY
                r.health_failures = 0
                r.last_health = payload
            if previous != READY:
                self._event("replica_admit", replica=r.name,
                            previous_state=previous)
        elif live:
            # warming (after spawn/restart) or draining: out of rotation,
            # but alive — not a health FAILURE, so no failure count
            with self._lock:
                previous = r.state
                if r.state == READY:
                    r.state = EJECTED
                r.health_failures = 0
                r.last_health = payload
            if previous == READY:
                self._event("replica_eject", replica=r.name,
                            reason="not_ready")
        else:
            with self._lock:
                r.health_failures += 1
                eject = (r.state == READY
                         and r.health_failures >= self.fail_threshold)
                if eject:
                    r.state = EJECTED
                failures = r.health_failures
            if eject:
                self._event("replica_eject", replica=r.name,
                            reason=f"{failures} consecutive healthz failures")

    def _handle_dead(self, r: Replica, rc: int, now: float) -> None:
        with self._lock:
            first = r.state != DEAD
            if first:
                r.state = DEAD
                r.in_flight = 0
                r.exit_code = rc
                r.health_failures = 0
                r.restart_not_before = now + backoff_delay(
                    r.restarts + 1, self.backoff_s, self.backoff_max_s)
        if first:
            self._event("replica_exit", replica=r.name, exit_code=rc,
                        restarts=r.restarts)
            return
        if r.restarts >= self.max_restarts or now < r.restart_not_before:
            return
        proc = self._spawn(list(r.argv))
        with self._lock:
            r.proc = proc
            r.state = STARTING       # re-warms; re-admitted via healthz
            r.restarts += 1
            r.exit_code = None
            self.restart_total += 1
            restart = r.restarts
        self._event("replica_restart", replica=r.name, restart=restart)

    def start(self) -> None:
        """Launch the background health loop."""
        assert self._thread is None, "health loop already running"
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="vitax-fleet-health")
        self._thread.start()

    def _next_interval(self) -> float:
        """Jittered sleep before the next health sweep: uniform in
        health_interval_s * [1 - jitter, 1 + jitter]. Without jitter every
        manager in a deployment polls on the same cadence and a slow fleet
        sees synchronized probe bursts (a thundering herd against replicas
        already struggling to answer)."""
        if self.health_jitter <= 0.0:
            return self.health_interval_s
        spread = self.health_jitter * (2.0 * self._rng.random() - 1.0)
        return self.health_interval_s * (1.0 + spread)

    def _loop(self) -> None:
        while not self._stop.wait(timeout=self._next_interval()):
            try:
                self.poll_once()
            except Exception as e:  # noqa: BLE001 — health loop must survive
                print(f"[vitax.fleet] health sweep failed: "
                      f"{type(e).__name__}: {e}", file=sys.stderr, flush=True)

    def stop(self) -> None:
        """Stop the health loop, then SIGTERM-drain every managed replica
        (their serve drain answers in-flight requests and exits 0)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.health_interval_s * 4 + 5.0)
            self._thread = None
        with self._lock:
            fleet = list(self.replicas)
        for r in fleet:
            if r.proc is not None and r.proc.poll() is None:
                rc = terminate_child(r.proc, self.term_grace_s,
                                     sleep=self._sleep)
                with self._lock:
                    r.state = DEAD
                    r.exit_code = rc
                self._event("replica_exit", replica=r.name, exit_code=rc,
                            drained=True)

    # -- observability ---------------------------------------------------------

    def snapshot(self) -> Dict[str, dict]:
        """Per-replica rotation/load state for the router's /metrics."""
        now = time.time()
        uptime = max(now - self.started, 1e-9)
        with self._lock:
            out = {}
            for r in self.replicas:
                snap = r.snapshot()
                snap["requests_per_sec"] = round(
                    r.requests_total / uptime, 3)
                out[r.name] = snap
            return out

    def _event(self, kind: str, **payload) -> None:
        if self.recorder is not None:
            try:
                self.recorder.event(kind, **payload)
            except Exception:  # noqa: BLE001 # vtx: ignore[VTX106] telemetry must not kill the fleet
                pass
