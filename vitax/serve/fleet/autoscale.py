"""Autoscaler: grow and shrink the fleet from signals it already emits.

PR 8 gave the fleet a static replica count and PR 13 gave it reflexes
(breakers, hedges, brownout); this loop gives it growth. No new
measurement machinery — every input is a signal the serve stack already
maintains:

scale-OUT (any one, sustained for `dwell_s`):
- **sustained admission sheds**: the AdmissionController's shed counter is
  advancing at >= `shed_rate_per_s` — clients are being turned away at the
  current capacity estimate;
- **predicted-wait overshoot**: depth * EWMA(service) / effective capacity
  is at or above the --slo_p99_ms deadline — the same prediction admission
  sheds on, read before it starts shedding in volume;
- **brownout dwell**: any replica advertises degraded: true — a replica is
  already shedding optional work to stay alive.

scale-IN (sustained for `dwell_s`, only when no scale-out signal fires):
- **idle occupancy**: in-flight per READY replica at or below
  `idle_occupancy` with zero shed pressure.

Both directions are guarded by the two classic chatter guards composed
(the BrownoutController pattern): a `dwell_s` streak requirement so blips
never scale, and a `cooldown_s` dead time after every action so the loop
observes the consequences of one decision before making another. Fleet
size is clamped to [min_replicas, max_replicas]; a fleet that fell below
the floor (a replica exhausted its restart budget) is repaired on the next
tick regardless of traffic.

A new replica enters through the existing lifecycle: STARTING until its
own /healthz reports ready (AOT warmup done), so a scaling fleet never
routes to a cold replica — the autoscaler only adds capacity, the health
loop decides routability.

Scale-in never strands a request: the victim is **retired** first (out of
rotation, never re-admitted), the loop then waits for its in-flight count
to reach zero before discarding it — and discard itself SIGTERM-drains a
managed process (the PR 8 drain contract: in-flight answered, exit 0), so
even the `drain_timeout_s` force path cannot drop accepted work.

Provisioning is delegated: `scale_out()` returns a new Replica (local
spawn via ReplicaManager.manage, or a cross-host placement provision +
adopt — see placement.py) and `release(replica)` frees remote resources
after a drain. When local scale-out is DENIED — the fleet is at
max_replicas, or every placement agent is full — a `request_capacity`
closure (the chip arbiter, vitax/arbiter) escalates the sustained demand
to the pod instead of silently cooling down, recorded as an autoscale
event with outcome "escalated". `clock` is injectable so hysteresis is
unit-testable with no real time (tests/test_autoscale.py).

Stdlib-only: the router tier must run on a box with no jax.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Callable, Optional

from vitax.serve.fleet.replica import ReplicaManager

DEFAULT_INTERVAL_S = 0.5
DEFAULT_DWELL_S = 3.0
DEFAULT_COOLDOWN_S = 10.0
DEFAULT_SHED_RATE_PER_S = 1.0
DEFAULT_WAIT_OVERSHOOT_FRAC = 1.0
DEFAULT_IDLE_OCCUPANCY = 0.25
DEFAULT_DRAIN_TIMEOUT_S = 30.0


class Autoscaler:
    """Hysteretic fleet sizing over an existing ReplicaManager."""

    def __init__(self, manager: ReplicaManager, admission=None,
                 min_replicas: int = 1, max_replicas: int = 1,
                 scale_out: Optional[Callable[[], object]] = None,
                 release: Optional[Callable[[object], None]] = None,
                 request_capacity: Optional[Callable[[str], object]] = None,
                 interval_s: float = DEFAULT_INTERVAL_S,
                 dwell_s: float = DEFAULT_DWELL_S,
                 cooldown_s: float = DEFAULT_COOLDOWN_S,
                 shed_rate_per_s: float = DEFAULT_SHED_RATE_PER_S,
                 wait_overshoot_frac: float = DEFAULT_WAIT_OVERSHOOT_FRAC,
                 idle_occupancy: float = DEFAULT_IDLE_OCCUPANCY,
                 drain_timeout_s: float = DEFAULT_DRAIN_TIMEOUT_S,
                 recorder=None,
                 clock: Callable[[], float] = time.monotonic):
        assert 1 <= min_replicas <= max_replicas, (min_replicas, max_replicas)
        assert dwell_s >= 0 and cooldown_s >= 0, (dwell_s, cooldown_s)
        assert shed_rate_per_s > 0, shed_rate_per_s
        assert idle_occupancy >= 0, idle_occupancy
        self.manager = manager
        self.admission = admission
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.interval_s = interval_s
        self.dwell_s = dwell_s
        self.cooldown_s = cooldown_s
        self.shed_rate_per_s = shed_rate_per_s
        self.wait_overshoot_frac = wait_overshoot_frac
        self.idle_occupancy = idle_occupancy
        self.drain_timeout_s = drain_timeout_s
        self.recorder = recorder
        self._clock = clock
        self._scale_out_fn = scale_out
        self._release_fn = release
        # escalation closure (the chip arbiter, vitax/arbiter): sustained
        # pressure the fleet CANNOT answer locally — at max_replicas, or
        # every placement agent full — asks the pod for more chips instead
        # of silently cooling down
        self._request_capacity_fn = request_capacity
        self._lock = threading.Lock()
        # hysteresis state (all guarded by _lock)
        self._pressure_since: Optional[float] = None
        self._idle_since: Optional[float] = None
        self._cooldown_until = 0.0
        self._last_tick: Optional[float] = None
        self._shed_seen = 0
        self._shed_rate = 0.0
        self._draining = None            # Replica being drained for scale-in
        self._drain_deadline = 0.0
        self.scale_out_total = 0
        self.scale_in_total = 0
        self.escalations_total = 0
        self.last_event: Optional[dict] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- signal gathering -----------------------------------------------------

    def _signals(self, now: float) -> dict:
        """One sample of every input, read OUTSIDE self._lock (the manager
        and admission controller have their own locks; never nested)."""
        ready = self.manager.ready_count()
        depth = self.manager.total_in_flight()
        degraded = self.manager.degraded_count()
        warming = self.manager.warming_count()
        active = self.manager.active_count()
        adm = self.admission.snapshot() if self.admission is not None else {}
        return {"ready": ready, "depth": depth, "degraded": degraded,
                "warming": warming, "active": active,
                "shed_total": adm.get("shed_total", 0),
                "ewma_service_s": adm.get("ewma_service_s"),
                "deadline_s": (adm.get("deadline_ms") or 0.0) / 1000.0,
                "warming_frac": adm.get("warming_capacity_frac", 0.5)}

    def _pressure(self, sig: dict) -> Optional[str]:
        """Which scale-out signal fires, or None. Warming replicas count at
        the admission discount so an in-progress scale-out relieves the
        predicted wait instead of stacking decisions."""
        if self._shed_rate >= self.shed_rate_per_s:
            return "shed_rate"
        ewma, deadline = sig["ewma_service_s"], sig["deadline_s"]
        if deadline > 0 and ewma:
            capacity = sig["ready"] + sig["warming_frac"] * sig["warming"]
            predicted = sig["depth"] * ewma / max(capacity, 1e-9)
            if predicted >= deadline * self.wait_overshoot_frac:
                return "predicted_wait"
        if sig["degraded"] > 0:
            return "brownout"
        return None

    # -- decision loop --------------------------------------------------------

    def tick(self, now: Optional[float] = None) -> Optional[str]:
        """One evaluation (the background loop calls this every
        `interval_s`; tests call it directly). Returns the action taken
        ("scale_out" / "scale_in" / "retire") or None."""
        now = self._clock() if now is None else now
        sig = self._signals(now)
        with self._lock:
            # shed rate over the tick interval (events/second)
            if self._last_tick is not None and now > self._last_tick:
                delta = sig["shed_total"] - self._shed_seen
                self._shed_rate = delta / (now - self._last_tick)
            self._shed_seen = sig["shed_total"]
            self._last_tick = now
            draining = self._draining
        if draining is not None:
            return self._continue_drain(draining, now)
        pressure = None
        action = None
        with self._lock:
            pressure = self._pressure(sig)
            if pressure is not None:
                self._idle_since = None
                if self._pressure_since is None:
                    self._pressure_since = now
                sustained = now - self._pressure_since >= self.dwell_s
                if sustained and now >= self._cooldown_until:
                    if (sig["active"] < self.max_replicas
                            and self._scale_out_fn is not None):
                        action = "scale_out"
                    elif self._request_capacity_fn is not None:
                        # denied locally (ceiling, or nothing to spawn
                        # with): escalate to the arbiter
                        action = "escalate"
            else:
                self._pressure_since = None
                occupancy = sig["depth"] / max(sig["ready"], 1)
                idle = (sig["ready"] > 0 and self._shed_rate == 0.0
                        and occupancy <= self.idle_occupancy)
                if idle:
                    if self._idle_since is None:
                        self._idle_since = now
                    sustained = now - self._idle_since >= self.dwell_s
                    if (sustained and now >= self._cooldown_until
                            and sig["active"] > self.min_replicas):
                        action = "retire"
                else:
                    self._idle_since = None
            # floor repair: a fleet below min (restart budget exhausted)
            # grows back regardless of traffic
            if (action is None and sig["active"] < self.min_replicas
                    and now >= self._cooldown_until
                    and self._scale_out_fn is not None):
                action, pressure = "scale_out", "below_min"
        if action == "scale_out":
            return self._do_scale_out(pressure, now, sig)
        if action == "escalate":
            return self._do_escalate(pressure, now, sig)
        if action == "retire":
            return self._do_retire(now, sig)
        return None

    def _do_scale_out(self, reason: str, now: float, sig: dict):
        try:
            replica = self._scale_out_fn()
        except Exception as e:  # noqa: BLE001 — a failed provision must not kill the loop
            replica = None
            self._event(event="scale_out_failed", reason=reason,
                        detail=f"{type(e).__name__}: {e}")
            if self._request_capacity_fn is not None:
                # "no free agent slot" surfaces here (every placement
                # agent returned 409): same escalation as the ceiling case
                return self._do_escalate(reason, now, sig)
        with self._lock:
            self._pressure_since = None
            self._cooldown_until = now + self.cooldown_s
            if replica is None:
                return None
            self.scale_out_total += 1
            self.last_event = {"event": "scale_out", "reason": reason,
                               "replica": getattr(replica, "name", "?"),
                               "size": sig["active"] + 1}
        self._event(**self.last_event)
        return "scale_out"

    def _do_escalate(self, reason: str, now: float, sig: dict):
        """Sustained pressure the fleet cannot answer locally: hand the
        demand to the arbiter (request_capacity closure) and cool down —
        the borrowed capacity arrives asynchronously via /fleet/adopt, so
        this tick's job ends at the ask. The autoscale event grows an
        `escalated` outcome so a starved fleet is visible in
        metrics_report, not silent."""
        try:
            self._request_capacity_fn(reason)
        except Exception as e:  # noqa: BLE001 — an unreachable arbiter must not kill the loop
            self._event(event="escalate_failed", reason=reason,
                        detail=f"{type(e).__name__}: {e}")
            with self._lock:
                self._pressure_since = None
                self._cooldown_until = now + self.cooldown_s
            return None
        with self._lock:
            self._pressure_since = None
            self._cooldown_until = now + self.cooldown_s
            self.escalations_total += 1
            self.last_event = {"event": "scale_out", "outcome": "escalated",
                               "reason": reason, "size": sig["active"]}
        self._event(**self.last_event)
        return "escalated"

    def _do_retire(self, now: float, sig: dict):
        """Start a scale-in: pick the least-loaded READY replica, take it
        out of rotation (never re-admitted), and let _continue_drain kill
        it only once its in-flight count reaches zero."""
        victim, victim_flight = None, 0
        for r in self.manager.ready_replicas():
            flight = self.manager.in_flight_of(r)
            if victim is None or flight < victim_flight:
                victim, victim_flight = r, flight
        if victim is None:
            return None
        self.manager.retire(victim)
        with self._lock:
            self._idle_since = None
            self._draining = victim
            self._drain_deadline = now + self.drain_timeout_s
            self._cooldown_until = now + self.cooldown_s
        self._event(event="retire", replica=victim.name,
                    in_flight=victim_flight, size=sig["active"])
        return "retire"

    def _continue_drain(self, replica, now: float):
        """Finish a scale-in once the retired replica is idle. The normal
        path discards only at in_flight == 0; the `drain_timeout_s` force
        path still SIGTERM-drains (terminate_child -> the replica's own
        drain answers whatever is left), so no accepted request is ever
        dropped either way."""
        in_flight = self.manager.in_flight_of(replica)
        with self._lock:
            deadline = self._drain_deadline
        if in_flight > 0 and now < deadline:
            return None
        forced = in_flight > 0
        if self._release_fn is not None:
            try:
                self._release_fn(replica)
            except Exception as e:  # noqa: BLE001 — remote release is best-effort
                self._event(event="release_failed", replica=replica.name,
                            detail=f"{type(e).__name__}: {e}")
        self.manager.discard(replica)
        with self._lock:
            self._draining = None
            self.scale_in_total += 1
            self._cooldown_until = now + self.cooldown_s
            self.last_event = {"event": "scale_in", "replica": replica.name,
                               "forced": forced,
                               "size": self.manager.active_count()}
        self._event(**self.last_event)
        return "scale_in"

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> None:
        assert self._thread is None, "autoscaler loop already running"
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="vitax-fleet-autoscaler")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(timeout=self.interval_s):
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 — the loop must survive
                print(f"[vitax.fleet] autoscaler tick failed: "
                      f"{type(e).__name__}: {e}", file=sys.stderr, flush=True)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval_s * 4 + 5.0)
            self._thread = None

    # -- observability ---------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas,
                "scale_out_total": self.scale_out_total,
                "scale_in_total": self.scale_in_total,
                "escalations_total": self.escalations_total,
                "shed_rate_per_s": round(self._shed_rate, 4),
                "draining": (self._draining.name
                             if self._draining is not None else None),
                "last_event": self.last_event,
            }

    def _event(self, **payload) -> None:
        if self.recorder is not None:
            try:
                self.recorder.event("autoscale", **payload)
            except Exception:  # noqa: BLE001 # vtx: ignore[VTX106] telemetry must not kill scaling
                pass
