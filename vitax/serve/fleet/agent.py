"""CLI entry: python -m vitax.serve.fleet.agent — one placement agent per host.

The remote half of cross-host placement (see placement.py): binds the
agent API and waits. A fleet router provisions replicas here with
`--placement_agents http://this-host:7070`, and every replica this agent
spawns is supervised locally (restart-with-backoff, SIGTERM drain) via
the agent's own ReplicaManager.

    python -m vitax.serve.fleet.agent --agent_port 7070 \\
        --agent_advertise 10.0.0.7 --agent_base_port 8100

SIGTERM/SIGINT shut the API down, then SIGTERM-drain every replica the
agent still owns (in-flight answered, exit 0).
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading

from vitax.serve.fleet.placement import (PlacementAgent, start_agent,
                                         stop_agent, DEFAULT_AGENT_PORT,
                                         DEFAULT_BASE_PORT)


def build_agent_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m vitax.serve.fleet.agent",
        description="vitax placement agent: spawn/supervise serve replicas "
                    "on this host for a remote fleet router")
    parser.add_argument("--agent_port", type=int, default=DEFAULT_AGENT_PORT,
                        help="port the agent API binds (0 = ephemeral)")
    parser.add_argument("--agent_advertise", type=str, default="127.0.0.1",
                        help="host embedded in provisioned replica URLs — "
                             "the address the ROUTER can reach this host at")
    parser.add_argument("--agent_base_port", type=int,
                        default=DEFAULT_BASE_PORT,
                        help="replica i spawned by this agent binds "
                             "base_port + i (provision may pin an explicit "
                             "port instead)")
    parser.add_argument("--health_interval_s", type=float, default=0.5,
                        help="seconds between the agent's replica /healthz "
                             "sweeps")
    parser.add_argument("--replica_max_restarts", type=int, default=10,
                        help="restarts-with-backoff per replica before the "
                             "agent gives up on it")
    parser.add_argument("--agent_max_replicas", type=int, default=0,
                        help="replica slots on this host (0 = unbounded); "
                             "/provision answers 409 once every slot is "
                             "taken so callers try another host or escalate")
    return parser


def main(argv=None) -> int:
    ns = build_agent_parser().parse_args(argv)
    agent = PlacementAgent(
        advertise_host=ns.agent_advertise, base_port=ns.agent_base_port,
        health_interval_s=ns.health_interval_s,
        max_slots=ns.agent_max_replicas,
        max_restarts=ns.replica_max_restarts)
    httpd = start_agent(agent, ns.agent_port)
    print(f"placement agent: API on :{httpd.server_address[1]}, replicas "
          f"from :{ns.agent_base_port} (advertised as {ns.agent_advertise})",
          flush=True)

    stop = threading.Event()

    def _on_signal(signum, frame):  # noqa: ARG001 — handler signature
        stop.set()

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, _on_signal)
        except ValueError:
            pass  # not the main thread (embedded use)
    while not stop.wait(timeout=0.5):
        pass
    print("placement agent: shutting down (replica drains)", flush=True)
    stop_agent(httpd, agent)
    return 0


if __name__ == "__main__":
    sys.exit(main())
