"""Least-loaded HTTP router: one front door over N serve replicas.

Speaks the exact single-engine contract (POST /predict, GET /healthz,
GET /metrics — clients cannot tell a fleet from one replica) and adds the
fleet behaviors on top:

- **least-loaded dispatch**: each /predict goes to the READY replica with
  the fewest in-flight requests (ties broken by EWMA latency), via
  ReplicaManager.acquire()/release();
- **one retry**: a dispatch failure (connection refused, replica 5xx,
  socket timeout) is retried once on a DIFFERENT replica — /predict is
  idempotent, so the retry is safe and hides single-replica deaths from
  clients;
- **circuit breakers** (vitax/serve/fleet/breaker.py): per-replica
  closed -> open after `breaker_threshold` consecutive dispatch failures,
  half-open single-probe re-admission after `breaker_cooldown_s`. Distinct
  from the manager's health ejection, which only sees /healthz — the
  breaker sees actual dispatches, so a replica that answers health probes
  but fails every request is still contained;
- **retry budget**: retries and hedges spend a token bucket refilled at
  `retry_budget_ratio` per request, so a dying fleet degrades to fast
  503s (reason "retry_budget_exhausted") instead of a retry storm;
- **hedged requests** (opt-in, `--hedge_after_ms`): when the first attempt
  exceeds max(hedge_after_ms, rolling p99), a second attempt fires on a
  DIFFERENT replica; first response wins, the loser is ignored (its
  thread still releases its in-flight slot). Hedges draw from the same
  retry budget;
- **admission control**: before dispatch, the AdmissionController predicts
  this request's queue delay; over-deadline arrivals get 429 +
  Retry-After (see admission.py). A replica's own queue-full 503 is
  mapped to the same 429 shed — backpressure composes up the stack;
- **fleet metrics**: GET /metrics aggregates router-side p50/p95/p99 and
  per-replica rotation/load state, folding in each ready replica's own
  /metrics (including its brownout `degraded` flag), breaker states, and
  retry-budget counters, so one scrape shows the whole fleet.

Chaos: the `router_dispatch` fault site (vitax/faults.py) fires once per
dispatch attempt, so the retry/breaker/budget paths are drillable without
a sick replica.

Stdlib-only and jax-free: the router runs on a box with no accelerator.
"""

from __future__ import annotations

import json
import queue as queue_mod
import threading
import time
import urllib.error
import urllib.request
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple

from vitax import faults
from vitax.serve.fleet.admission import AdmissionController
from vitax.serve.fleet.breaker import (CircuitBreaker, RetryBudget,
                                       DEFAULT_BUDGET_RATIO,
                                       DEFAULT_COOLDOWN_S,
                                       DEFAULT_FAIL_THRESHOLD)
from vitax.serve.fleet.replica import ReplicaManager

DISPATCH_ATTEMPTS = 2  # first pick + one retry on a different replica


def _percentile(sorted_vals, q: float) -> Optional[float]:
    if not sorted_vals:
        return None
    pos = (len(sorted_vals) - 1) * q
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return float(sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac)


class RouterMetrics:
    """Thread-safe router-side counters behind the fleet GET /metrics."""

    def __init__(self, window: int = 4096):
        self._lock = threading.Lock()
        self.started = time.time()
        self.requests_total = 0
        self.errors_total = 0
        self.shed_total = 0
        self.retries_total = 0
        self.hedges_total = 0
        self.hedge_wins_total = 0
        self._latency = deque(maxlen=window)
        self._times = deque(maxlen=window)

    def observe(self, latency_s: float) -> None:
        with self._lock:
            self.requests_total += 1
            self._latency.append(latency_s)
            self._times.append(time.time())

    def error(self) -> None:
        with self._lock:
            self.errors_total += 1

    def shed(self) -> None:
        with self._lock:
            self.shed_total += 1

    def retry(self) -> None:
        with self._lock:
            self.retries_total += 1

    def hedge(self) -> None:
        with self._lock:
            self.hedges_total += 1

    def hedge_win(self) -> None:
        with self._lock:
            self.hedge_wins_total += 1

    def p99(self) -> Optional[float]:
        """Rolling client-latency p99 — the hedge trigger threshold."""
        with self._lock:
            lat = sorted(self._latency)
        return _percentile(lat, 0.99)

    def snapshot(self) -> dict:
        with self._lock:
            lat = sorted(self._latency)
            times = list(self._times)
            total, errors = self.requests_total, self.errors_total
            shed, retries = self.shed_total, self.retries_total
            hedges, hedge_wins = self.hedges_total, self.hedge_wins_total
        now = time.time()
        recent = [t for t in times if now - t <= 60.0]
        return {
            "requests_total": total,
            "errors_total": errors,
            "shed_total": shed,
            "retries_total": retries,
            "hedges_total": hedges,
            "hedge_wins_total": hedge_wins,
            "uptime_s": round(now - self.started, 3),
            "requests_per_sec": round(total / max(now - self.started, 1e-9), 3),
            "requests_per_sec_60s": round(len(recent) / 60.0, 3),
            "latency_s_p50": _percentile(lat, 0.50),
            "latency_s_p95": _percentile(lat, 0.95),
            "latency_s_p99": _percentile(lat, 0.99),
        }


class Router:
    """Dispatch policy + fleet observability; the HTTP shell is
    start_router(). Separated so tests drive dispatch() directly."""

    def __init__(self, manager: ReplicaManager,
                 admission: Optional[AdmissionController] = None,
                 recorder=None, request_timeout_s: float = 60.0,
                 breaker_threshold: int = DEFAULT_FAIL_THRESHOLD,
                 breaker_cooldown_s: float = DEFAULT_COOLDOWN_S,
                 retry_budget_ratio: float = DEFAULT_BUDGET_RATIO,
                 hedge_after_ms: float = 0.0):
        assert hedge_after_ms >= 0, hedge_after_ms
        self.manager = manager
        self.admission = admission
        self.recorder = recorder
        self.request_timeout_s = request_timeout_s
        self.breaker_threshold = breaker_threshold
        self.breaker_cooldown_s = breaker_cooldown_s
        self.hedge_after_ms = hedge_after_ms
        self.budget = RetryBudget(ratio=retry_budget_ratio)
        self.metrics = RouterMetrics()
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._breaker_lock = threading.Lock()

    # -- dispatch --------------------------------------------------------------

    def dispatch(self, body: bytes,
                 content_type: str) -> Tuple[int, dict, object]:
        """Route one /predict. Returns (status, extra headers, payload):
        payload is raw bytes on 200 (the replica's JSON passed through
        verbatim) and a dict (to be JSON-encoded) otherwise."""
        ready = self.manager.ready_count()
        if ready == 0:
            self.metrics.error()
            return 503, {"Retry-After": "1"}, {
                "error": "no ready replicas", "reason": "no_ready_replicas"}
        if self.admission is not None:
            retry_after = self.admission.check(
                self.manager.total_in_flight(), ready)
            if retry_after is not None:
                self.metrics.shed()
                return 429, {"Retry-After": str(retry_after)}, {
                    "error": "shed: predicted wait exceeds the p99 deadline",
                    "reason": "admission"}
        self.budget.deposit()
        exclude: set = set()
        for attempt in range(DISPATCH_ATTEMPTS):
            replica = self._pick(exclude)
            if replica is None:
                break
            if attempt == 0 and self.hedge_after_ms > 0:
                outcome = self._attempt_hedged(replica, body, content_type,
                                               exclude)
            else:
                outcome = self._attempt(replica, body, content_type)
            if outcome["kind"] == "response":
                return self._finish(outcome)
            exclude.add(replica.name)
            self._event("dispatch_retry", replica=replica.name,
                        attempt=attempt, detail=outcome["detail"])
            if attempt + 1 < DISPATCH_ATTEMPTS:
                if not self.budget.withdraw():
                    # budget dry: fail FAST instead of amplifying load on a
                    # dying fleet — the anti-retry-storm contract
                    self._event("retry_budget", event="exhausted",
                                replica=replica.name)
                    self.metrics.error()
                    return 503, {"Retry-After": "1"}, {
                        "error": "retry budget exhausted",
                        "reason": "retry_budget_exhausted"}
                self.metrics.retry()
        self.metrics.error()
        return 503, {"Retry-After": "1"}, {
            "error": "dispatch failed on all replicas",
            "reason": "dispatch_failed"}

    def _breaker(self, name: str) -> CircuitBreaker:
        with self._breaker_lock:
            br = self._breakers.get(name)
            if br is None:
                br = CircuitBreaker(
                    name, fail_threshold=self.breaker_threshold,
                    cooldown_s=self.breaker_cooldown_s,
                    on_event=lambda p: self._event("breaker", **p))
                self._breakers[name] = br
            return br

    def _blocked_names(self) -> set:
        """Replicas whose breaker currently refuses dispatches. Closed
        breakers answer eligible() with one lock-guarded state read — the
        no-fault fast path adds no dispatch latency."""
        with self._breaker_lock:
            items = list(self._breakers.items())
        return {name for name, br in items if not br.eligible()}

    def _pick(self, exclude: set):
        """Least-loaded READY replica whose breaker admits a dispatch, with
        the breaker reservation (half-open single probe) taken."""
        skip = set(exclude)
        while True:
            replica = self.manager.acquire(exclude=skip | self._blocked_names())
            if replica is None:
                return None
            if self._breaker(replica.name).begin():
                return replica
            # lost a half-open probe race: hand the slot back uncharged
            self.manager.release(replica, counted=False)
            skip.add(replica.name)

    def _attempt(self, replica, body: bytes, content_type: str) -> dict:
        """One dispatch to one replica (breaker reservation already held).
        Returns {"kind": "response", ...} for anything the client should
        see (200/429/4xx) or {"kind": "failed", "detail": ...} when the
        attempt should be retried elsewhere. Per-attempt accounting
        (release, breaker, admission EWMA) happens here; per-REQUEST
        counters happen once in _finish() so hedges never double-count."""
        breaker = self._breaker(replica.name)
        t0 = time.monotonic()
        try:
            faults.fire("router_dispatch")
            req = urllib.request.Request(
                replica.url + "/predict", data=body,
                headers={"Content-Type": content_type or
                         "application/octet-stream"})
            with urllib.request.urlopen(
                    req, timeout=self.request_timeout_s) as resp:
                out = resp.read()
            latency = time.monotonic() - t0
            self.manager.release(replica, latency_s=latency, ok=True)
            breaker.record_success()
            if self.admission is not None:
                self.admission.observe(latency)
            return {"kind": "response", "status": 200, "headers": {},
                    "payload": out, "latency": latency,
                    "replica": replica.name}
        except urllib.error.HTTPError as e:
            payload = self._json_body(e)
            if e.code == 503 and payload.get("reason") == "queue_full":
                # replica backpressure -> fleet admission shed: clients
                # see one uniform overload signal (429 + Retry-After).
                # The replica answered, so the breaker counts a success.
                self.manager.release(replica, ok=False)
                breaker.record_success()
                retry_hdr = e.headers.get("Retry-After", "1") \
                    if e.headers else "1"
                return {"kind": "response", "status": 429,
                        "headers": {"Retry-After": retry_hdr},
                        "payload": {"error": "shed: replica queue full",
                                    "reason": "replica_queue_full"},
                        "shed": True, "replica": replica.name}
            if 400 <= e.code < 500:
                # the client's fault (bad image, bad topk): pass the
                # replica's verdict through verbatim, never retry
                self.manager.release(replica, ok=False)
                breaker.record_success()
                return {"kind": "response", "status": e.code, "headers": {},
                        "payload": payload or {
                            "error": f"replica answered {e.code}"},
                        "client_error": True, "replica": replica.name}
            detail = f"HTTP {e.code}"
        except Exception as e:  # noqa: BLE001 — refused/timeout/reset
            detail = f"{type(e).__name__}: {e}"
        self.manager.release(replica, ok=False)
        breaker.record_failure()
        return {"kind": "failed", "detail": detail, "replica": replica.name}

    def _hedge_delay_s(self) -> float:
        """Hedge trigger: the rolling p99, floored at --hedge_after_ms (the
        floor keeps a cold window from hedging every request)."""
        p99 = self.metrics.p99()
        return max(p99 or 0.0, self.hedge_after_ms / 1000.0)

    def _attempt_hedged(self, primary, body: bytes, content_type: str,
                        exclude: set) -> dict:
        """First attempt with a hedge: if `primary` has not answered within
        the hedge delay, fire the same request at a second replica (budget
        permitting); first response wins, the loser is ignored — its
        worker thread still runs _attempt's release/breaker accounting."""
        results: queue_mod.Queue = queue_mod.Queue()

        def run(replica, is_hedge: bool) -> None:
            out = self._attempt(replica, body, content_type)
            out["hedge"] = is_hedge
            results.put(out)

        threading.Thread(  # vtx: ignore[VTX205] fire-and-forget: loser self-accounts in _attempt, result abandoned
            target=run, args=(primary, False), daemon=True,
            name="vitax-router-hedge-primary").start()
        launched = 1
        got: list = []
        try:
            got.append(results.get(timeout=self._hedge_delay_s()))
        except queue_mod.Empty:
            # primary is slow past the threshold: hedge on another replica,
            # bounded by the same retry budget as plain retries
            if self.budget.withdraw():
                hedge_replica = self._pick(set(exclude) | {primary.name})
                if hedge_replica is not None:
                    self.metrics.hedge()
                    self._event("hedge", event="fired", primary=primary.name,
                                replica=hedge_replica.name)
                    threading.Thread(  # vtx: ignore[VTX205] fire-and-forget: see the primary-attempt thread above
                        target=run, args=(hedge_replica, True), daemon=True,
                        name="vitax-router-hedge-secondary").start()
                    launched = 2
        # first RESPONSE wins; a failed attempt keeps waiting on the other
        deadline = time.monotonic() + self.request_timeout_s + 1.0
        while (len(got) < launched
               and not any(o["kind"] == "response" for o in got)):
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                got.append(results.get(timeout=remaining))
            except queue_mod.Empty:
                break
        winner = next((o for o in got if o["kind"] == "response"), None)
        if winner is not None:
            if winner.get("hedge"):
                self.metrics.hedge_win()
                self._event("hedge", event="win", replica=winner["replica"])
            return winner
        for o in got:
            exclude.add(o["replica"])
        details = "; ".join(o["detail"] for o in got)
        return {"kind": "failed", "replica": primary.name,
                "detail": details or "hedged attempts timed out"}

    def _finish(self, outcome: dict) -> Tuple[int, dict, object]:
        """Per-request bookkeeping, exactly once per client response (the
        losing side of a hedge never reaches here)."""
        status = outcome["status"]
        if status == 200:
            self.metrics.observe(outcome["latency"])
        elif outcome.get("shed"):
            self.metrics.shed()
            if self.admission is not None:
                self.admission.record_shed(reason="replica_queue_full",
                                           replica=outcome["replica"])
        else:
            self.metrics.error()
        return status, outcome["headers"], outcome["payload"]

    @staticmethod
    def _json_body(e: urllib.error.HTTPError) -> dict:
        try:
            payload = json.loads(e.read().decode("utf-8"))
            return payload if isinstance(payload, dict) else {}
        except Exception:  # noqa: BLE001 # vtx: ignore[VTX106] non-JSON error body is expected from dead proxies
            return {}

    # -- observability -----------------------------------------------------------

    def healthz(self) -> dict:
        replicas = self.manager.snapshot()
        return {
            "status": "ok",
            "ready": self.manager.ready_count() > 0,
            "replicas": {name: snap["state"]
                         for name, snap in replicas.items()},
        }

    def fleet_metrics(self) -> dict:
        replicas = self.manager.snapshot()
        # fold each ready replica's own /metrics in (fail-soft: a replica
        # dying mid-scrape must not fail the fleet scrape)
        for r in self.manager.ready_replicas():
            try:
                replicas[r.name]["server"] = self.manager._http_get(
                    r.url + "/metrics", self.manager.health_timeout_s)
            except Exception:  # noqa: BLE001 # vtx: ignore[VTX106] scrape is best-effort by contract
                pass
        snap = self.metrics.snapshot()
        snap["request_timeout_s"] = self.request_timeout_s
        snap["fleet"] = {
            "size": len(replicas),
            "ready": self.manager.ready_count(),
            "in_flight": self.manager.total_in_flight(),
            "replica_restarts": self.manager.restart_total,
            # brownout visibility: replicas advertising degraded: true in
            # their last /healthz (serving, but shedding optional work)
            "degraded": self.manager.degraded_count(),
            "degraded_seconds": self.manager.degraded_seconds(),
        }
        # weight-footprint aggregation (vitax/serve/quant.py): summed
        # device-resident param bytes across scraped replicas and the set of
        # weight dtypes in play (mixed during a quantized rollout). Only
        # present when at least one replica reported them — older replicas
        # without the keys degrade the scrape, not the schema.
        reporting = [r["server"] for r in replicas.values()
                     if "server" in r and "param_bytes" in r["server"]]
        if reporting:
            snap["fleet"]["param_bytes"] = sum(
                int(s["param_bytes"]) for s in reporting)
            snap["fleet"]["weights_dtypes"] = sorted(
                {str(s.get("weights_dtype", "")) for s in reporting})
            # tier-2 quant mode sets: mixed values flag a partial rollout
            # of act-quant / fused-dequant across the fleet
            snap["fleet"]["act_quants"] = sorted(
                {str(s.get("act_quant", "off")) for s in reporting})
            snap["fleet"]["fused_dequants"] = sorted(
                {str(bool(s.get("fused_dequant", False)))
                 for s in reporting})
        snap["replicas"] = replicas
        with self._breaker_lock:
            breakers = list(self._breakers.items())
        snap["breakers"] = {name: br.snapshot() for name, br in breakers}
        snap["breaker_opens"] = sum(
            br.opens_total + br.reopens_total for _, br in breakers)
        snap["retry_budget"] = self.budget.snapshot()
        if self.admission is not None:
            snap["admission"] = self.admission.snapshot()
        return snap

    def _event(self, kind: str, **payload) -> None:
        if self.recorder is not None:
            try:
                self.recorder.event(kind, **payload)
            except Exception:  # noqa: BLE001 # vtx: ignore[VTX106] telemetry must not kill dispatch
                pass


def _make_handler(router: Router):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # noqa: A003
            pass

        def _reply(self, code: int, payload, headers=None) -> None:
            body = (payload if isinstance(payload, bytes)
                    else json.dumps(payload).encode("utf-8"))
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for key, value in (headers or {}).items():
                self.send_header(key, value)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
            if self.path == "/healthz":
                self._reply(200, router.healthz())
            elif self.path == "/metrics":
                self._reply(200, router.fleet_metrics())
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})

        def do_POST(self):  # noqa: N802
            if self.path != "/predict":
                self._reply(404, {"error": f"unknown path {self.path}"})
                return
            length = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(length)
            code, headers, payload = router.dispatch(
                body, self.headers.get("Content-Type", ""))
            self._reply(code, payload, headers=headers)

    return Handler


def start_router(router: Router, port: int):
    """Bind the fleet front door (background thread). Returns the httpd;
    httpd.server_address[1] is the bound port (0 = ephemeral, tests)."""
    httpd = ThreadingHTTPServer(("0.0.0.0", port), _make_handler(router))
    httpd.daemon_threads = True
    thread = threading.Thread(  # vtx: ignore[VTX205] stop_router's httpd.shutdown() ends serve_forever
        target=httpd.serve_forever, daemon=True, name="vitax-fleet-router")
    thread.start()
    return httpd


def stop_router(httpd) -> None:
    httpd.shutdown()
    httpd.server_close()
