"""Least-loaded HTTP router: one front door over N serve replicas.

Speaks the exact single-engine contract (POST /predict, GET /healthz,
GET /metrics — clients cannot tell a fleet from one replica) and adds the
fleet behaviors on top:

- **least-loaded dispatch**: each /predict goes to the READY replica with
  the fewest in-flight requests (ties broken by EWMA latency), via
  ReplicaManager.acquire()/release();
- **one retry**: a dispatch failure (connection refused, replica 5xx,
  socket timeout) is retried once on a DIFFERENT replica — /predict is
  idempotent, so the retry is safe and hides single-replica deaths from
  clients;
- **admission control**: before dispatch, the AdmissionController predicts
  this request's queue delay; over-deadline arrivals get 429 +
  Retry-After (see admission.py). A replica's own queue-full 503 is
  mapped to the same 429 shed — backpressure composes up the stack;
- **fleet metrics**: GET /metrics aggregates router-side p50/p95/p99 and
  per-replica rotation/load state, folding in each ready replica's own
  /metrics, so one scrape shows the whole fleet.

Stdlib-only and jax-free: the router runs on a box with no accelerator.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple

from vitax.serve.fleet.admission import AdmissionController
from vitax.serve.fleet.replica import ReplicaManager

DISPATCH_ATTEMPTS = 2  # first pick + one retry on a different replica


def _percentile(sorted_vals, q: float) -> Optional[float]:
    if not sorted_vals:
        return None
    pos = (len(sorted_vals) - 1) * q
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return float(sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac)


class RouterMetrics:
    """Thread-safe router-side counters behind the fleet GET /metrics."""

    def __init__(self, window: int = 4096):
        self._lock = threading.Lock()
        self.started = time.time()
        self.requests_total = 0
        self.errors_total = 0
        self.shed_total = 0
        self.retries_total = 0
        self._latency = deque(maxlen=window)
        self._times = deque(maxlen=window)

    def observe(self, latency_s: float) -> None:
        with self._lock:
            self.requests_total += 1
            self._latency.append(latency_s)
            self._times.append(time.time())

    def error(self) -> None:
        with self._lock:
            self.errors_total += 1

    def shed(self) -> None:
        with self._lock:
            self.shed_total += 1

    def retry(self) -> None:
        with self._lock:
            self.retries_total += 1

    def snapshot(self) -> dict:
        with self._lock:
            lat = sorted(self._latency)
            times = list(self._times)
            total, errors = self.requests_total, self.errors_total
            shed, retries = self.shed_total, self.retries_total
        now = time.time()
        recent = [t for t in times if now - t <= 60.0]
        return {
            "requests_total": total,
            "errors_total": errors,
            "shed_total": shed,
            "retries_total": retries,
            "uptime_s": round(now - self.started, 3),
            "requests_per_sec": round(total / max(now - self.started, 1e-9), 3),
            "requests_per_sec_60s": round(len(recent) / 60.0, 3),
            "latency_s_p50": _percentile(lat, 0.50),
            "latency_s_p95": _percentile(lat, 0.95),
            "latency_s_p99": _percentile(lat, 0.99),
        }


class Router:
    """Dispatch policy + fleet observability; the HTTP shell is
    start_router(). Separated so tests drive dispatch() directly."""

    def __init__(self, manager: ReplicaManager,
                 admission: Optional[AdmissionController] = None,
                 recorder=None, request_timeout_s: float = 60.0):
        self.manager = manager
        self.admission = admission
        self.recorder = recorder
        self.request_timeout_s = request_timeout_s
        self.metrics = RouterMetrics()

    # -- dispatch --------------------------------------------------------------

    def dispatch(self, body: bytes,
                 content_type: str) -> Tuple[int, dict, object]:
        """Route one /predict. Returns (status, extra headers, payload):
        payload is raw bytes on 200 (the replica's JSON passed through
        verbatim) and a dict (to be JSON-encoded) otherwise."""
        ready = self.manager.ready_count()
        if ready == 0:
            self.metrics.error()
            return 503, {"Retry-After": "1"}, {
                "error": "no ready replicas", "reason": "no_ready_replicas"}
        if self.admission is not None:
            retry_after = self.admission.check(
                self.manager.total_in_flight(), ready)
            if retry_after is not None:
                self.metrics.shed()
                return 429, {"Retry-After": str(retry_after)}, {
                    "error": "shed: predicted wait exceeds the p99 deadline",
                    "reason": "admission"}
        exclude = set()
        for attempt in range(DISPATCH_ATTEMPTS):
            replica = self.manager.acquire(exclude=exclude)
            if replica is None:
                break
            t0 = time.monotonic()
            try:
                req = urllib.request.Request(
                    replica.url + "/predict", data=body,
                    headers={"Content-Type": content_type or
                             "application/octet-stream"})
                with urllib.request.urlopen(
                        req, timeout=self.request_timeout_s) as resp:
                    out = resp.read()
                latency = time.monotonic() - t0
                self.manager.release(replica, latency_s=latency, ok=True)
                if self.admission is not None:
                    self.admission.observe(latency)
                self.metrics.observe(latency)
                return 200, {}, out
            except urllib.error.HTTPError as e:
                payload = self._json_body(e)
                if e.code == 503 and payload.get("reason") == "queue_full":
                    # replica backpressure -> fleet admission shed: clients
                    # see one uniform overload signal (429 + Retry-After)
                    self.manager.release(replica, ok=False)
                    self.metrics.shed()
                    if self.admission is not None:
                        self.admission.record_shed(
                            reason="replica_queue_full", replica=replica.name)
                    retry_hdr = e.headers.get("Retry-After", "1") \
                        if e.headers else "1"
                    return 429, {"Retry-After": retry_hdr}, {
                        "error": "shed: replica queue full",
                        "reason": "replica_queue_full"}
                if 400 <= e.code < 500:
                    # the client's fault (bad image, bad topk): pass the
                    # replica's verdict through verbatim, never retry
                    self.manager.release(replica, ok=False)
                    self.metrics.error()
                    return e.code, {}, payload or {
                        "error": f"replica answered {e.code}"}
                self._dispatch_failed(replica, exclude, attempt,
                                      f"HTTP {e.code}")
            except Exception as e:  # noqa: BLE001 — refused/timeout/reset
                self._dispatch_failed(replica, exclude, attempt,
                                      f"{type(e).__name__}: {e}")
        self.metrics.error()
        return 503, {"Retry-After": "1"}, {
            "error": "dispatch failed on all replicas",
            "reason": "dispatch_failed"}

    def _dispatch_failed(self, replica, exclude: set, attempt: int,
                         detail: str) -> None:
        self.manager.release(replica, ok=False)
        exclude.add(replica.name)
        if attempt + 1 < DISPATCH_ATTEMPTS:
            self.metrics.retry()
        self._event("dispatch_retry", replica=replica.name, attempt=attempt,
                    detail=detail)

    @staticmethod
    def _json_body(e: urllib.error.HTTPError) -> dict:
        try:
            payload = json.loads(e.read().decode("utf-8"))
            return payload if isinstance(payload, dict) else {}
        except Exception:  # noqa: BLE001 # vtx: ignore[VTX106] non-JSON error body is expected from dead proxies
            return {}

    # -- observability -----------------------------------------------------------

    def healthz(self) -> dict:
        replicas = self.manager.snapshot()
        return {
            "status": "ok",
            "ready": self.manager.ready_count() > 0,
            "replicas": {name: snap["state"]
                         for name, snap in replicas.items()},
        }

    def fleet_metrics(self) -> dict:
        replicas = self.manager.snapshot()
        # fold each ready replica's own /metrics in (fail-soft: a replica
        # dying mid-scrape must not fail the fleet scrape)
        for r in self.manager.ready_replicas():
            try:
                replicas[r.name]["server"] = self.manager._http_get(
                    r.url + "/metrics", self.manager.health_timeout_s)
            except Exception:  # noqa: BLE001 # vtx: ignore[VTX106] scrape is best-effort by contract
                pass
        snap = self.metrics.snapshot()
        snap["request_timeout_s"] = self.request_timeout_s
        snap["fleet"] = {
            "size": len(replicas),
            "ready": self.manager.ready_count(),
            "in_flight": self.manager.total_in_flight(),
            "replica_restarts": self.manager.restart_total,
        }
        snap["replicas"] = replicas
        if self.admission is not None:
            snap["admission"] = self.admission.snapshot()
        return snap

    def _event(self, kind: str, **payload) -> None:
        if self.recorder is not None:
            try:
                self.recorder.event(kind, **payload)
            except Exception:  # noqa: BLE001 # vtx: ignore[VTX106] telemetry must not kill dispatch
                pass


def _make_handler(router: Router):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # noqa: A003
            pass

        def _reply(self, code: int, payload, headers=None) -> None:
            body = (payload if isinstance(payload, bytes)
                    else json.dumps(payload).encode("utf-8"))
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for key, value in (headers or {}).items():
                self.send_header(key, value)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
            if self.path == "/healthz":
                self._reply(200, router.healthz())
            elif self.path == "/metrics":
                self._reply(200, router.fleet_metrics())
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})

        def do_POST(self):  # noqa: N802
            if self.path != "/predict":
                self._reply(404, {"error": f"unknown path {self.path}"})
                return
            length = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(length)
            code, headers, payload = router.dispatch(
                body, self.headers.get("Content-Type", ""))
            self._reply(code, payload, headers=headers)

    return Handler


def start_router(router: Router, port: int):
    """Bind the fleet front door (background thread). Returns the httpd;
    httpd.server_address[1] is the bound port (0 = ephemeral, tests)."""
    httpd = ThreadingHTTPServer(("0.0.0.0", port), _make_handler(router))
    httpd.daemon_threads = True
    thread = threading.Thread(  # vtx: ignore[VTX205] stop_router's httpd.shutdown() ends serve_forever
        target=httpd.serve_forever, daemon=True, name="vitax-fleet-router")
    thread.start()
    return httpd


def stop_router(httpd) -> None:
    httpd.shutdown()
    httpd.server_close()
