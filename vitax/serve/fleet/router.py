"""Least-loaded HTTP router: one front door over N serve replicas.

Speaks the exact single-engine contract (POST /predict, GET /healthz,
GET /metrics — clients cannot tell a fleet from one replica) and adds the
fleet behaviors on top:

- **least-loaded dispatch**: each /predict goes to the READY replica with
  the fewest in-flight requests (ties broken by EWMA latency), via
  ReplicaManager.acquire()/release();
- **one retry**: a dispatch failure (connection refused, replica 5xx,
  socket timeout) is retried once on a DIFFERENT replica — /predict is
  idempotent, so the retry is safe and hides single-replica deaths from
  clients;
- **circuit breakers** (vitax/serve/fleet/breaker.py): per-replica
  closed -> open after `breaker_threshold` consecutive dispatch failures,
  half-open single-probe re-admission after `breaker_cooldown_s`. Distinct
  from the manager's health ejection, which only sees /healthz — the
  breaker sees actual dispatches, so a replica that answers health probes
  but fails every request is still contained;
- **retry budget**: retries and hedges spend a token bucket refilled at
  `retry_budget_ratio` per request, so a dying fleet degrades to fast
  503s (reason "retry_budget_exhausted") instead of a retry storm;
- **hedged requests** (opt-in, `--hedge_after_ms`): when the first attempt
  exceeds max(hedge_after_ms, rolling p99), a second attempt fires on a
  DIFFERENT replica; first response wins, the loser is ignored (its
  thread still releases its in-flight slot). Hedges draw from the same
  retry budget;
- **admission control**: before dispatch, the AdmissionController predicts
  this request's queue delay; over-deadline arrivals get 429 +
  Retry-After (see admission.py). A replica's own queue-full 503 is
  mapped to the same 429 shed — backpressure composes up the stack;
- **fleet metrics**: GET /metrics aggregates router-side p50/p95/p99 and
  per-replica rotation/load state, folding in each ready replica's own
  /metrics (including its brownout `degraded` flag), breaker states, and
  retry-budget counters, so one scrape shows the whole fleet.

Chaos: the `router_dispatch` fault site (vitax/faults.py) fires once per
dispatch attempt, so the retry/breaker/budget paths are drillable without
a sick replica.

Stdlib-only and jax-free: the router runs on a box with no accelerator.
"""

from __future__ import annotations

import base64
import json
import queue as queue_mod
import threading
import time
import urllib.error
import urllib.request
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from vitax import faults
from vitax.serve.fleet.admission import AdmissionController
from vitax.serve.fleet.breaker import (CircuitBreaker, RetryBudget,
                                       DEFAULT_BUDGET_RATIO,
                                       DEFAULT_COOLDOWN_S,
                                       DEFAULT_FAIL_THRESHOLD)
from vitax.serve.fleet.cache import PredictionCache
from vitax.serve.fleet.replica import ReplicaManager

DISPATCH_ATTEMPTS = 2  # first pick + one retry on a different replica


def _percentile(sorted_vals, q: float) -> Optional[float]:
    if not sorted_vals:
        return None
    pos = (len(sorted_vals) - 1) * q
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return float(sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac)


class RouterMetrics:
    """Thread-safe router-side counters behind the fleet GET /metrics."""

    def __init__(self, window: int = 4096):
        self._lock = threading.Lock()
        self.started = time.time()
        self.requests_total = 0
        self.errors_total = 0
        self.shed_total = 0
        self.retries_total = 0
        self.hedges_total = 0
        self.hedge_wins_total = 0
        self.cache_hits_total = 0
        self._latency = deque(maxlen=window)
        self._times = deque(maxlen=window)

    def observe(self, latency_s: float) -> None:
        with self._lock:
            self.requests_total += 1
            self._latency.append(latency_s)
            self._times.append(time.time())

    def error(self) -> None:
        with self._lock:
            self.errors_total += 1

    def shed(self) -> None:
        with self._lock:
            self.shed_total += 1

    def retry(self) -> None:
        with self._lock:
            self.retries_total += 1

    def hedge(self) -> None:
        with self._lock:
            self.hedges_total += 1

    def hedge_win(self) -> None:
        with self._lock:
            self.hedge_wins_total += 1

    def cache_hit(self) -> None:
        """A /predict answered from the prediction cache: no dispatch, no
        latency sample — counted apart so requests_per_sec stays a measure
        of replica work."""
        with self._lock:
            self.cache_hits_total += 1

    def p99(self) -> Optional[float]:
        """Rolling client-latency p99 — the hedge trigger threshold."""
        with self._lock:
            lat = sorted(self._latency)
        return _percentile(lat, 0.99)

    def snapshot(self) -> dict:
        with self._lock:
            lat = sorted(self._latency)
            times = list(self._times)
            total, errors = self.requests_total, self.errors_total
            shed, retries = self.shed_total, self.retries_total
            hedges, hedge_wins = self.hedges_total, self.hedge_wins_total
            cache_hits = self.cache_hits_total
        now = time.time()
        recent = [t for t in times if now - t <= 60.0]
        return {
            "requests_total": total,
            "errors_total": errors,
            "shed_total": shed,
            "retries_total": retries,
            "hedges_total": hedges,
            "hedge_wins_total": hedge_wins,
            "cache_hits_total": cache_hits,
            "uptime_s": round(now - self.started, 3),
            "requests_per_sec": round(total / max(now - self.started, 1e-9), 3),
            "requests_per_sec_60s": round(len(recent) / 60.0, 3),
            "latency_s_p50": _percentile(lat, 0.50),
            "latency_s_p95": _percentile(lat, 0.95),
            "latency_s_p99": _percentile(lat, 0.99),
        }


class Router:
    """Dispatch policy + fleet observability; the HTTP shell is
    start_router(). Separated so tests drive dispatch() directly."""

    def __init__(self, manager: ReplicaManager,
                 admission: Optional[AdmissionController] = None,
                 recorder=None, request_timeout_s: float = 60.0,
                 breaker_threshold: int = DEFAULT_FAIL_THRESHOLD,
                 breaker_cooldown_s: float = DEFAULT_COOLDOWN_S,
                 retry_budget_ratio: float = DEFAULT_BUDGET_RATIO,
                 hedge_after_ms: float = 0.0,
                 cache: Optional[PredictionCache] = None,
                 autoscaler=None,
                 batch_window_ms: float = 0.0, batch_max: int = 8):
        assert hedge_after_ms >= 0, hedge_after_ms
        assert batch_window_ms >= 0, batch_window_ms
        self.manager = manager
        self.admission = admission
        self.recorder = recorder
        self.request_timeout_s = request_timeout_s
        self.breaker_threshold = breaker_threshold
        self.breaker_cooldown_s = breaker_cooldown_s
        self.hedge_after_ms = hedge_after_ms
        self.cache = cache
        self.autoscaler = autoscaler  # observability only; it owns itself
        # arbiter plane (POST /fleet/adopt, /fleet/release): the fleet CLI
        # installs these so a chip arbiter can hand the router a replica it
        # provisioned on a borrowed host — and take it back with a drain.
        # None -> the routes answer 501 (router not arbiter-enabled).
        self.fleet_adopt_fn = None      # (url: str) -> dict
        self.fleet_release_fn = None    # (url: str) -> dict
        self.budget = RetryBudget(ratio=retry_budget_ratio)
        self.metrics = RouterMetrics()
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._breaker_lock = threading.Lock()
        # cross-replica continuous batching (opt-in, --batch_window_ms):
        # the composer groups concurrent /predict bodies and dispatches one
        # /predict_batch per group instead of trickling singles into every
        # replica's own max_batch_wait_ms window
        self._composer = (BatchComposer(self, batch_window_ms, batch_max)
                          if batch_window_ms > 0 else None)

    # -- dispatch --------------------------------------------------------------

    def dispatch(self, body: bytes,
                 content_type: str) -> Tuple[int, dict, object]:
        """Route one /predict. Returns (status, extra headers, payload):
        payload is raw bytes on 200 (the replica's JSON passed through
        verbatim) and a dict (to be JSON-encoded) otherwise.

        Order matters: the cache is consulted FIRST — a hit is exact
        (deterministic AOT-pinned classification) and free, so it bypasses
        readiness, admission, and dispatch entirely; identical bytes never
        touch a TPU twice, and cached answers keep flowing even while the
        fleet has zero ready replicas."""
        topk = self._request_topk(body, content_type)
        if self.cache is not None:
            hit = self.cache.get(body, topk)
            if hit is not None:
                self.metrics.cache_hit()
                return 200, {"X-Vitax-Cache": "hit"}, hit
        ready = self.manager.ready_count()
        if ready == 0:
            self.metrics.error()
            return 503, {"Retry-After": "1"}, {
                "error": "no ready replicas", "reason": "no_ready_replicas"}
        if self.admission is not None:
            retry_after = self.admission.check(
                self.manager.total_in_flight(), ready,
                warming_replicas=self.manager.warming_count())
            if retry_after is not None:
                self.metrics.shed()
                return 429, {"Retry-After": str(retry_after)}, {
                    "error": "shed: predicted wait exceeds the p99 deadline",
                    "reason": "admission"}
        self.budget.deposit()
        if self._composer is not None:
            status, headers, payload = self._composer.submit(
                body, content_type)
        else:
            status, headers, payload = self._dispatch_direct(
                body, content_type)
        if (status == 200 and self.cache is not None
                and isinstance(payload, bytes)
                and self.manager.degraded_count() == 0):
            # never cache a browned-out answer: degraded replicas clamp
            # topk to 1, and replaying that after recovery would be wrong
            self.cache.put(body, topk, payload)
        return status, headers, payload

    @staticmethod
    def _request_topk(body: bytes, content_type: str):
        """The topk component of the cache key. JSON bodies may carry a
        per-request topk; raw image bodies get the replica default. (The
        body hash already separates the two — this keeps the key honest
        and the `distinct topk never alias` property self-evident.)"""
        if content_type and "application/json" in content_type:
            try:
                topk = json.loads(body.decode("utf-8")).get("topk")
                if topk is not None:
                    return int(topk)
            except Exception:  # noqa: BLE001 # vtx: ignore[VTX106] malformed body keys as default; the replica 400s it
                pass
        return "default"

    def _dispatch_direct(self, body: bytes,
                         content_type: str) -> Tuple[int, dict, object]:
        """The per-request attempt loop: least-loaded pick, one retry on a
        different replica, hedging, breaker + retry-budget containment."""
        exclude: set = set()
        for attempt in range(DISPATCH_ATTEMPTS):
            replica = self._pick(exclude)
            if replica is None:
                break
            if attempt == 0 and self.hedge_after_ms > 0:
                outcome = self._attempt_hedged(replica, body, content_type,
                                               exclude)
            else:
                outcome = self._attempt(replica, body, content_type)
            if outcome["kind"] == "response":
                return self._finish(outcome)
            exclude.add(replica.name)
            self._event("dispatch_retry", replica=replica.name,
                        attempt=attempt, detail=outcome["detail"])
            if attempt + 1 < DISPATCH_ATTEMPTS:
                if not self.budget.withdraw():
                    # budget dry: fail FAST instead of amplifying load on a
                    # dying fleet — the anti-retry-storm contract
                    self._event("retry_budget", event="exhausted",
                                replica=replica.name)
                    self.metrics.error()
                    return 503, {"Retry-After": "1"}, {
                        "error": "retry budget exhausted",
                        "reason": "retry_budget_exhausted"}
                self.metrics.retry()
        self.metrics.error()
        return 503, {"Retry-After": "1"}, {
            "error": "dispatch failed on all replicas",
            "reason": "dispatch_failed"}

    def _breaker(self, name: str) -> CircuitBreaker:
        with self._breaker_lock:
            br = self._breakers.get(name)
            if br is None:
                br = CircuitBreaker(
                    name, fail_threshold=self.breaker_threshold,
                    cooldown_s=self.breaker_cooldown_s,
                    on_event=lambda p: self._event("breaker", **p))
                self._breakers[name] = br
            return br

    def _blocked_names(self) -> set:
        """Replicas whose breaker currently refuses dispatches. Closed
        breakers answer eligible() with one lock-guarded state read — the
        no-fault fast path adds no dispatch latency."""
        with self._breaker_lock:
            items = list(self._breakers.items())
        return {name for name, br in items if not br.eligible()}

    def _pick(self, exclude: set):
        """Least-loaded READY replica whose breaker admits a dispatch, with
        the breaker reservation (half-open single probe) taken."""
        skip = set(exclude)
        while True:
            replica = self.manager.acquire(exclude=skip | self._blocked_names())
            if replica is None:
                return None
            if self._breaker(replica.name).begin():
                return replica
            # lost a half-open probe race: hand the slot back uncharged
            self.manager.release(replica, counted=False)
            skip.add(replica.name)

    def _attempt(self, replica, body: bytes, content_type: str) -> dict:
        """One dispatch to one replica (breaker reservation already held).
        Returns {"kind": "response", ...} for anything the client should
        see (200/429/4xx) or {"kind": "failed", "detail": ...} when the
        attempt should be retried elsewhere. Per-attempt accounting
        (release, breaker, admission EWMA) happens here; per-REQUEST
        counters happen once in _finish() so hedges never double-count."""
        breaker = self._breaker(replica.name)
        t0 = time.monotonic()
        try:
            faults.fire("router_dispatch")
            req = urllib.request.Request(
                replica.url + "/predict", data=body,
                headers={"Content-Type": content_type or
                         "application/octet-stream"})
            with urllib.request.urlopen(
                    req, timeout=self.request_timeout_s) as resp:
                out = resp.read()
            latency = time.monotonic() - t0
            self.manager.release(replica, latency_s=latency, ok=True)
            breaker.record_success()
            if self.admission is not None:
                self.admission.observe(latency)
            return {"kind": "response", "status": 200, "headers": {},
                    "payload": out, "latency": latency,
                    "replica": replica.name}
        except urllib.error.HTTPError as e:
            payload = self._json_body(e)
            if e.code == 503 and payload.get("reason") == "queue_full":
                # replica backpressure -> fleet admission shed: clients
                # see one uniform overload signal (429 + Retry-After).
                # The replica answered, so the breaker counts a success.
                self.manager.release(replica, ok=False)
                breaker.record_success()
                retry_hdr = e.headers.get("Retry-After", "1") \
                    if e.headers else "1"
                return {"kind": "response", "status": 429,
                        "headers": {"Retry-After": retry_hdr},
                        "payload": {"error": "shed: replica queue full",
                                    "reason": "replica_queue_full"},
                        "shed": True, "replica": replica.name}
            if 400 <= e.code < 500:
                # the client's fault (bad image, bad topk): pass the
                # replica's verdict through verbatim, never retry
                self.manager.release(replica, ok=False)
                breaker.record_success()
                return {"kind": "response", "status": e.code, "headers": {},
                        "payload": payload or {
                            "error": f"replica answered {e.code}"},
                        "client_error": True, "replica": replica.name}
            detail = f"HTTP {e.code}"
        except Exception as e:  # noqa: BLE001 — refused/timeout/reset
            detail = f"{type(e).__name__}: {e}"
        self.manager.release(replica, ok=False)
        breaker.record_failure()
        return {"kind": "failed", "detail": detail, "replica": replica.name}

    def _hedge_delay_s(self) -> float:
        """Hedge trigger: the rolling p99, floored at --hedge_after_ms (the
        floor keeps a cold window from hedging every request)."""
        p99 = self.metrics.p99()
        return max(p99 or 0.0, self.hedge_after_ms / 1000.0)

    def _attempt_hedged(self, primary, body: bytes, content_type: str,
                        exclude: set) -> dict:
        """First attempt with a hedge: if `primary` has not answered within
        the hedge delay, fire the same request at a second replica (budget
        permitting); first response wins, the loser is ignored — its
        worker thread still runs _attempt's release/breaker accounting."""
        results: queue_mod.Queue = queue_mod.Queue()

        def run(replica, is_hedge: bool) -> None:
            out = self._attempt(replica, body, content_type)
            out["hedge"] = is_hedge
            results.put(out)

        threading.Thread(  # vtx: ignore[VTX205] fire-and-forget: loser self-accounts in _attempt, result abandoned
            target=run, args=(primary, False), daemon=True,
            name="vitax-router-hedge-primary").start()
        launched = 1
        got: list = []
        try:
            got.append(results.get(timeout=self._hedge_delay_s()))
        except queue_mod.Empty:
            # primary is slow past the threshold: hedge on another replica,
            # bounded by the same retry budget as plain retries
            if self.budget.withdraw():
                hedge_replica = self._pick(set(exclude) | {primary.name})
                if hedge_replica is not None:
                    self.metrics.hedge()
                    self._event("hedge", event="fired", primary=primary.name,
                                replica=hedge_replica.name)
                    threading.Thread(  # vtx: ignore[VTX205] fire-and-forget: see the primary-attempt thread above
                        target=run, args=(hedge_replica, True), daemon=True,
                        name="vitax-router-hedge-secondary").start()
                    launched = 2
        # first RESPONSE wins; a failed attempt keeps waiting on the other
        deadline = time.monotonic() + self.request_timeout_s + 1.0
        while (len(got) < launched
               and not any(o["kind"] == "response" for o in got)):
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                got.append(results.get(timeout=remaining))
            except queue_mod.Empty:
                break
        winner = next((o for o in got if o["kind"] == "response"), None)
        if winner is not None:
            if winner.get("hedge"):
                self.metrics.hedge_win()
                self._event("hedge", event="win", replica=winner["replica"])
            return winner
        for o in got:
            exclude.add(o["replica"])
        details = "; ".join(o["detail"] for o in got)
        return {"kind": "failed", "replica": primary.name,
                "detail": details or "hedged attempts timed out"}

    def _finish(self, outcome: dict) -> Tuple[int, dict, object]:
        """Per-request bookkeeping, exactly once per client response (the
        losing side of a hedge never reaches here)."""
        status = outcome["status"]
        if status == 200:
            self.metrics.observe(outcome["latency"])
        elif outcome.get("shed"):
            self.metrics.shed()
            if self.admission is not None:
                self.admission.record_shed(reason="replica_queue_full",
                                           replica=outcome["replica"])
        else:
            self.metrics.error()
        return status, outcome["headers"], outcome["payload"]

    def _attempt_batch(self, items: List[dict]):
        """One /predict_batch dispatch carrying a composed group to one
        replica. Returns a list of per-item (status, headers, payload)
        tuples aligned with `items`, the sentinel string "unsupported"
        when the replica has no /predict_batch (404/501 — mixed-version
        fleet), or None on a dispatch failure (the composer falls back to
        per-item direct dispatch either way)."""
        replica = self._pick(set())
        if replica is None:
            return None
        breaker = self._breaker(replica.name)
        wire = json.dumps({
            "items": [base64.b64encode(it["body"]).decode("ascii")
                      for it in items],
            "content_types": [it["content_type"] or
                              "application/octet-stream" for it in items],
        }).encode("utf-8")
        t0 = time.monotonic()
        try:
            faults.fire("router_dispatch")
            req = urllib.request.Request(
                replica.url + "/predict_batch", data=wire,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(
                    req, timeout=self.request_timeout_s) as resp:
                out = json.load(resp)
            latency = time.monotonic() - t0
            results = out.get("results")
            if (not isinstance(results, list)
                    or len(results) != len(items)):
                # answered but malformed: count the dispatch against the
                # breaker and let the composer re-drive items directly
                self.manager.release(replica, ok=False)
                breaker.record_failure()
                return None
            self.manager.release(replica, latency_s=latency, ok=True)
            breaker.record_success()
            if self.admission is not None:
                # one EWMA sample per batch, not per item: the predictor
                # models dispatch round-trips, and a group is one trip
                self.admission.observe(latency)
            outcomes = []
            for res in results:
                status = int(res.get("status", 500))
                payload = str(res.get("body", "")).encode("utf-8")
                if status == 200:
                    self.metrics.observe(latency)
                    outcomes.append((200, {}, payload))
                elif status == 503 and res.get("reason") == "queue_full":
                    self.metrics.shed()
                    if self.admission is not None:
                        self.admission.record_shed(
                            reason="replica_queue_full",
                            replica=replica.name)
                    outcomes.append((429, {"Retry-After": "1"}, {
                        "error": "shed: replica queue full",
                        "reason": "replica_queue_full"}))
                else:
                    # client errors (bad image, bad topk) pass through
                    # verbatim, exactly like the single-dispatch path
                    self.metrics.error()
                    outcomes.append((status, {}, payload or {
                        "error": f"replica answered {status}"}))
            return outcomes
        except urllib.error.HTTPError as e:
            if e.code in (404, 501):
                # no /predict_batch on this replica: not a fault, just an
                # older binary — hand the slot back uncharged
                self.manager.release(replica, counted=False)
                breaker.record_success()
                return "unsupported"
            detail = f"HTTP {e.code}"
        except Exception as e:  # noqa: BLE001 — refused/timeout/reset
            detail = f"{type(e).__name__}: {e}"
        self.manager.release(replica, ok=False)
        breaker.record_failure()
        self._event("dispatch_retry", replica=replica.name,
                    attempt="batch", detail=detail)
        return None

    @staticmethod
    def _json_body(e: urllib.error.HTTPError) -> dict:
        try:
            payload = json.loads(e.read().decode("utf-8"))
            return payload if isinstance(payload, dict) else {}
        except Exception:  # noqa: BLE001 # vtx: ignore[VTX106] non-JSON error body is expected from dead proxies
            return {}

    # -- observability -----------------------------------------------------------

    def healthz(self) -> dict:
        replicas = self.manager.snapshot()
        return {
            "status": "ok",
            "ready": self.manager.ready_count() > 0,
            "replicas": {name: snap["state"]
                         for name, snap in replicas.items()},
        }

    def fleet_metrics(self) -> dict:
        replicas = self.manager.snapshot()
        # fold each ready replica's own /metrics in (fail-soft: a replica
        # dying mid-scrape must not fail the fleet scrape)
        for r in self.manager.ready_replicas():
            try:
                replicas[r.name]["server"] = self.manager._http_get(
                    r.url + "/metrics", self.manager.health_timeout_s)
            except Exception:  # noqa: BLE001 # vtx: ignore[VTX106] scrape is best-effort by contract
                pass
        snap = self.metrics.snapshot()
        snap["request_timeout_s"] = self.request_timeout_s
        snap["fleet"] = {
            "size": len(replicas),
            "ready": self.manager.ready_count(),
            # scale-out visibility: live replicas still inside warmup —
            # admission already counts them at warming_capacity_frac
            "warming": self.manager.warming_count(),
            "in_flight": self.manager.total_in_flight(),
            "replica_restarts": self.manager.restart_total,
            # brownout visibility: replicas advertising degraded: true in
            # their last /healthz (serving, but shedding optional work)
            "degraded": self.manager.degraded_count(),
            "degraded_seconds": self.manager.degraded_seconds(),
        }
        # weight-footprint aggregation (vitax/serve/quant.py): summed
        # device-resident param bytes across scraped replicas and the set of
        # weight dtypes in play (mixed during a quantized rollout). Only
        # present when at least one replica reported them — older replicas
        # without the keys degrade the scrape, not the schema.
        reporting = [r["server"] for r in replicas.values()
                     if "server" in r and "param_bytes" in r["server"]]
        if reporting:
            snap["fleet"]["param_bytes"] = sum(
                int(s["param_bytes"]) for s in reporting)
            snap["fleet"]["weights_dtypes"] = sorted(
                {str(s.get("weights_dtype", "")) for s in reporting})
            # tier-2 quant mode sets: mixed values flag a partial rollout
            # of act-quant / fused-dequant across the fleet
            snap["fleet"]["act_quants"] = sorted(
                {str(s.get("act_quant", "off")) for s in reporting})
            snap["fleet"]["fused_dequants"] = sorted(
                {str(bool(s.get("fused_dequant", False)))
                 for s in reporting})
        snap["replicas"] = replicas
        with self._breaker_lock:
            breakers = list(self._breakers.items())
        snap["breakers"] = {name: br.snapshot() for name, br in breakers}
        snap["breaker_opens"] = sum(
            br.opens_total + br.reopens_total for _, br in breakers)
        snap["retry_budget"] = self.budget.snapshot()
        if self.admission is not None:
            snap["admission"] = self.admission.snapshot()
        if self.cache is not None:
            snap["cache"] = self.cache.snapshot()
            snap["cache_hits"] = snap["cache"]["hits_total"]
            snap["cache_hit_rate"] = snap["cache"]["hit_rate"]
        if self.autoscaler is not None:
            snap["autoscale"] = self.autoscaler.snapshot()
            snap["scale_events"] = (snap["autoscale"]["scale_out_total"]
                                    + snap["autoscale"]["scale_in_total"])
        if self._composer is not None:
            snap["continuous_batching"] = self._composer.snapshot()
        return snap

    def close(self) -> None:
        """Stop router-owned background machinery (the batch composer);
        the manager and autoscaler have their own stop() lifecycles."""
        if self._composer is not None:
            self._composer.close()

    def _event(self, kind: str, **payload) -> None:
        if self.recorder is not None:
            try:
                self.recorder.event(kind, **payload)
            except Exception:  # noqa: BLE001 # vtx: ignore[VTX106] telemetry must not kill dispatch
                pass


class BatchComposer:
    """Cross-replica continuous batching, Orca-style at the fleet level.

    Without it, each replica's DynamicBatcher waits out its own
    --max_batch_wait_ms hoping for co-arrivals, but least-loaded routing
    SPREADS concurrent arrivals across replicas — so at moderate load
    every replica batcher times out at batch_size 1 and the TPU runs its
    AOT bucket at 1/max_batch occupancy. The composer inverts that:
    concurrent /predict bodies wait up to `window_ms` at the ROUTER, then
    one /predict_batch carries the whole group to ONE replica, whose
    batcher admits them together into a single bucket.

    Exactness: the replica answers each item with the byte-identical JSON
    body a lone /predict would have produced (same engine, same padded
    bucket semantics), so clients cannot tell composed from direct
    dispatch.

    Fallbacks: a replica without /predict_batch (404/501 — mixed-version
    fleet) disables composition permanently for this router; a dispatch
    failure re-drives just that group. Both paths settle every item via
    _dispatch_direct, so composition never costs availability.

    Threading: one worker groups under a Condition (wait-in-while);
    handler threads block on a per-item Event. close() joins the worker
    and 503s anything still parked.
    """

    def __init__(self, router: Router, window_ms: float, batch_max: int):
        assert window_ms > 0, window_ms
        assert batch_max >= 1, batch_max
        self.router = router
        self.window_s = window_ms / 1000.0
        self.batch_max = batch_max
        self._cond = threading.Condition()
        # guarded by _cond:
        self._pending: List[dict] = []
        self._closed = False
        self._disabled = False
        self.batches_total = 0
        self.items_total = 0
        self.fallback_items_total = 0
        self._fills = deque(maxlen=4096)
        self._worker = threading.Thread(
            target=self._run, daemon=True, name="vitax-batch-composer")
        self._worker.start()

    def submit(self, body: bytes,
               content_type: str) -> Tuple[int, dict, object]:
        """Handler-thread entry: park this request for grouping and block
        until its group's dispatch settles it."""
        item = {"body": body, "content_type": content_type,
                "done": threading.Event(), "result": None}
        with self._cond:
            bypass = self._disabled or self._closed
            if not bypass:
                self._pending.append(item)
                self._cond.notify()
        if bypass:
            # composition is off (mixed-version fleet) or shutting down:
            # same answer, just without the grouping wait
            return self.router._dispatch_direct(body, content_type)
        timeout = self.window_s + self.router.request_timeout_s + 5.0
        if not item["done"].wait(timeout=timeout):
            self.router.metrics.error()
            return 503, {"Retry-After": "1"}, {
                "error": "batched dispatch timed out",
                "reason": "dispatch_failed"}
        return item["result"]

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._pending and not self._closed:
                    self._cond.wait()
                if self._closed:
                    return  # close() settles whatever is still parked
                # the window opens at the FIRST arrival: collect
                # co-arrivals until it closes or the group is full
                deadline = time.monotonic() + self.window_s
                while len(self._pending) < self.batch_max \
                        and not self._closed:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(timeout=remaining)
                group = self._pending[:self.batch_max]
                del self._pending[:len(group)]
                self.batches_total += 1
                self.items_total += len(group)
                self._fills.append(len(group) / self.batch_max)
            self._dispatch_group(group)  # outside the lock: it blocks

    def _dispatch_group(self, group: List[dict]) -> None:
        with self._cond:
            disabled = self._disabled
        outcomes = None if disabled else self.router._attempt_batch(group)
        if outcomes == "unsupported":
            with self._cond:
                self._disabled = True
            self.router._event("continuous_batching", event="disabled",
                               detail="replica lacks /predict_batch")
            outcomes = None
        if outcomes is None:
            self._fallback(group)
            return
        for item, outcome in zip(group, outcomes):
            item["result"] = outcome
            item["done"].set()

    def _fallback(self, group: List[dict]) -> None:
        """Settle every item of a failed group via the direct per-request
        path (which has its own retry/breaker/budget containment)."""
        with self._cond:
            self.fallback_items_total += len(group)

        def run(item: dict) -> None:
            item["result"] = self.router._dispatch_direct(
                item["body"], item["content_type"])
            item["done"].set()

        threads = [threading.Thread(target=run, args=(it,), daemon=True,
                                    name="vitax-batch-fallback")
                   for it in group]
        for t in threads:
            t.start()
        for t in threads:
            # a straggler past this join still settles its own item, and
            # submit()'s wait timeout bounds the client either way
            t.join(timeout=self.router.request_timeout_s + 5.0)

    def snapshot(self) -> dict:
        with self._cond:
            fills = sorted(self._fills)
            return {
                "window_ms": round(self.window_s * 1000.0, 3),
                "batch_max": self.batch_max,
                "disabled": self._disabled,
                "batches_total": self.batches_total,
                "items_total": self.items_total,
                "fallback_items_total": self.fallback_items_total,
                "batch_fill_p50": _percentile(fills, 0.50),
                "batch_fill_p95": _percentile(fills, 0.95),
            }

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._worker.join(timeout=10.0)
        with self._cond:
            leftovers, self._pending = self._pending, []
        for item in leftovers:
            item["result"] = (503, {"Retry-After": "1"}, {
                "error": "router shutting down",
                "reason": "dispatch_failed"})
            item["done"].set()


def _make_handler(router: Router):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # noqa: A003
            pass

        def _reply(self, code: int, payload, headers=None) -> None:
            body = (payload if isinstance(payload, bytes)
                    else json.dumps(payload).encode("utf-8"))
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for key, value in (headers or {}).items():
                self.send_header(key, value)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
            if self.path == "/healthz":
                self._reply(200, router.healthz())
            elif self.path == "/metrics":
                self._reply(200, router.fleet_metrics())
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})

        def do_POST(self):  # noqa: N802
            if self.path in ("/fleet/adopt", "/fleet/release"):
                self._fleet_hook()
                return
            if self.path != "/predict":
                self._reply(404, {"error": f"unknown path {self.path}"})
                return
            length = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(length)
            code, headers, payload = router.dispatch(
                body, self.headers.get("Content-Type", ""))
            self._reply(code, payload, headers=headers)

        def _fleet_hook(self) -> None:
            """Arbiter control plane: adopt a borrowed-host replica into
            the fleet / drain it back out. Delegates to hooks the fleet
            CLI installs; a router without them answers 501."""
            hook = (router.fleet_adopt_fn if self.path == "/fleet/adopt"
                    else router.fleet_release_fn)
            if hook is None:
                self._reply(501, {"error": "router has no arbiter hooks "
                                           f"({self.path})"})
                return
            length = int(self.headers.get("Content-Length", 0))
            try:
                payload = json.loads(self.rfile.read(length) or b"{}")
            except ValueError as e:
                self._reply(400, {"error": f"bad JSON body: {e}"})
                return
            url = payload.get("url", "")
            if not url:
                self._reply(400, {"error": "missing \"url\""})
                return
            try:
                out = hook(url)
            except Exception as e:  # noqa: BLE001 # vtx: ignore[VTX106] hook failure -> arbiter, not a dead socket
                self._reply(500, {"error": f"{type(e).__name__}: {e}"})
                return
            self._reply(200, out if isinstance(out, dict) else {"ok": True})

    return Handler


def start_router(router: Router, port: int):
    """Bind the fleet front door (background thread). Returns the httpd;
    httpd.server_address[1] is the bound port (0 = ephemeral, tests)."""
    httpd = ThreadingHTTPServer(("0.0.0.0", port), _make_handler(router))
    httpd.daemon_threads = True
    thread = threading.Thread(  # vtx: ignore[VTX205] stop_router's httpd.shutdown() ends serve_forever
        target=httpd.serve_forever, daemon=True, name="vitax-fleet-router")
    thread.start()
    return httpd


def stop_router(httpd, router: Optional[Router] = None) -> None:
    httpd.shutdown()
    httpd.server_close()
    if router is not None:
        router.close()
