"""Per-replica circuit breaker + token-bucket retry budget for the router.

Two containment mechanisms the dispatch path (vitax/serve/fleet/router.py)
composes on top of the manager's health ejection — which only sees
`/healthz`, so a replica that answers health probes but fails every
dispatch (wedged batcher, hung accelerator) stays in rotation forever
without them:

- **CircuitBreaker** (one per replica): closed -> open after
  `fail_threshold` CONSECUTIVE dispatch failures; while open, the router
  skips the replica entirely (no connection attempt, no timeout burned).
  After `cooldown_s` the breaker admits exactly ONE probe dispatch
  (half-open): success re-closes it, failure re-opens it for another
  cooldown. The closed path is a single lock-guarded state check — no
  dispatch latency when healthy.

- **RetryBudget** (one per router): gRPC-style token bucket capping
  retries + hedges at a fraction of recent request volume. Every
  dispatched request deposits `ratio` tokens (bucket capped at `cap`);
  every retry or hedge withdraws one whole token. When the fleet is dying
  and every request wants a retry, the bucket drains and the router
  degrades to FAST 503s instead of multiplying the load it cannot serve
  (the retry-storm anti-pattern). `ratio <= 0` disables the budget
  (every withdraw granted — the pre-budget behavior).

Stdlib-only and jax-free, like the rest of the router tier. Telemetry:
state transitions surface through the `on_event` callback as
`kind:"breaker"` events; counters fold into the router's /metrics.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

# breaker states
CLOSED = "closed"          # healthy: dispatches flow
OPEN = "open"              # tripped: no dispatches until cooldown elapses
HALF_OPEN = "half_open"    # cooldown over: exactly one probe in flight

DEFAULT_FAIL_THRESHOLD = 3
DEFAULT_COOLDOWN_S = 2.0
DEFAULT_BUDGET_RATIO = 0.1
DEFAULT_BUDGET_CAP = 10.0


class CircuitBreaker:
    """Closed/open/half-open state machine over consecutive dispatch
    failures. Thread-safe: handler threads record outcomes concurrently."""

    def __init__(self, name: str,
                 fail_threshold: int = DEFAULT_FAIL_THRESHOLD,
                 cooldown_s: float = DEFAULT_COOLDOWN_S,
                 clock: Callable[[], float] = time.monotonic,
                 on_event: Optional[Callable[[dict], None]] = None):
        assert fail_threshold >= 1, fail_threshold
        assert cooldown_s >= 0, cooldown_s
        self.name = name
        self.fail_threshold = fail_threshold
        self.cooldown_s = cooldown_s
        self._clock = clock
        self._on_event = on_event
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._open_until = 0.0
        self._probe_in_flight = False
        self.opens_total = 0      # closed -> open trips
        self.reopens_total = 0    # failed half-open probes
        self.closes_total = 0     # successful re-admissions

    # -- dispatch-side API ---------------------------------------------------

    def eligible(self) -> bool:
        """May a dispatch be SENT here now? Pure check, no reservation —
        the router uses it to filter replica selection. Closed: always.
        Open: only once the cooldown elapsed (the would-be probe).
        Half-open: only while the single probe slot is free."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                return self._clock() >= self._open_until
            return not self._probe_in_flight

    def begin(self) -> bool:
        """Reserve the dispatch just picked for this replica. Closed: free.
        Open past cooldown: transition to half-open and claim the single
        probe slot. False means another thread won the probe race (or the
        breaker is still cooling down) — the caller must pick elsewhere."""
        event = None
        with self._lock:
            if self._state == CLOSED:
                ok = True
            elif self._state == OPEN:
                if self._clock() >= self._open_until:
                    self._state = HALF_OPEN
                    self._probe_in_flight = True
                    event = {"event": "half_open"}
                    ok = True
                else:
                    ok = False
            else:  # HALF_OPEN
                ok = not self._probe_in_flight
                if ok:
                    self._probe_in_flight = True
        if event is not None:
            self._emit(event)
        return ok

    def release_unused(self) -> None:
        """Hand back a begin() reservation without an outcome (the picked
        replica was never dispatched to — e.g. hedge bookkeeping)."""
        with self._lock:
            self._probe_in_flight = False

    def record_success(self) -> None:
        event = None
        with self._lock:
            self._consecutive_failures = 0
            if self._state == HALF_OPEN:
                self._state = CLOSED
                self._probe_in_flight = False
                self.closes_total += 1
                event = {"event": "close"}
        if event is not None:
            self._emit(event)

    def record_failure(self) -> None:
        event = None
        with self._lock:
            if self._state == HALF_OPEN:
                # the probe failed: back to open for another cooldown
                self._state = OPEN
                self._probe_in_flight = False
                self._open_until = self._clock() + self.cooldown_s
                self.reopens_total += 1
                event = {"event": "reopen"}
            elif self._state == CLOSED:
                self._consecutive_failures += 1
                if self._consecutive_failures >= self.fail_threshold:
                    self._state = OPEN
                    self._open_until = self._clock() + self.cooldown_s
                    self.opens_total += 1
                    event = {"event": "open",
                             "failures": self._consecutive_failures}
            # OPEN: a straggler failure from a pre-trip dispatch — no-op
        if event is not None:
            self._emit(event)

    # -- observability -------------------------------------------------------

    def state(self) -> str:
        with self._lock:
            return self._state

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "state": self._state,
                "consecutive_failures": self._consecutive_failures,
                "opens_total": self.opens_total,
                "reopens_total": self.reopens_total,
                "closes_total": self.closes_total,
            }

    def _emit(self, payload: dict) -> None:
        # outside the lock: the telemetry sink must never block transitions
        if self._on_event is not None:
            try:
                self._on_event({"replica": self.name, **payload})
            except Exception:  # noqa: BLE001 # vtx: ignore[VTX106] telemetry must not kill dispatch
                pass


class RetryBudget:
    """Token bucket bounding retries + hedges to a fraction of traffic."""

    def __init__(self, ratio: float = DEFAULT_BUDGET_RATIO,
                 cap: float = DEFAULT_BUDGET_CAP):
        assert ratio >= 0, ratio
        assert cap >= 1, cap
        self.ratio = ratio
        self.cap = float(cap)
        self._lock = threading.Lock()
        # starts full: a cold router can absorb a startup blip's retries
        self._tokens = float(cap)
        self.deposits_total = 0
        self.granted_total = 0
        self.exhausted_total = 0

    @property
    def enabled(self) -> bool:
        return self.ratio > 0

    def deposit(self) -> None:
        """One dispatched request earns `ratio` tokens of future retry."""
        if not self.enabled:
            return
        with self._lock:
            self.deposits_total += 1
            self._tokens = min(self.cap, self._tokens + self.ratio)

    def withdraw(self) -> bool:
        """Spend one token to retry/hedge; False = budget exhausted, the
        caller must fail fast (503) instead of amplifying load."""
        if not self.enabled:
            return True
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                self.granted_total += 1
                return True
            self.exhausted_total += 1
            return False

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "ratio": self.ratio,
                "cap": self.cap,
                "tokens": round(self._tokens, 3),
                "deposits_total": self.deposits_total,
                "granted_total": self.granted_total,
                "exhausted_total": self.exhausted_total,
            }
