"""CLI entry: python -m vitax.serve.fleet — N replicas behind one router.

Shares the single-replica CLI surface (every python -m vitax.serve flag
works here and is forwarded to the replicas) plus the fleet flags:

    python -m vitax.serve.fleet --replicas 2 --ckpt_dir /ckpts \\
        --embed_dim 5120 ... --serve_port 8000 --slo_p99_ms 500

The router binds --serve_port; replica i binds --base_port + i (default
base_port = serve_port + 1). When --metrics_dir is set the router writes
<metrics_dir>/serve.jsonl (admission sheds, replica lifecycle) and each
replica writes its own under <metrics_dir>/replica_<i>/. SIGTERM/SIGINT
shut down the router, then SIGTERM-drain every replica (in-flight
answered, exit 0).
"""

from __future__ import annotations

import signal
import sys
import threading
from typing import List, Sequence

from vitax.config import Config, build_parser, config_fields_from_namespace

# fleet/source flags that must NOT be forwarded to replica processes
# (value-taking form: both "--flag v" and "--flag=v" are stripped)
_FLEET_ONLY_FLAGS = (
    "--replicas", "--base_port", "--slo_p99_ms", "--health_interval_s",
    "--fail_threshold", "--replica_max_restarts",
    # router-side failure containment (vitax/serve/fleet/breaker.py):
    "--breaker_threshold", "--breaker_cooldown_s", "--retry_budget_ratio",
    "--hedge_after_ms",
    # replica-specific overrides the fleet re-issues per replica:
    "--serve_port", "--metrics_dir",
)


def strip_flags(argv: Sequence[str], flags: Sequence[str]) -> List[str]:
    """Drop value-taking flags (and their values) from an argv copy, in
    both "--flag value" and "--flag=value" spellings."""
    out: List[str] = []
    skip = False
    for arg in argv:
        if skip:
            skip = False
            continue
        name = arg.split("=", 1)[0]
        if name in flags:
            skip = "=" not in arg
            continue
        out.append(arg)
    return out


def replica_argv(argv: Sequence[str], port: int,
                 metrics_dir: str = "") -> List[str]:
    """The subprocess command for one replica: the fleet CLI minus the
    fleet-only flags, re-targeted at this replica's port/metrics dir."""
    child = [sys.executable, "-m", "vitax.serve"]
    child += strip_flags(argv, _FLEET_ONLY_FLAGS)
    child += ["--serve_port", str(port)]
    if metrics_dir:
        child += ["--metrics_dir", metrics_dir]
    return child


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    parser = build_parser()
    src = parser.add_argument_group("vitax serve source")
    src.add_argument("--npz", type=str, default="",
                     help="consolidated .npz export to serve (overrides "
                          "--ckpt_dir/--epoch)")
    src.add_argument("--epoch", type=int, default=-1,
                     help="epoch checkpoint to serve (-1 = latest under "
                          "--ckpt_dir)")
    fleet = parser.add_argument_group("vitax serve fleet")
    fleet.add_argument("--replicas", type=int, default=2,
                       help="engine replicas to spawn behind the router")
    fleet.add_argument("--base_port", type=int, default=0,
                       help="replica i binds base_port + i "
                            "(0 = serve_port + 1)")
    fleet.add_argument("--slo_p99_ms", type=float, default=0.0,
                       help="p99 deadline for admission control: arrivals "
                            "whose predicted queue wait exceeds it are shed "
                            "with 429 + Retry-After (0 = shedding off)")
    fleet.add_argument("--health_interval_s", type=float, default=0.5,
                       help="seconds between replica /healthz sweeps")
    fleet.add_argument("--fail_threshold", type=int, default=2,
                       help="consecutive failed health polls before a READY "
                            "replica is ejected from rotation")
    fleet.add_argument("--replica_max_restarts", type=int, default=10,
                       help="restarts-with-backoff per replica before the "
                            "fleet gives up on it")
    fleet.add_argument("--breaker_threshold", type=int, default=3,
                       help="consecutive dispatch failures that trip a "
                            "replica's circuit breaker open (half-open "
                            "single-probe re-admission after the cooldown)")
    fleet.add_argument("--breaker_cooldown_s", type=float, default=2.0,
                       help="seconds an open breaker waits before admitting "
                            "its half-open probe dispatch")
    fleet.add_argument("--retry_budget_ratio", type=float, default=0.1,
                       help="retry/hedge token earned per dispatched "
                            "request: caps retries at this fraction of "
                            "recent traffic so a dying fleet degrades to "
                            "fast 503s, not a retry storm (0 = unlimited)")
    fleet.add_argument("--hedge_after_ms", type=float, default=0.0,
                       help="opt-in hedged requests: when the first attempt "
                            "exceeds max(this, rolling p99), fire a second "
                            "attempt on another replica — first response "
                            "wins, bounded by the retry budget (0 = off)")
    ns = parser.parse_args(argv)
    cfg = Config(**config_fields_from_namespace(ns)).validate()
    assert ns.replicas >= 1, f"--replicas must be >= 1, got {ns.replicas}"
    base_port = ns.base_port or cfg.serve_port + 1

    from vitax.serve.server import build_serve_recorder
    from vitax.serve.fleet.admission import AdmissionController
    from vitax.serve.fleet.replica import ReplicaManager
    from vitax.serve.fleet.router import Router, start_router, stop_router

    recorder = build_serve_recorder(cfg)
    # arm the chaos layer in THIS process too: the replica_health and
    # router_dispatch fault sites live router-side (--fault_plan is also
    # forwarded to every replica for the engine/batcher sites)
    import os
    from vitax import faults
    if cfg.fault_plan or os.environ.get(faults.ENV_VAR, ""):
        faults.install_from_config(cfg)
        if recorder is not None:
            faults.set_reporter(
                lambda p: recorder.event("serve_fault", **p))
    manager = ReplicaManager(
        recorder=recorder, health_interval_s=ns.health_interval_s,
        fail_threshold=ns.fail_threshold,
        max_restarts=ns.replica_max_restarts)
    for i in range(ns.replicas):
        port = base_port + i
        metrics_dir = (os.path.join(cfg.metrics_dir, f"replica_{i}")
                       if cfg.metrics_dir else "")
        manager.manage(replica_argv(argv, port, metrics_dir),
                       f"http://127.0.0.1:{port}", name=f"replica_{i}")
    manager.start()

    admission = AdmissionController(ns.slo_p99_ms, recorder=recorder)
    router = Router(manager, admission=admission, recorder=recorder,
                    request_timeout_s=cfg.serve_request_timeout_s,
                    breaker_threshold=ns.breaker_threshold,
                    breaker_cooldown_s=ns.breaker_cooldown_s,
                    retry_budget_ratio=ns.retry_budget_ratio,
                    hedge_after_ms=ns.hedge_after_ms)
    httpd = start_router(router, cfg.serve_port)
    print(f"fleet: router on :{httpd.server_address[1]}, {ns.replicas} "
          f"replicas on :{base_port}..:{base_port + ns.replicas - 1} "
          f"(slo_p99_ms {ns.slo_p99_ms or 'off'})", flush=True)

    stop = threading.Event()

    def _on_signal(signum, frame):  # noqa: ARG001 — handler signature
        stop.set()

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, _on_signal)
        except ValueError:
            pass  # not the main thread (embedded use)
    while not stop.wait(timeout=0.5):
        pass
    print("fleet: shutting down (router first, then replica drains)",
          flush=True)
    stop_router(httpd)
    manager.stop()  # SIGTERM-drains each replica: in-flight answered
    if recorder is not None:
        recorder.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
