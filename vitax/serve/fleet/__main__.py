"""CLI entry: python -m vitax.serve.fleet — N replicas behind one router.

Shares the single-replica CLI surface (every python -m vitax.serve flag
works here and is forwarded to the replicas) plus the fleet flags:

    python -m vitax.serve.fleet --replicas 2 --ckpt_dir /ckpts \\
        --embed_dim 5120 ... --serve_port 8000 --slo_p99_ms 500

The router binds --serve_port; replica i binds --base_port + i (default
base_port = serve_port + 1). When --metrics_dir is set the router writes
<metrics_dir>/serve.jsonl (admission sheds, replica lifecycle) and each
replica writes its own under <metrics_dir>/replica_<i>/. SIGTERM/SIGINT
shut down the router, then SIGTERM-drain every replica (in-flight
answered, exit 0).
"""

from __future__ import annotations

import signal
import sys
import threading
from typing import List, Sequence

from vitax.config import Config, build_parser, config_fields_from_namespace

# fleet/source flags that must NOT be forwarded to replica processes
# (value-taking form: both "--flag v" and "--flag=v" are stripped)
_FLEET_ONLY_FLAGS = (
    "--replicas", "--base_port", "--slo_p99_ms", "--health_interval_s",
    "--fail_threshold", "--replica_max_restarts",
    # router-side failure containment (vitax/serve/fleet/breaker.py):
    "--breaker_threshold", "--breaker_cooldown_s", "--retry_budget_ratio",
    "--hedge_after_ms",
    # autoscaling + cross-host placement (this PR's fleet growth tier):
    "--min_replicas", "--max_replicas", "--warming_capacity_frac",
    "--autoscale_dwell_s", "--autoscale_cooldown_s", "--autoscale_idle_frac",
    "--placement_agents", "--arbiter_url",
    # router-side caching/batching knobs (Config fields, but meaningless
    # inside a replica process — keep its argv clean):
    "--serve_cache_max", "--serve_cache_ttl_s", "--serve_batch_window_ms",
    "--serve_batch_max",
    # replica-specific overrides the fleet re-issues per replica:
    "--serve_port", "--metrics_dir",
)


def strip_flags(argv: Sequence[str], flags: Sequence[str]) -> List[str]:
    """Drop value-taking flags (and their values) from an argv copy, in
    both "--flag value" and "--flag=value" spellings."""
    out: List[str] = []
    skip = False
    for arg in argv:
        if skip:
            skip = False
            continue
        name = arg.split("=", 1)[0]
        if name in flags:
            skip = "=" not in arg
            continue
        out.append(arg)
    return out


def replica_argv(argv: Sequence[str], port: int,
                 metrics_dir: str = "") -> List[str]:
    """The subprocess command for one replica: the fleet CLI minus the
    fleet-only flags, re-targeted at this replica's port/metrics dir."""
    child = [sys.executable, "-m", "vitax.serve"]
    child += strip_flags(argv, _FLEET_ONLY_FLAGS)
    child += ["--serve_port", str(port)]
    if metrics_dir:
        child += ["--metrics_dir", metrics_dir]
    return child


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    parser = build_parser()
    src = parser.add_argument_group("vitax serve source")
    src.add_argument("--npz", type=str, default="",
                     help="consolidated .npz export to serve (overrides "
                          "--ckpt_dir/--epoch)")
    src.add_argument("--epoch", type=int, default=-1,
                     help="epoch checkpoint to serve (-1 = latest under "
                          "--ckpt_dir)")
    fleet = parser.add_argument_group("vitax serve fleet")
    fleet.add_argument("--replicas", type=int, default=2,
                       help="engine replicas to spawn behind the router")
    fleet.add_argument("--base_port", type=int, default=0,
                       help="replica i binds base_port + i "
                            "(0 = serve_port + 1)")
    fleet.add_argument("--slo_p99_ms", type=float, default=0.0,
                       help="p99 deadline for admission control: arrivals "
                            "whose predicted queue wait exceeds it are shed "
                            "with 429 + Retry-After (0 = shedding off)")
    fleet.add_argument("--health_interval_s", type=float, default=0.5,
                       help="seconds between replica /healthz sweeps")
    fleet.add_argument("--fail_threshold", type=int, default=2,
                       help="consecutive failed health polls before a READY "
                            "replica is ejected from rotation")
    fleet.add_argument("--replica_max_restarts", type=int, default=10,
                       help="restarts-with-backoff per replica before the "
                            "fleet gives up on it")
    fleet.add_argument("--breaker_threshold", type=int, default=3,
                       help="consecutive dispatch failures that trip a "
                            "replica's circuit breaker open (half-open "
                            "single-probe re-admission after the cooldown)")
    fleet.add_argument("--breaker_cooldown_s", type=float, default=2.0,
                       help="seconds an open breaker waits before admitting "
                            "its half-open probe dispatch")
    fleet.add_argument("--retry_budget_ratio", type=float, default=0.1,
                       help="retry/hedge token earned per dispatched "
                            "request: caps retries at this fraction of "
                            "recent traffic so a dying fleet degrades to "
                            "fast 503s, not a retry storm (0 = unlimited)")
    fleet.add_argument("--hedge_after_ms", type=float, default=0.0,
                       help="opt-in hedged requests: when the first attempt "
                            "exceeds max(this, rolling p99), fire a second "
                            "attempt on another replica — first response "
                            "wins, bounded by the retry budget (0 = off)")
    fleet.add_argument("--min_replicas", type=int, default=0,
                       help="autoscaler floor (0 = --replicas); a fleet "
                            "below it is repaired regardless of traffic")
    fleet.add_argument("--max_replicas", type=int, default=0,
                       help="autoscaler ceiling; > 0 turns the autoscaler "
                            "on (scale-out on sustained sheds / predicted-"
                            "wait overshoot / brownout, scale-in on "
                            "sustained idleness; 0 = static fleet)")
    fleet.add_argument("--warming_capacity_frac", type=float, default=0.5,
                       help="admission counts a live-but-warming replica as "
                            "this fraction of a ready one, so mid-scale-out "
                            "sheds relax toward the new capacity")
    fleet.add_argument("--autoscale_dwell_s", type=float, default=3.0,
                       help="a scale signal must hold this long before the "
                            "autoscaler acts (blips never scale)")
    fleet.add_argument("--autoscale_cooldown_s", type=float, default=10.0,
                       help="dead time after every scaling action, so one "
                            "decision's consequences are observed before "
                            "the next")
    fleet.add_argument("--autoscale_idle_frac", type=float, default=0.25,
                       help="scale-in trigger: in-flight per ready replica "
                            "sustained at or below this with zero sheds")
    fleet.add_argument("--placement_agents", type=str, default="",
                       help="comma-separated placement-agent URLs (python "
                            "-m vitax.serve.fleet.agent, one per host); "
                            "replicas and scale-outs round-robin across "
                            "them instead of spawning locally")
    # NOTE: --arbiter_url itself is a Config field (build_parser's ext
    # group defines it); fleet-side it turns on autoscaler escalation and
    # the router's /fleet/adopt + /fleet/release hooks below. It stays in
    # _FLEET_ONLY_FLAGS so replicas never see it.
    ns = parser.parse_args(argv)
    cfg = Config(**config_fields_from_namespace(ns)).validate()
    assert ns.replicas >= 1, f"--replicas must be >= 1, got {ns.replicas}"
    min_replicas = ns.min_replicas or ns.replicas
    if ns.max_replicas:
        assert min_replicas <= ns.max_replicas, (
            f"--min_replicas {min_replicas} must be <= --max_replicas "
            f"{ns.max_replicas}")
    base_port = ns.base_port or cfg.serve_port + 1

    from vitax.serve.server import build_serve_recorder
    from vitax.serve.fleet.admission import AdmissionController
    from vitax.serve.fleet.autoscale import Autoscaler
    from vitax.serve.fleet.cache import PredictionCache
    from vitax.serve.fleet.placement import AgentFullError, PlacementClient
    from vitax.serve.fleet.replica import ReplicaManager
    from vitax.serve.fleet.router import Router, start_router, stop_router

    recorder = build_serve_recorder(cfg)
    # arm the chaos layer in THIS process too: the replica_health and
    # router_dispatch fault sites live router-side (--fault_plan is also
    # forwarded to every replica for the engine/batcher sites)
    import os
    from vitax import faults
    if cfg.fault_plan or os.environ.get(faults.ENV_VAR, ""):
        faults.install_from_config(cfg)
        if recorder is not None:
            faults.set_reporter(
                lambda p: recorder.event("serve_fault", **p))
    manager = ReplicaManager(
        recorder=recorder, health_interval_s=ns.health_interval_s,
        fail_threshold=ns.fail_threshold,
        max_restarts=ns.replica_max_restarts)

    # -- provisioning: local spawn, or round-robin across placement agents.
    # One closure serves both the initial fleet and autoscaler scale-outs,
    # so a grown replica is indistinguishable from a boot-time one.
    agents = [PlacementClient(u.strip())
              for u in ns.placement_agents.split(",") if u.strip()]
    placed: dict = {}          # local name -> (client, remote name)
    spawn_state = {"next": 0, "rr": 0}
    spawn_lock = threading.Lock()

    def spawn_replica():
        with spawn_lock:
            i = spawn_state["next"]
            spawn_state["next"] += 1
            rr = spawn_state["rr"]
            spawn_state["rr"] += 1
        name = f"replica_{i}"
        if agents:
            # round-robin, but a full agent (409/AgentFullError) is not the
            # end: try every other agent before raising — only a fleet with
            # NO free slot anywhere escalates to the arbiter
            last_full = None
            for k in range(len(agents)):
                client = agents[(rr + k) % len(agents)]
                try:
                    out = client.provision(
                        strip_flags(argv, _FLEET_ONLY_FLAGS), name=name)
                except AgentFullError as e:
                    last_full = e
                    continue
                replica = manager.adopt(out["url"], name=name)
                with spawn_lock:
                    placed[name] = (client, out["name"])
                return replica
            raise last_full
        port = base_port + i
        metrics_dir = (os.path.join(cfg.metrics_dir, f"replica_{i}")
                       if cfg.metrics_dir else "")
        return manager.manage(replica_argv(argv, port, metrics_dir),
                              f"http://127.0.0.1:{port}", name=name)

    def release_replica(replica):
        # scale-in epilogue: a locally managed replica was already
        # SIGTERM-drained by discard(); a placed one must also be freed on
        # its agent so the remote process never leaks
        with spawn_lock:
            entry = placed.pop(replica.name, None)
        if entry is not None:
            client, remote_name = entry
            client.release(remote_name)

    for _ in range(ns.replicas):
        spawn_replica()
    manager.start()

    admission = AdmissionController(
        ns.slo_p99_ms, recorder=recorder,
        warming_capacity_frac=ns.warming_capacity_frac)

    # -- arbiter escalation: when the fleet is at --max_replicas (or every
    # agent slot is taken) the autoscaler asks the chip arbiter for a
    # whole host instead of failing. Fire-and-forget POST; the arbiter's
    # ticker decides, borrows, and calls back on /fleet/adopt.
    request_capacity = None
    if ns.arbiter_url:
        import json as json_mod
        import urllib.request

        def request_capacity(reason: str):
            data = json_mod.dumps({"reason": reason}).encode("utf-8")
            req = urllib.request.Request(
                ns.arbiter_url.rstrip("/") + "/request", data=data,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=2.0) as resp:
                return json_mod.load(resp)

    autoscaler = None
    if ns.max_replicas > 0:
        autoscaler = Autoscaler(
            manager, admission=admission, min_replicas=min_replicas,
            max_replicas=ns.max_replicas, scale_out=spawn_replica,
            release=release_replica, dwell_s=ns.autoscale_dwell_s,
            cooldown_s=ns.autoscale_cooldown_s,
            idle_occupancy=ns.autoscale_idle_frac, recorder=recorder,
            request_capacity=request_capacity)
        autoscaler.start()
    cache = (PredictionCache(cfg.serve_cache_max,
                             ttl_s=cfg.serve_cache_ttl_s, recorder=recorder)
             if cfg.serve_cache_max > 0 else None)
    router = Router(manager, admission=admission, recorder=recorder,
                    request_timeout_s=cfg.serve_request_timeout_s,
                    breaker_threshold=ns.breaker_threshold,
                    breaker_cooldown_s=ns.breaker_cooldown_s,
                    retry_budget_ratio=ns.retry_budget_ratio,
                    hedge_after_ms=ns.hedge_after_ms,
                    cache=cache, autoscaler=autoscaler,
                    batch_window_ms=cfg.serve_batch_window_ms,
                    batch_max=cfg.serve_batch_max or cfg.serve_max_batch)

    if ns.arbiter_url:
        # the arbiter's side of the loan: adopt() a replica it provisioned
        # on a borrowed host into rotation, and on return retire -> wait
        # for in-flight zero -> discard (adopted processes belong to the
        # arbiter's agent, so discard only forgets the URL)
        borrow_state = {"next": 0}

        def fleet_adopt(url: str) -> dict:
            with spawn_lock:
                k = borrow_state["next"]
                borrow_state["next"] += 1
            replica = manager.adopt(url, name=f"borrowed_{k}")
            return {"adopted": replica.name, "url": url}

        def fleet_release(url: str) -> dict:
            target = None
            for name, snap in manager.snapshot().items():
                if snap.get("url") == url:
                    target = manager.find(name)
                    break
            if target is None:
                return {"released": None, "url": url}
            manager.retire(target)
            pause = threading.Event()
            waited = 0.0
            while (manager.in_flight_of(target) > 0
                   and waited < cfg.serve_request_timeout_s):
                pause.wait(0.05)
                waited += 0.05
            manager.discard(target)
            return {"released": target.name, "url": url,
                    "in_flight_at_discard": manager.in_flight_of(target)}

        router.fleet_adopt_fn = fleet_adopt
        router.fleet_release_fn = fleet_release

    httpd = start_router(router, cfg.serve_port)
    scale_desc = (f"autoscale [{min_replicas}, {ns.max_replicas}]"
                  if autoscaler is not None else "static")
    print(f"fleet: router on :{httpd.server_address[1]}, {ns.replicas} "
          f"replicas ({'placed' if agents else f'on :{base_port}..'}), "
          f"{scale_desc}, slo_p99_ms {ns.slo_p99_ms or 'off'}, "
          f"cache {cfg.serve_cache_max or 'off'}, "
          f"batch_window_ms {cfg.serve_batch_window_ms or 'off'}",
          flush=True)

    stop = threading.Event()

    def _on_signal(signum, frame):  # noqa: ARG001 — handler signature
        stop.set()

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, _on_signal)
        except ValueError:
            pass  # not the main thread (embedded use)
    while not stop.wait(timeout=0.5):
        pass
    print("fleet: shutting down (router first, then replica drains)",
          flush=True)
    stop_router(httpd, router)
    if autoscaler is not None:
        autoscaler.stop()
    manager.stop()  # SIGTERM-drains each replica: in-flight answered
    for name, (client, remote_name) in list(placed.items()):
        try:
            client.release(remote_name)
        except Exception:  # noqa: BLE001 # vtx: ignore[VTX106] best-effort: the agent also drains on its own shutdown
            pass
    if recorder is not None:
        recorder.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
