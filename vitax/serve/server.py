"""HTTP front end: stdlib ThreadingHTTPServer over the engine + batcher.

Endpoints:
- POST /predict  — body is raw image bytes (any PIL-decodable format) or
                   JSON {"image": <base64 image bytes>, "topk": <optional,
                   <= --serve_topk>}; the image runs the SAME eval
                   transforms training validation uses
                   (vitax/data/transforms.py ValTransform), then the
                   dynamic batcher; response is
                   {"classes": [...], "probs": [...], "latency_ms": ...}.
- GET /healthz   — liveness + readiness: the server is LIVE once it binds
                   (status "ok") but READY only after AOT bucket warmup
                   completes and while not draining — a fleet router
                   (vitax/serve/fleet/) keys rotation off "ready".
- GET /metrics   — aggregate counters: requests/s, latency p50/p95/p99,
                   queue wait, batch occupancy, queue depth, the configured
                   request timeout, readiness/drain state.

Overload and shutdown semantics:
- a full batcher queue (--serve_queue_max) answers 503 with JSON reason
  "queue_full" and Retry-After — the fleet router maps that to an
  admission shed (429);
- **brownout** (BrownoutController): queue depth sustained at or above
  --serve_brownout_enter_frac of --serve_queue_max for
  --serve_brownout_dwell_s enters DEGRADED mode — optional work is shed
  (topk clamped to 1, the batcher deadline shortened to
  --serve_brownout_wait_ms so queued work drains in smaller waits) and
  /healthz + /metrics advertise `degraded: true` (the fleet router folds
  the count into its aggregate). Recovery is hysteretic: depth must hold
  at or below --serve_brownout_exit_frac for the same dwell. Degraded is
  NOT unready — a browned-out replica still serves;
- SIGTERM drains gracefully (python -m vitax.serve): stop accepting new
  work (ready: false, new /predict -> 503), answer every in-flight
  request, flush the batcher, exit 0 — so a ReplicaManager restart never
  drops an accepted request.

Chaos: --fault_plan (or VITAX_FAULT_PLAN) arms the serve fault sites
(vitax/faults.py: engine_predict, batcher_flush) at startup; with
--serve_allow_chaos, POST /chaos installs a plan into a RUNNING replica
(tools/serve_bench.py --chaos drives this). Fired faults surface as
kind:"serve_fault" telemetry events.

Observability rides the existing vitax.telemetry Recorder/sinks: one
schema-versioned JSONL record per request (kind "serve_request") plus
lifecycle events land in <metrics_dir>/serve.jsonl, summarized by
tools/serve_bench.py --json for CI.
"""

from __future__ import annotations

import base64
import io
import json
import os
import signal
import sys
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from vitax import faults
from vitax.config import Config
from vitax.serve.engine import InferenceEngine
from vitax.serve.batcher import DynamicBatcher, QueueFull
from vitax.platform import device_kind
from vitax.telemetry.threads import install_thread_excepthook
from vitax.utils.logging import master_print

# acceptance contract of a serve_request record: tools/serve_bench.py and
# tests/test_serve.py key off this exact set (beyond the Recorder's own
# schema/time/kind/rank envelope)
REQUIRED_SERVE_KEYS = (
    "latency_s", "queue_wait_s", "infer_s", "batch_size", "bucket", "topk",
)

# a request outlives at most: its batcher deadline + one engine batch +
# generous slack — beyond that the handler answers 503 instead of hanging
# the client forever. Default for --serve_request_timeout_s (and the
# fallback when a Config predates the flag).
REQUEST_TIMEOUT_S = 60.0


class ServeMetrics:
    """Thread-safe aggregate counters behind GET /metrics."""

    def __init__(self, window: int = 2048):
        self._lock = threading.Lock()
        self.started = time.time()
        self.requests_total = 0
        self.errors_total = 0
        self._latency = deque(maxlen=window)
        self._wait = deque(maxlen=window)
        self._occupancy = deque(maxlen=window)  # batch_size / bucket
        self._times = deque(maxlen=window)      # completion timestamps

    def observe(self, latency_s: float, queue_wait_s: float,
                batch_size: int, bucket: int) -> None:
        with self._lock:
            self.requests_total += 1
            self._latency.append(latency_s)
            self._wait.append(queue_wait_s)
            self._occupancy.append(batch_size / max(bucket, 1))
            self._times.append(time.time())

    def error(self) -> None:
        with self._lock:
            self.errors_total += 1

    @staticmethod
    def _pct(sorted_vals, q: float) -> Optional[float]:
        if not sorted_vals:
            return None
        pos = (len(sorted_vals) - 1) * q
        lo = int(pos)
        hi = min(lo + 1, len(sorted_vals) - 1)
        frac = pos - lo
        return float(sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac)

    def snapshot(self) -> dict:
        with self._lock:
            lat = sorted(self._latency)
            waits = list(self._wait)
            occ = list(self._occupancy)
            times = list(self._times)
            total, errors = self.requests_total, self.errors_total
        now = time.time()
        recent = [t for t in times if now - t <= 60.0]
        return {
            "requests_total": total,
            "errors_total": errors,
            "uptime_s": round(now - self.started, 3),
            "requests_per_sec": round(total / max(now - self.started, 1e-9), 3),
            "requests_per_sec_60s": round(len(recent) / 60.0, 3),
            "latency_s_p50": self._pct(lat, 0.50),
            "latency_s_p95": self._pct(lat, 0.95),
            "latency_s_p99": self._pct(lat, 0.99),
            "queue_wait_s_mean": (round(sum(waits) / len(waits), 6)
                                  if waits else None),
            "batch_occupancy_mean": (round(sum(occ) / len(occ), 4)
                                     if occ else None),
        }


class BrownoutController:
    """Hysteretic degraded mode keyed on batcher queue depth.

    Pressure (depth >= enter_depth) sustained for `dwell_s` enters
    DEGRADED; calm (depth <= exit_depth) sustained for the same dwell
    exits. The dwell window means blips never flip the mode, and the
    enter/exit gap means depths between the thresholds hold the current
    state — the two classic chatter guards composed. `clock` is
    injectable so tests drive transitions without real time.

    The controller only decides; the owner passes `on_enter`/`on_exit`
    callbacks for the actual shedding (topk clamp, batcher deadline) and
    telemetry. Disabled (never degrades) when queue_max or enter_frac
    is 0."""

    def __init__(self, queue_max: int, enter_frac: float, exit_frac: float,
                 dwell_s: float,
                 clock: Callable[[], float] = time.monotonic,
                 on_enter: Optional[Callable[[], None]] = None,
                 on_exit: Optional[Callable[[float], None]] = None):
        self.enabled = queue_max > 0 and enter_frac > 0
        self.enter_depth = enter_frac * queue_max
        self.exit_depth = exit_frac * queue_max
        self.dwell_s = dwell_s
        self._clock = clock
        self._on_enter = on_enter
        self._on_exit = on_exit
        self._lock = threading.Lock()
        self.degraded = False
        self._streak_since: Optional[float] = None  # pressure/calm streak
        self._entered_at: Optional[float] = None
        self.enters_total = 0
        self._degraded_s = 0.0  # accumulated across COMPLETED episodes

    def observe(self, depth: int, now: Optional[float] = None) -> bool:
        """Feed one queue-depth sample; returns the (possibly updated)
        degraded state. Called from /predict and /healthz handlers — the
        health poll keeps recovery moving when traffic stops entirely."""
        if not self.enabled:
            return False
        now = self._clock() if now is None else now
        transition = None
        with self._lock:
            if not self.degraded:
                if depth >= self.enter_depth:
                    if self._streak_since is None:
                        self._streak_since = now
                    if now - self._streak_since >= self.dwell_s:
                        self.degraded = True
                        self.enters_total += 1
                        self._entered_at = now
                        self._streak_since = None
                        transition = ("enter", depth)
                else:
                    self._streak_since = None
            else:
                if depth <= self.exit_depth:
                    if self._streak_since is None:
                        self._streak_since = now
                    if now - self._streak_since >= self.dwell_s:
                        self.degraded = False
                        episode_s = now - (self._entered_at or now)
                        self._degraded_s += episode_s
                        self._entered_at = None
                        self._streak_since = None
                        transition = ("exit", episode_s)
                else:
                    self._streak_since = None
            degraded = self.degraded
        # callbacks outside the lock: they touch the batcher and telemetry
        if transition is not None:
            kind, arg = transition
            if kind == "enter" and self._on_enter is not None:
                self._on_enter()
            elif kind == "exit" and self._on_exit is not None:
                self._on_exit(arg)
        return degraded

    def degraded_seconds(self, now: Optional[float] = None) -> float:
        """Total time spent degraded, including the live episode."""
        with self._lock:
            total = self._degraded_s
            if self._entered_at is not None:
                total += (self._clock() if now is None else now) \
                    - self._entered_at
            return total


def build_serve_recorder(cfg: Config):
    """Recorder writing schema-versioned serve.jsonl records through the
    existing telemetry sinks, or None when --metrics_dir is unset. Fail-soft
    like training telemetry: an unwritable dir disables recording, never
    serving."""
    metrics_dir = getattr(cfg, "metrics_dir", "") or ""
    if not metrics_dir:
        return None
    import jax
    from vitax.telemetry.record import Recorder
    from vitax.telemetry.sinks import JsonlSink
    try:
        os.makedirs(metrics_dir, exist_ok=True)
        sinks = [JsonlSink(os.path.join(metrics_dir, "serve.jsonl"))]
    except OSError as e:
        print(f"vitax.serve: --metrics_dir {metrics_dir!r} is not writable "
              f"({e}); serve telemetry disabled", file=sys.stderr, flush=True)
        return None
    return Recorder(cfg, sinks, jax.device_count(),
                    device_kind(), rank=0)


def decode_image_bytes(raw: bytes, transform):
    """One /predict image body -> transformed HWC array.

    JPEG bodies route through the native in-memory pipeline
    (vitax/data/native.py process_bytes — libjpeg decode + the PIL-parity
    resize, one C call, no per-request Python decode tax); anything else, or
    a native failure/missing library, falls back to PIL. The two paths apply
    the SAME eval transform (tests/test_stream.py pins resize-path parity)."""
    from vitax.data import native
    if (native.is_jpeg_bytes(raw) and hasattr(transform, "native_params")
            and native.mem_available()):
        arr = native.process_bytes(
            raw, transform.native_params(0, 0, 0), transform.image_size,
            getattr(transform, "resize_to", 0),
            normalize=getattr(transform, "normalize", True))
        if arr is not None:
            return arr
    from PIL import Image
    img = Image.open(io.BytesIO(raw)).convert("RGB")
    return transform(img)


class ServeContext:
    """Everything a handler thread needs, wired once at startup."""

    def __init__(self, cfg: Config, engine: InferenceEngine, recorder=None):
        from vitax.data.transforms import val_transform
        self.cfg = cfg
        self.engine = engine
        self.recorder = recorder
        self.metrics = ServeMetrics()
        self.request_timeout_s = float(
            getattr(cfg, "serve_request_timeout_s", REQUEST_TIMEOUT_S))
        # drain/readiness state: handlers enter through enter_request() so a
        # drain can wait for the in-flight count to reach zero before the
        # batcher is flushed and the process exits
        self.draining = False
        self._inflight = 0
        self._flight_cond = threading.Condition()
        # normalize=False: the eval stack emits uint8 HWC and the engine's
        # compiled program normalizes on device (vitax/train/step.py
        # prepare_images) — the same split training uses
        self.transform = val_transform(cfg.image_size, normalize=False)
        from vitax.serve.engine import next_bucket
        self.batcher = DynamicBatcher(
            engine.predict, max_batch=cfg.serve_max_batch,
            max_wait_ms=cfg.max_batch_wait_ms,
            bucket_of=lambda n: next_bucket(n, engine.buckets),
            on_batch=self._record_batch,
            queue_max=getattr(cfg, "serve_queue_max", 0))
        # brownout: shed optional work under sustained queue pressure
        # instead of tipping into queue-full sheds (degraded != unready:
        # a browned-out replica still serves)
        self.brownout = BrownoutController(
            queue_max=getattr(cfg, "serve_queue_max", 0),
            enter_frac=getattr(cfg, "serve_brownout_enter_frac", 0.0),
            exit_frac=getattr(cfg, "serve_brownout_exit_frac", 0.0),
            dwell_s=getattr(cfg, "serve_brownout_dwell_s", 2.0),
            on_enter=self._brownout_enter, on_exit=self._brownout_exit)

    def _brownout_enter(self) -> None:
        # shorten the flush deadline: under pressure, smaller faster
        # batches drain the queue instead of waiting out the full deadline
        self.batcher.set_max_wait_ms(
            getattr(self.cfg, "serve_brownout_wait_ms", 1.0))
        if self.recorder is not None:
            self.recorder.event("brownout", event="enter",
                                queue_depth=self.batcher.queue_depth())

    def _brownout_exit(self, degraded_s: float) -> None:
        self.batcher.set_max_wait_ms(self.cfg.max_batch_wait_ms)
        if self.recorder is not None:
            self.recorder.event("brownout", event="exit",
                                degraded_s=round(degraded_s, 6))

    def degraded(self) -> bool:
        """Current brownout verdict, refreshed with a live depth sample
        (handlers call this, so /healthz polls keep recovery moving even
        with zero traffic)."""
        return self.brownout.observe(self.batcher.queue_depth())

    def is_ready(self) -> bool:
        """READY = warmed up and not draining. Distinct from liveness: a
        warming or draining server still answers /healthz (live) but must
        not receive routed traffic."""
        return not self.draining and getattr(self.engine, "ready", True)

    def enter_request(self) -> bool:
        """Admit one /predict into the in-flight set; False when the server
        is warming or draining (the handler answers 503)."""
        with self._flight_cond:
            if not self.is_ready():
                return False
            self._inflight += 1
            return True

    def exit_request(self) -> None:
        with self._flight_cond:
            self._inflight -= 1
            self._flight_cond.notify_all()

    def inflight(self) -> int:
        with self._flight_cond:
            return self._inflight

    def wait_idle(self, timeout_s: float) -> bool:
        """Block until every in-flight request is answered (drain step 2);
        False if `timeout_s` elapsed with requests still in flight."""
        deadline = time.monotonic() + timeout_s
        with self._flight_cond:
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._flight_cond.wait(timeout=remaining)
            return True

    def _record_batch(self, stats: dict) -> None:
        if self.recorder is not None:
            self.recorder.event("serve_batch", **stats)

    def decode(self, body: bytes, content_type: str):
        """(uint8 HWC image, requested topk) from a /predict body."""
        topk = self.engine.topk
        if "application/json" in content_type:
            payload = json.loads(body.decode("utf-8"))
            raw = base64.b64decode(payload["image"])
            if "topk" in payload:
                topk = int(payload["topk"])
                if not 1 <= topk <= self.engine.topk:
                    raise ValueError(
                        f"topk must be in [1, {self.engine.topk}] "
                        f"(--serve_topk caps the compiled top-k)")
        else:
            raw = body
        return decode_image_bytes(raw, self.transform), topk

    def close(self) -> None:
        self.batcher.close()
        if self.recorder is not None:
            self.recorder.close()


def _make_handler(ctx: ServeContext):
    class Handler(BaseHTTPRequestHandler):
        # per-request access logging off: at serving rates stderr chatter is
        # a throughput bug, and telemetry owns the durable record
        def log_message(self, fmt, *args):  # noqa: A003
            pass

        def _reply(self, code: int, payload: dict,
                   headers: Optional[dict] = None) -> None:
            body = json.dumps(payload).encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for key, value in (headers or {}).items():
                self.send_header(key, value)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
            if self.path == "/healthz":
                self._reply(200, {
                    "status": "ok",                 # liveness: we answered
                    "ready": ctx.is_ready(),        # routable: warmed + not draining
                    "draining": ctx.draining,
                    "degraded": ctx.degraded(),     # brownout: serving, but shedding optional work
                    "degraded_seconds": round(
                        ctx.brownout.degraded_seconds(), 3),
                    "buckets": list(ctx.engine.buckets),
                    "topk": ctx.engine.topk,
                    "compile_count": ctx.engine.compile_count,
                })
            elif self.path == "/metrics":
                snap = ctx.metrics.snapshot()
                snap["queue_depth"] = ctx.batcher.queue_depth()
                snap["queue_max"] = ctx.batcher.queue_max
                snap["batches_flushed"] = ctx.batcher.batches_flushed
                snap["compile_count"] = ctx.engine.compile_count
                snap["request_timeout_s"] = ctx.request_timeout_s
                snap["ready"] = ctx.is_ready()
                snap["draining"] = ctx.draining
                snap["degraded"] = ctx.degraded()
                snap["degraded_seconds"] = round(
                    ctx.brownout.degraded_seconds(), 3)
                snap["brownout_enters"] = ctx.brownout.enters_total
                # device-resident weight footprint (vitax/serve/quant.py):
                # the per-replica HBM number serve_bench and the fleet
                # router's capacity math read; only-when-reported so
                # engine-shaped stand-ins without the accounting still serve
                if hasattr(ctx.engine, "weights_dtype"):
                    snap["weights_dtype"] = ctx.engine.weights_dtype
                if hasattr(ctx.engine, "param_bytes"):
                    snap["param_bytes"] = ctx.engine.param_bytes()
                # tier-2 quant mode flags (PR 16): which activation-quant
                # and fused-dequant policy this replica's program compiled
                # with — the fleet router surfaces mixed values during a
                # rollout
                if hasattr(ctx.engine, "act_quant"):
                    snap["act_quant"] = ctx.engine.act_quant
                if hasattr(ctx.engine, "fused_dequant"):
                    snap["fused_dequant"] = ctx.engine.fused_dequant
                self._reply(200, snap)
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})

        def do_POST(self):  # noqa: N802
            if self.path == "/chaos":
                self._chaos()
                return
            if self.path not in ("/predict", "/predict_batch"):
                self._reply(404, {"error": f"unknown path {self.path}"})
                return
            if not ctx.enter_request():
                reason = "draining" if ctx.draining else "warming_up"
                ctx.metrics.error()
                self._reply(503, {"error": f"not ready: {reason}",
                                  "reason": reason},
                            headers={"Retry-After": "1"})
                return
            try:
                if self.path == "/predict_batch":
                    self._predict_batch()
                else:
                    self._predict()
            finally:
                ctx.exit_request()

        def _chaos(self) -> None:
            """Install a fault plan into this running replica (the drill
            transport behind tools/serve_bench.py --chaos). Gated hard on
            --serve_allow_chaos: an open chaos endpoint on a production
            replica would be remote code-adjacent sabotage, so without the
            opt-in the path answers 403 and changes nothing. An empty body
            disarms."""
            if not getattr(ctx.cfg, "serve_allow_chaos", False):
                self._reply(403, {
                    "error": "chaos endpoint disabled "
                             "(start with --serve_allow_chaos to arm)"})
                return
            length = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(length).decode("utf-8").strip()
            if not body:
                faults.uninstall()
                self._reply(200, {"installed": None})
                return
            try:
                plan = faults.install(body)
            except ValueError as e:
                self._reply(400, {"error": str(e)})
                return
            if ctx.recorder is not None:
                rec = ctx.recorder
                faults.set_reporter(
                    lambda p: rec.event("serve_fault", **p))
            if ctx.recorder is not None:
                ctx.recorder.event("chaos_install", plan=plan.describe())
            self._reply(200, {"installed": plan.describe()})

        def _predict(self) -> None:
            t0 = time.time()
            try:
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                image, topk = ctx.decode(
                    body, self.headers.get("Content-Type", ""))
            except Exception as e:  # noqa: BLE001 — client error, not ours
                ctx.metrics.error()
                self._reply(400, {"error": f"bad request: {e}"})
                return
            # brownout: this sample feeds the pressure window, and while
            # degraded the optional work (top-k beyond 1) is shed
            if ctx.degraded():
                topk = 1
            try:
                fut = ctx.batcher.submit(image)
            except QueueFull as e:
                # typed overload: the fleet router maps this reason to an
                # admission shed (429); a bare client just backs off
                ctx.metrics.error()
                self._reply(503, {"error": f"overloaded: {e}",
                                  "reason": "queue_full"},
                            headers={"Retry-After": "1"})
                return
            try:
                result = fut.result(timeout=ctx.request_timeout_s)
            except Exception as e:  # noqa: BLE001
                ctx.metrics.error()
                self._reply(503, {"error": f"inference failed: {e}"})
                return
            latency_s = time.time() - t0
            ctx.metrics.observe(latency_s, result.queue_wait_s,
                                result.batch_size, result.bucket)
            if ctx.recorder is not None:
                ctx.recorder.event(
                    "serve_request", latency_s=round(latency_s, 6),
                    queue_wait_s=round(result.queue_wait_s, 6),
                    infer_s=round(result.infer_s, 6),
                    batch_size=result.batch_size, bucket=result.bucket,
                    topk=topk)
            self._reply(200, {
                "classes": [int(c) for c in result.classes[:topk]],
                "probs": [float(p) for p in result.probs[:topk]],
                "latency_ms": round(latency_s * 1000.0, 3),
            })

        def _predict_batch(self) -> None:
            """Composed dispatch from the fleet router (BatchComposer in
            vitax/serve/fleet/router.py): decode every item, submit ALL
            of them to the batcher BEFORE waiting on any future — the
            group lands in the queue together, so the DynamicBatcher
            flushes it as one bucket instead of trickling singles through
            its max_batch_wait_ms window. Each item's `body` is the exact
            JSON a lone /predict would have produced (same engine, same
            formatting), so composed and direct dispatch are
            indistinguishable to clients. Per-item failures (bad image,
            queue full, inference error) settle that item only; the
            batch call itself only 400s on an unparseable envelope."""
            t0 = time.time()
            try:
                length = int(self.headers.get("Content-Length", 0))
                wire = json.loads(self.rfile.read(length).decode("utf-8"))
                bodies = [base64.b64decode(s) for s in wire["items"]]
                ctypes = wire.get("content_types") or [""] * len(bodies)
                if len(ctypes) != len(bodies):
                    raise ValueError("content_types/items length mismatch")
            except Exception as e:  # noqa: BLE001 — client error, not ours
                ctx.metrics.error()
                self._reply(400, {"error": f"bad batch request: {e}"})
                return
            results = [None] * len(bodies)
            waiting = []  # (index, topk, future)
            for i, (body, ctype) in enumerate(zip(bodies, ctypes)):
                try:
                    image, topk = ctx.decode(body, ctype)
                except Exception as e:  # noqa: BLE001 — client error
                    ctx.metrics.error()
                    results[i] = {"status": 400, "body": json.dumps(
                        {"error": f"bad request: {e}"})}
                    continue
                if ctx.degraded():
                    topk = 1
                try:
                    fut = ctx.batcher.submit(image)
                except QueueFull as e:
                    ctx.metrics.error()
                    results[i] = {"status": 503, "reason": "queue_full",
                                  "body": json.dumps(
                                      {"error": f"overloaded: {e}",
                                       "reason": "queue_full"})}
                    continue
                waiting.append((i, topk, fut))
            for i, topk, fut in waiting:
                try:
                    result = fut.result(timeout=ctx.request_timeout_s)
                except Exception as e:  # noqa: BLE001
                    ctx.metrics.error()
                    results[i] = {"status": 503, "body": json.dumps(
                        {"error": f"inference failed: {e}"})}
                    continue
                latency_s = time.time() - t0
                ctx.metrics.observe(latency_s, result.queue_wait_s,
                                    result.batch_size, result.bucket)
                if ctx.recorder is not None:
                    ctx.recorder.event(
                        "serve_request", latency_s=round(latency_s, 6),
                        queue_wait_s=round(result.queue_wait_s, 6),
                        infer_s=round(result.infer_s, 6),
                        batch_size=result.batch_size, bucket=result.bucket,
                        topk=topk, batched=True)
                results[i] = {"status": 200, "body": json.dumps({
                    "classes": [int(c) for c in result.classes[:topk]],
                    "probs": [float(p) for p in result.probs[:topk]],
                    "latency_ms": round(latency_s * 1000.0, 3),
                })}
            self._reply(200, {"results": results})

    return Handler


def start_server(cfg: Config, engine: InferenceEngine,
                 port: Optional[int] = None):
    """Warmed engine -> listening server (background thread).

    Returns (httpd, ctx): httpd.server_address[1] is the bound port (pass
    port=0 / --serve_port 0 for an ephemeral one — tests do). Call
    `stop_server(httpd, ctx)` to drain and shut down."""
    recorder = build_serve_recorder(cfg)
    # arm the serve-path chaos sites (engine_predict, batcher_flush) when a
    # plan is named; left untouched otherwise so embedding tests that
    # installed a plan directly keep it
    if getattr(cfg, "fault_plan", "") or os.environ.get(faults.ENV_VAR, ""):
        faults.install_from_config(cfg)
    if faults.active() and recorder is not None:
        faults.set_reporter(lambda p: recorder.event("serve_fault", **p))
    # batcher worker + HTTP handler threads: crashes become thread_crash
    # events in serve.jsonl instead of silent 500s-forever
    install_thread_excepthook(recorder, rank=0)
    ctx = ServeContext(cfg, engine, recorder=recorder)
    bind_port = cfg.serve_port if port is None else port
    httpd = ThreadingHTTPServer(("0.0.0.0", bind_port), _make_handler(ctx))
    httpd.daemon_threads = True
    thread = threading.Thread(  # vtx: ignore[VTX205] stop_server's httpd.shutdown() ends serve_forever
        target=httpd.serve_forever, daemon=True, name="vitax-serve-http")
    thread.start()
    if recorder is not None:
        recorder.event("serve_start", port=httpd.server_address[1],
                       buckets=list(engine.buckets), topk=engine.topk,
                       max_batch_wait_ms=cfg.max_batch_wait_ms,
                       compile_count=engine.compile_count)
    master_print(f"serve: listening on :{httpd.server_address[1]} "
                 f"(buckets {list(engine.buckets)}, "
                 f"wait {cfg.max_batch_wait_ms}ms, top-{engine.topk})")
    return httpd, ctx


def stop_server(httpd, ctx: ServeContext) -> None:
    httpd.shutdown()
    httpd.server_close()
    ctx.close()


def drain(httpd, ctx: ServeContext, timeout_s: float = 30.0) -> bool:
    """Graceful shutdown: stop accepting, answer in-flight, flush, close.

    The SIGTERM contract a ReplicaManager restart relies on — an accepted
    request is never dropped:
      1. mark draining (healthz reports ready: false; new /predict -> 503)
         and stop the accept loop;
      2. wait for every in-flight request to be answered (their batch
         futures resolve through the still-running batcher worker);
      3. close the batcher (flushes anything still queued) and telemetry.
    Returns True when the in-flight set drained inside `timeout_s`."""
    with ctx._flight_cond:
        ctx.draining = True
    httpd.shutdown()
    idle = ctx.wait_idle(timeout_s)
    httpd.server_close()
    if ctx.recorder is not None:
        ctx.recorder.event("serve_drain", clean=idle,
                           inflight_left=ctx.inflight())
    ctx.close()
    if not idle:
        master_print(f"serve: drain timed out after {timeout_s:.0f}s with "
                     f"{ctx.inflight()} requests in flight")
    return idle


def serve_forever(cfg: Config, engine: InferenceEngine) -> None:
    """Blocking entry point (python -m vitax.serve).

    Binds FIRST, then warms up: /healthz is answerable (live, ready: false)
    while the AOT buckets compile, so a fleet router can watch a replica
    warm without routing to it. SIGTERM/SIGINT trigger the graceful drain
    and the function returns (the CLI exits 0)."""
    httpd, ctx = start_server(cfg, engine)
    stop = threading.Event()

    def _on_signal(signum, frame):  # noqa: ARG001 — handler signature
        stop.set()

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, _on_signal)
        except ValueError:
            pass  # not the main thread (embedded use): Ctrl-C unavailable
    if not getattr(engine, "ready", True):
        engine.warmup()
    while not stop.wait(timeout=0.5):
        pass
    master_print("serve: draining (SIGTERM/SIGINT)")
    drain(httpd, ctx)
