"""Quantized serving: int8 weights on device, dequantized at use.

The serve half of the --dtype int8 export (vitax/checkpoint/consolidate.py):
`InferenceEngine.from_npz` keeps every quantized leaf RESIDENT AS INT8 on
device — the manifest's float32 per-output-channel scales are the only
extra state — and the eval forward dequantizes at use, inside the jitted
program: `(w_int8 * scale).astype(compute)` feeds the consuming matmul
directly, so XLA fuses the convert+multiply into the dot's operand read and
no f32 copy of a weight ever persists between calls. HBM per replica drops
~4x on the weight tree (the fleet-density axis — README "Quantized
serving"), while the AOT bucket contract, zero-recompile pin, and
mesh/sharding layout are untouched: int8 leaves have the same shapes as
their f32 originals, so `param_specs` shards them identically, and the
scales (keepdims-broadcast, O(out_channels)) ride along replicated.

The schema is dtype-keyed (consolidate.QUANT_DTYPES: int8 and
float8_e4m3), so both quantized dtypes share this whole module — fp8
leaves just dequantize through the same `w_q.astype(f32) * scale` read.
VTX-R007 (vitax/analysis/rules.py) pins the result on the lowered
program: large matmul operands quantized-dtype-sourced, no block-sized
float weight argument.

Tier 2 (this file's additions): `merge_quant_scales` folds the flat scale
table into the param tree as sibling `qscale` leaves so the QuantDense
serve model (vitax/models/vit.py) can consume them through `nn.scan`'s
per-layer slicing, and `dense_site_kind` classifies which quantized
leaves belong to QuantDense sites vs. the in-place dequant fallback (the
patchify conv). The fused Pallas kernel itself lives in
vitax/ops/dequant_matmul.py.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from vitax.checkpoint.consolidate import (
    QUANT_SCALE_PREFIX,
    flatten_tree,
    quantize_flat,
)

PyTree = Any


def dequant_spec(flat: Dict[str, np.ndarray],
                 manifest: Dict[str, str]) -> Dict[str, dict]:
    """Per-key load spec of a quantized export: the dtype-aware load tree
    (the SNIPPETS §3 `make_shard_and_gather_fns(dtype_specs=...)` shape).

    {key: {"dtype": stored dtype string, "quantized": bool, "scale_key":
    scale entry name or None}} — from_npz walks this to decide which leaves
    stay int8 on device and which device_put at their stored float dtype."""
    spec: Dict[str, dict] = {}
    for k, v in flat.items():
        q = manifest.get(k)
        spec[k] = {
            "dtype": q if q else str(np.asarray(v).dtype),
            "quantized": q is not None,
            "scale_key": (QUANT_SCALE_PREFIX + k) if q else None,
        }
    return spec


def dequantize_leaf(w_q: jax.Array, scale: jax.Array,
                    dtype=jnp.float32) -> jax.Array:
    """`(w_int8 * scale).astype(dtype)` — called INSIDE the jitted forward,
    so the convert+multiply fuses into the consuming matmul's operand read
    instead of materializing a resident full-precision copy."""
    return (w_q.astype(dtype) * scale.astype(dtype)).astype(dtype)


def fused_dequant_matmul(x: jax.Array, w_q: jax.Array, scale: jax.Array,
                         dtype=jnp.float32) -> jax.Array:
    """x @ dequant(w_q): the canonical fused form — under jit XLA folds the
    dequant into the dot's rhs, which is exactly what the engine's in-jit
    `dequantize_tree` + flax Dense lowers to (tests/test_quant.py pins the
    numerics against the f32 matmul)."""
    return jnp.matmul(x.astype(dtype), dequantize_leaf(w_q, scale, dtype))


def dequantize_tree(qparams: PyTree, scales: Dict[str, jax.Array],
                    dtype=jnp.float32) -> PyTree:
    """Rebuild the full-precision param tree from int8 leaves + flat scales
    ("/"-joined keys, the flatten_tree convention). Must be called inside
    the jitted predict: outside it, the result would be the resident f32
    copy the whole design exists to avoid."""
    def leaf(path, v):
        key = "/".join(
            str(getattr(p, "key", getattr(p, "name", getattr(p, "idx", p))))
            for p in path)
        s = scales.get(key)
        return v if s is None else dequantize_leaf(v, s, dtype)
    return jax.tree_util.tree_map_with_path(leaf, qparams)


def dense_site_kind(key: str) -> str:
    """Classify a quantized leaf's consumer in the QuantDense serve model.

    "block" — the in-block Dense matmuls (qkv/proj/fc1/fc2), eligible for
    activation quant and the fused kernel; "head" — the classifier head
    (fused weight-only; its f32 logits feed softmax, so never act-quant);
    "" — everything else (the patchify conv kernel, MoE w1/w2), which stays
    on the in-place `dequantize_tree` path. The patch_embed conv is named
    "proj" too — the blocks-scope check is what excludes it."""
    parts = key.split("/")
    if len(parts) < 2 or parts[-1] != "kernel":
        return ""
    parent = parts[-2]
    if parent == "head":
        return "head"
    in_blocks = any(p == "blocks" or p.startswith("blocks_") for p in parts)
    if in_blocks and parent in ("qkv", "proj", "fc1", "fc2"):
        return "block"
    return ""


def merge_quant_scales(params: PyTree, scales: Dict[str, jax.Array]) -> PyTree:
    """Fold flat "/"-keyed scales into the param tree as sibling `qscale`
    leaves (".../qkv/kernel" gains ".../qkv/qscale") — the shape QuantDense
    (vitax/models/vit.py) declares, so scan-stacked (L, 1, F) scales slice
    per layer exactly like the stacked kernels. Called INSIDE the jitted
    predict; the input tree is copied structurally, never mutated."""
    from collections.abc import Mapping

    def copy(t):
        return {k: (copy(v) if isinstance(v, Mapping) else v)
                for k, v in t.items()}

    tree = copy(params)
    for key, s in scales.items():
        node = tree
        for p in key.split("/")[:-1]:
            node = node[p]
        node["qscale"] = s
    return tree


def scale_shardings(scales: Dict[str, np.ndarray], mesh) -> Dict[str, NamedSharding]:
    """Scales are O(out_channels) — replicate them; the int8 weights keep
    the full param_specs layout (same shapes as their f32 originals)."""
    return {k: NamedSharding(mesh, P()) for k in scales}


def quantize_params_for_serve(params: PyTree, cfg, mesh,
                              dtype: str = "int8") -> Tuple[PyTree, Dict[str, jax.Array]]:
    """In-memory quantization of a (possibly sharded) param tree for a serve
    engine: host-side per-channel int8/fp8 + scales, device_put back with
    the weights in their original shard layout and the scales replicated.
    The invariant arms use this to build the quantized serve program without
    a checkpoint on disk (vitax/analysis/rules.py build_serve_program)."""
    from vitax.checkpoint.consolidate import unflatten_tree
    from vitax.parallel.sharding import param_specs, shardings_of
    flat = {k: np.asarray(jax.device_get(v))
            for k, v in flatten_tree(params).items()}
    qflat, scales = quantize_flat(flat, dtype)
    qtree = unflatten_tree(qflat)
    # param_pspec keys off path+shape only, so the int8 tree lands in the
    # exact layout the f32 tree had
    shardings = shardings_of(mesh, param_specs(qtree, cfg, mesh))
    qtree = jax.tree.map(jax.device_put, qtree, shardings)
    sc_sh = scale_shardings(scales, mesh)
    scales = {k: jax.device_put(v, sc_sh[k]) for k, v in scales.items()}
    return qtree, scales


def topk_accuracy(ids: np.ndarray, labels: np.ndarray) -> Tuple[float, float]:
    """(top1, top5) from engine predict output ids (n, k) and labels (n,).
    top5 uses min(5, k) columns — the engine clamps k to num_classes."""
    labels = np.asarray(labels).reshape(-1, 1)
    top1 = float(np.mean(ids[:, :1] == labels))
    top5 = float(np.mean(np.any(ids[:, :min(5, ids.shape[1])] == labels,
                                axis=1)))
    return top1, top5


def eval_engine(engine, images: np.ndarray, labels: np.ndarray,
                batch: Optional[int] = None) -> Tuple[float, float]:
    """Top-1/top-5 of one engine over a fixed (images, labels) set, batched
    through the same bucketed predict path traffic uses — the serve-side
    twin of train.loop.eval_on_val's counting."""
    b = batch or engine.buckets[-1]
    ids = np.concatenate([
        engine.predict(images[i:i + b])[0]
        for i in range(0, images.shape[0], b)], axis=0)
    return topk_accuracy(ids, labels)


def run_quant_gate(engine_f32, engine_q, images: np.ndarray,
                   labels: np.ndarray, recorder=None) -> dict:
    """The accuracy gate: quantized vs f32 top-1/top-5 on the same eval set.

    Returns the gate record (top1/top5 per engine, deltas IN POINTS, n,
    weights dtypes) and, with a Recorder (--metrics_dir), emits it as one
    kind:"quant_gate" telemetry event — tools/metrics_report.py surfaces the
    latest. The hard threshold (|delta top1| <= 1.0 points) lives in
    tests/test_quant.py, where a regression fails CI instead of shipping."""
    top1_f, top5_f = eval_engine(engine_f32, images, labels)
    top1_q, top5_q = eval_engine(engine_q, images, labels)
    gate = {
        "top1_f32": top1_f, "top5_f32": top5_f,
        "top1_quant": top1_q, "top5_quant": top5_q,
        "delta_top1": round(100.0 * (top1_q - top1_f), 4),
        "delta_top5": round(100.0 * (top5_q - top5_f), 4),
        "n": int(images.shape[0]),
        "weights_dtype": engine_q.weights_dtype,
        "baseline_dtype": engine_f32.weights_dtype,
        "act_quant": getattr(engine_q, "act_quant", "off"),
        "fused_dequant": getattr(engine_q, "fused_dequant", False),
    }
    if recorder is not None:
        recorder.event("quant_gate", **gate)
    return gate
