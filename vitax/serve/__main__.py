"""CLI entry: python -m vitax.serve — load params, warm up, serve HTTP.

Shares the training CLI surface (vitax/config.py build_parser — the model
shape flags MUST match the checkpoint being served) plus two source flags:

    # serve the latest Orbax epoch checkpoint
    python -m vitax.serve --ckpt_dir /ckpts --embed_dim 5120 ... --serve_port 8000

    # serve a consolidated single-file export (vitax.checkpoint.consolidate)
    python -m vitax.serve --npz full.npz --embed_dim 5120 ...
"""

from __future__ import annotations

import sys

from vitax.config import Config, build_parser, config_fields_from_namespace


def main(argv=None) -> int:
    parser = build_parser()
    src = parser.add_argument_group("vitax serve source")
    src.add_argument("--npz", type=str, default="",
                     help="consolidated .npz export to serve (overrides "
                          "--ckpt_dir/--epoch)")
    src.add_argument("--epoch", type=int, default=-1,
                     help="epoch checkpoint to serve (-1 = latest under "
                          "--ckpt_dir)")
    ns = parser.parse_args(argv)
    cfg = Config(**config_fields_from_namespace(ns)).validate()

    # the registry's engine constructor (vitax/programs/builder.py):
    # scenario-checked, then npz export or Orbax checkpoint exactly as the
    # flags say — arbiter-provisioned replicas boot through the same path
    from vitax.programs.builder import build_engine
    from vitax.serve.server import serve_forever
    engine = build_engine(cfg, npz=ns.npz,
                          epoch=None if ns.epoch < 0 else ns.epoch)
    # serve_forever binds first, THEN warms: /healthz answers (live,
    # ready: false) while the AOT buckets compile, so a fleet router can
    # watch the replica warm without routing to it; SIGTERM drains cleanly
    # (in-flight answered, batcher flushed) and we exit 0
    serve_forever(cfg, engine)
    return 0


if __name__ == "__main__":
    sys.exit(main())
