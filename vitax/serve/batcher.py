"""Dynamic micro-batcher: requests -> futures -> bucketed engine batches.

The Orca/Clipper-style adaptive batching core: requests enqueue with a
Future and a single worker thread flushes them as one engine batch when
either the largest bucket fills (`max_batch`) or the oldest queued request
has waited `max_batch_wait_ms` — whichever comes first. Under load the
batcher runs full buckets back-to-back (throughput); a lone request waits
at most the deadline (bounded tail latency).

Thread-safe by construction: HTTP handler threads only append under the
condition lock and block on their Future; all engine work happens on the
one worker thread, so the engine needs no internal locking.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Callable, Optional

import numpy as np

from vitax import faults


class QueueFull(RuntimeError):
    """submit() against a batcher whose pending queue is at queue_max.

    The typed overload signal of the serve path: the single-engine HTTP
    server maps it to 503 (reason "queue_full"), the fleet router
    (vitax/serve/fleet/router.py) maps a replica's queue-full 503 to an
    admission shed (429 + Retry-After). Before the bound existed the deque
    grew without limit under overload and every queued request eventually
    timed out — now the queue depth is bounded by --serve_queue_max."""


class BatchResult:
    """Per-request slice of a flushed batch, plus the batch's accounting
    (queue wait, engine latency, occupancy) for telemetry."""

    __slots__ = ("classes", "probs", "queue_wait_s", "infer_s",
                 "batch_size", "bucket")

    def __init__(self, classes, probs, queue_wait_s, infer_s, batch_size,
                 bucket):
        self.classes = classes            # (k,) int32 class ids
        self.probs = probs                # (k,) float32 probabilities
        self.queue_wait_s = queue_wait_s  # this request's time in queue
        self.infer_s = infer_s            # engine latency of its batch
        self.batch_size = batch_size      # real requests in the batch
        self.bucket = bucket              # padded bucket it executed in


class DynamicBatcher:
    """Queue + worker thread around `predict_fn(images) -> (ids, probs)`.

    `predict_fn` receives a stacked (n, H, W, 3) array with n <= max_batch
    and returns per-row top-k ids/probs; the engine pads n to its bucket
    internally and reports the bucket via `bucket_of` (so telemetry can
    record occupancy = batch_size / bucket).
    """

    def __init__(self, predict_fn: Callable, max_batch: int,
                 max_wait_ms: float,
                 bucket_of: Optional[Callable[[int], int]] = None,
                 on_batch: Optional[Callable[[dict], None]] = None,
                 queue_max: int = 0):
        assert max_batch >= 1
        assert queue_max >= 0, queue_max
        self.predict_fn = predict_fn
        self.max_batch = max_batch
        self.max_wait_s = max_wait_ms / 1000.0
        self.queue_max = queue_max        # 0 = unbounded (pre-bound behavior)
        self.bucket_of = bucket_of or (lambda n: n)
        self.on_batch = on_batch          # telemetry hook, called per flush
        self.batches_flushed = 0
        self._pending: deque = deque()    # (image, Future, t_enqueue)
        self._cond = threading.Condition()
        self._closed = False
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name="vitax-serve-batcher")
        self._worker.start()

    def submit(self, image: np.ndarray) -> Future:
        """Enqueue one (H, W, 3) image; resolves to a BatchResult.

        Raises QueueFull when `queue_max` requests are already pending —
        overload is answered at admission time, not by letting the deque
        grow until every queued request times out."""
        fut: Future = Future()
        with self._cond:
            if self._closed:
                raise RuntimeError("batcher is closed")
            if self.queue_max and len(self._pending) >= self.queue_max:
                raise QueueFull(
                    f"{len(self._pending)} requests already pending "
                    f"(--serve_queue_max {self.queue_max})")
            self._pending.append((image, fut, time.time()))
            self._cond.notify()
        return fut

    def queue_depth(self) -> int:
        with self._cond:
            return len(self._pending)

    def set_max_wait_ms(self, max_wait_ms: float) -> None:
        """Retune the flush deadline at runtime (brownout mode shortens it
        to drain the queue faster, then restores it on recovery). The worker
        recomputes its deadline from `max_wait_s` every cycle, so the new
        value takes effect at the next flush decision."""
        assert max_wait_ms >= 0, max_wait_ms
        with self._cond:
            self.max_wait_s = max_wait_ms / 1000.0
            self._cond.notify()

    def close(self, timeout: float = 10.0) -> None:
        """Stop accepting work, flush what is queued, join the worker."""
        with self._cond:
            self._closed = True
            self._cond.notify()
        self._worker.join(timeout=timeout)

    # --- worker -----------------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._pending and not self._closed:
                    self._cond.wait()
                if not self._pending and self._closed:
                    return
                # flush when the largest bucket fills or the OLDEST request
                # hits the deadline, whichever first (deadline recomputed
                # each wait so set_max_wait_ms() applies to queued work too)
                while (len(self._pending) < self.max_batch
                       and not self._closed):
                    deadline = self._pending[0][2] + self.max_wait_s
                    remaining = deadline - time.time()
                    if remaining <= 0:
                        break
                    self._cond.wait(timeout=remaining)
                batch = [self._pending.popleft()
                         for _ in range(min(len(self._pending),
                                            self.max_batch))]
            self._flush(batch)

    def _flush(self, batch) -> None:  # vtx: ignore[VTX103] predict_fn fences internally (np.asarray on outputs)
        images = np.stack([img for img, _, _ in batch])
        t_flush = time.time()
        try:
            # chaos hook on the worker thread: `hang` stalls the whole batch
            # (the predict-hang drill), `oserror` fails it — delivered to
            # every request future below, never killing the worker
            faults.fire("batcher_flush")
            ids, probs = self.predict_fn(images)
        except Exception as e:  # noqa: BLE001 — deliver, don't kill the worker
            for _, fut, _ in batch:
                if not fut.cancelled():
                    fut.set_exception(e)
            return
        infer_s = time.time() - t_flush
        n = len(batch)
        bucket = self.bucket_of(n)
        self.batches_flushed += 1
        for row, (_, fut, t_enq) in enumerate(batch):
            if not fut.cancelled():
                fut.set_result(BatchResult(
                    classes=ids[row], probs=probs[row],
                    queue_wait_s=t_flush - t_enq, infer_s=infer_s,
                    batch_size=n, bucket=bucket))
        if self.on_batch is not None:
            try:
                self.on_batch({"batch_size": n, "bucket": bucket,
                               "infer_s": infer_s,
                               "queue_wait_s_max": t_flush - batch[0][2]})
            except Exception:  # noqa: BLE001 # vtx: ignore[VTX106] telemetry must not kill serving
                pass
