"""InferenceEngine: checkpoint -> jitted eval-mode forward over bucketed batches.

The inference half of the stack (ROADMAP north star: serve heavy traffic).
Params come from either source the training side produces:

- `from_checkpoint`: a sharded Orbax epoch checkpoint (vitax/checkpoint/
  orbax_io.py) restored straight into the serving mesh layout — the abstract
  target tree carries the same param_specs shardings training used, so a
  checkpoint written on one topology serves on another;
- `from_npz`: a consolidated single-file export (vitax/checkpoint/
  consolidate.py), restored to the exact param tree via the shared
  flatten_tree/unflatten_tree key convention, then device_put per-shard.

The forward is eval-mode only (det=True: no dropout, no loss, no optimizer
state — the restored opt_state is dropped on the floor so a 10B serve fits
in a third of the training footprint) and is AOT-compiled once per
power-of-two batch bucket (1, 2, 4, ..., serve_max_batch) at startup
(`warmup`). Requests are padded to the next bucket, so steady-state traffic
executes precompiled programs only: `compile_count` is exactly
len(bucket_sizes) after warmup and never moves again — recompiles are
structurally impossible because `predict` calls AOT executables, which
reject any shape they were not compiled for (tests/test_serve.py pins this).
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from vitax import faults
from vitax.config import Config
from vitax.parallel.mesh import BATCH_AXES, Mesh, batch_pspec, build_mesh
from vitax.utils.logging import master_print


def bucket_sizes(max_batch: int) -> Tuple[int, ...]:
    """Power-of-two buckets 1, 2, 4, ..., max_batch (validate() guarantees
    max_batch is itself a power of two)."""
    sizes = []
    b = 1
    while b <= max_batch:
        sizes.append(b)
        b *= 2
    return tuple(sizes)


def next_bucket(n: int, buckets: Tuple[int, ...]) -> int:
    """Smallest bucket holding n requests (n must fit the largest bucket)."""
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(
        f"batch of {n} exceeds the largest bucket {buckets[-1]} "
        f"(--serve_max_batch); the batcher never emits this")


def _quant_model_mode(cfg: Config) -> bool:
    """Whether serving uses the QuantDense model (vitax/models/vit.py):
    quantized weights with activation quant and/or the fused dequant-matmul
    engaged. Weight-only serving with the fused kernel off keeps the PR-14
    `dequantize_tree` path — same jit signature either way, so VTX-R007's
    arg pins hold for both."""
    from vitax.ops.dequant_matmul import fused_dequant_active
    if not getattr(cfg, "serve_quant_dtype", ""):
        return False
    if getattr(cfg, "moe_experts", 0) > 0:
        return False
    return (getattr(cfg, "serve_act_quant", "off") != "off"
            or fused_dequant_active(cfg))


def _build_model(cfg: Config, mesh: Mesh, quantized: bool = True):
    """The same model construction the training loop performs (attention
    impl + activation-sharding anchors included), so serving runs the
    identical forward graph eval ran — except under quant-model mode
    (quantized=True and _quant_model_mode), where every Dense site becomes
    QuantDense consuming the quantized kernel + merged qscale directly.
    quantized=False forces the plain model (full-precision param sources:
    from_checkpoint, param init in the invariant arms)."""
    from vitax.models import build_model
    from vitax.ops.attention import make_attention_impl
    from vitax.train.loop import _moe_dispatch_sharding, _token_sharding
    quant_matmul = None
    if quantized and _quant_model_mode(cfg):
        from vitax.ops.dequant_matmul import make_quant_matmul
        quant_matmul = make_quant_matmul(cfg)
    return build_model(
        cfg, attention_impl=make_attention_impl(cfg, mesh),
        token_sharding=_token_sharding(cfg, mesh),
        moe_dispatch_sharding=_moe_dispatch_sharding(cfg, mesh),
        quant_matmul=quant_matmul)


class InferenceEngine:
    """Bucketed eval-mode forward: uint8 (B, H, W, 3) images -> top-k.

    Thread-compatible by design: `predict` is called from the batcher's
    single worker thread; construction/warmup happen before the server
    accepts traffic.
    """

    def __init__(self, cfg: Config, mesh: Mesh, model, params,
                 scales: Optional[Dict[str, jax.Array]] = None,
                 quant_dtype: str = ""):
        assert getattr(cfg, "pp_size", 1) == 1, (
            "serving v1 runs the non-pipelined forward; restore a pp "
            "checkpoint with --pp_size 1 (Orbax reshards on load)")
        assert bool(scales) == bool(quant_dtype), (
            "quantized engines carry both scales and quant_dtype")
        self.cfg = cfg
        self.mesh = mesh
        self.model = model
        self.params = params
        # quantized serving: int8 leaves stay int8 on device; scales is the
        # flat {param_key: float32 per-output-channel scale} side table
        # (replicated — O(out_channels)), and the jitted predict dequantizes
        # at use so XLA fuses the convert into the matmul (vitax/serve/
        # quant.py). Empty scales = plain full-precision engine.
        self.scales: Dict[str, jax.Array] = scales or {}
        self.quant_dtype = quant_dtype
        # tier-2 quant accounting (reported on /metrics, aggregated by the
        # fleet router, scraped by serve_bench): dynamic activation quant
        # mode and whether the Pallas fused dequant-matmul is engaged
        from vitax.ops.dequant_matmul import fused_dequant_active
        quantized = bool(self.scales)
        self.act_quant = (getattr(cfg, "serve_act_quant", "off")
                          if quantized else "off")
        self.fused_dequant = bool(quantized and fused_dequant_active(cfg))
        self._quant_model = quantized and _quant_model_mode(cfg)
        self.topk = min(cfg.serve_topk, cfg.num_classes)
        self.buckets = bucket_sizes(cfg.serve_max_batch)
        self.compile_count = 0          # warmup compiles; pinned by tests
        # readiness vs liveness: the HTTP server is LIVE as soon as it binds
        # (healthz answers), but READY only once every AOT bucket is compiled
        # and exercised — a fleet router must not dispatch to a warming
        # replica (vitax/serve/fleet/replica.py keys off healthz "ready")
        self.ready = False
        self._compiled: Dict[int, jax.stages.Compiled] = {}
        self._batch_shardings: Dict[int, NamedSharding] = {}
        # batch-carrying device count: buckets divisible by it shard the
        # batch; smaller buckets replicate (tiny inputs, sharded params)
        self._batch_devices = 1
        for ax in BATCH_AXES:
            self._batch_devices *= mesh.shape.get(ax, 1)

    # --- accounting (reported on /metrics and by serve_bench) -------------

    @property
    def quantized(self) -> bool:
        return bool(self.scales)

    @property
    def weights_dtype(self) -> str:
        """Dtype of the matmul weights as resident on device: the quant
        dtype for a quantized engine, else the dtype of the largest leaf
        (LN/bias stragglers don't get to name a bf16 or f32 tree)."""
        if self.scales:
            return self.quant_dtype
        largest = max(jax.tree.leaves(self.params), key=lambda v: v.size)
        return str(largest.dtype)

    def param_bytes(self) -> int:
        """Device-resident parameter footprint: weight leaves plus the
        quant scale side table, logical (unsharded) bytes — the per-replica
        HBM number the fleet density math runs on."""
        total = sum(int(v.nbytes) for v in jax.tree.leaves(self.params))
        total += sum(int(v.nbytes) for v in self.scales.values())
        return total

    # --- constructors -----------------------------------------------------

    @classmethod
    def from_checkpoint(cls, cfg: Config, ckpt_dir: Optional[str] = None,
                        epoch: Optional[int] = None) -> "InferenceEngine":
        """Restore params from a sharded Orbax epoch checkpoint (epoch None =
        latest) directly into the serving mesh layout."""
        from vitax.checkpoint.orbax_io import latest_epoch, restore_state
        from vitax.train.state import build_optimizer, make_train_state
        ckpt_dir = ckpt_dir or cfg.ckpt_dir
        if epoch is None:
            epoch = latest_epoch(ckpt_dir)
            assert epoch is not None, f"no epoch checkpoint under {ckpt_dir}"
        mesh = build_mesh(cfg)
        model = _build_model(cfg, mesh, quantized=False)
        # the abstract TrainState is the restore target (no device
        # materialization); the optimizer exists only to shape it — its
        # restored moments are dropped immediately below
        tx, _ = build_optimizer(cfg, max_iteration=1)
        abstract, _, _ = make_train_state(
            cfg, model, tx, mesh, jax.random.key(cfg.seed), materialize=False)
        state = restore_state(ckpt_dir, epoch, abstract)
        engine = cls(cfg, mesh, model, state.params)
        del state  # opt_state/step freed: serving holds params only
        master_print(f"serve: params from Orbax checkpoint "
                     f"{ckpt_dir} epoch {epoch}")
        return engine

    @classmethod
    def from_npz(cls, cfg: Config, path: str) -> "InferenceEngine":
        """Restore params from a consolidated .npz export
        (vitax/checkpoint/consolidate.py) — the exact tree comes back through
        the shared flatten/unflatten key convention, then every leaf is
        device_put into its param_specs shard layout.

        A `__quant__`-manifested export loads its int8 leaves AS INT8 on
        device (param_pspec keys off path+shape, so the shard layout is the
        f32 one) with the scale side table replicated; the file's manifest
        is authoritative. --serve_quant_dtype only ASSERTS the expectation —
        pointing a quantized config at an unquantized export fails loudly
        instead of silently serving 4x the HBM."""
        from vitax.checkpoint.consolidate import load_npz_raw, unflatten_tree
        from vitax.parallel.sharding import param_specs, shardings_of
        mesh = build_mesh(cfg)
        model = _build_model(cfg, mesh)
        flat, scales, manifest = load_npz_raw(path)
        want = getattr(cfg, "serve_quant_dtype", "")
        if want and not manifest:
            raise ValueError(
                f"--serve_quant_dtype {want} but {path} has no __quant__ "
                f"manifest; re-export with consolidate.py --dtype {want}")
        params = unflatten_tree(flat)
        shardings = shardings_of(mesh, param_specs(params, cfg, mesh))
        params = jax.tree.map(jax.device_put, params, shardings)
        quant_dtype = ""
        if manifest:
            from vitax.serve.quant import scale_shardings
            quant_dtype = sorted(set(manifest.values()))[0]
            sc_sh = scale_shardings(scales, mesh)
            scales = {k: jax.device_put(v, sc_sh[k])
                      for k, v in scales.items()}
        else:
            scales = {}
        master_print(f"serve: params from consolidated export {path}"
                     + (f" (quantized: {quant_dtype}, "
                        f"{len(scales)} scaled leaves)" if manifest else ""))
        return cls(cfg, mesh, model, params, scales=scales,
                   quant_dtype=quant_dtype)

    # --- compilation ------------------------------------------------------

    def _batch_sharding(self, bucket: int) -> NamedSharding:
        if bucket % self._batch_devices == 0:
            return NamedSharding(self.mesh, batch_pspec())
        return NamedSharding(self.mesh, P())  # replicate sub-mesh buckets

    def _predict_fn(self):
        model, k = self.model, self.topk

        def forward(params, images):
            from vitax.train.step import prepare_images
            logits = model.apply(params, prepare_images(images), True)
            probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
            top_p, top_i = jax.lax.top_k(probs, k)
            return top_i.astype(jnp.int32), top_p

        if not self.scales:
            return forward

        if self._quant_model:
            def predict_quant_model(params, scales, images):
                # QuantDense mode: Dense-site kernels stay quantized all the
                # way into the matmul (fused Pallas kernel and/or int8 x
                # int8 dots — vitax/ops/dequant_matmul.py); their scales
                # merge into the tree as sibling qscale leaves, and only
                # the non-site leaves (the patchify conv) dequantize
                # in-place. VTX-R009 pins the result on the traced jaxpr.
                from vitax.serve.quant import (
                    dense_site_kind, dequantize_tree, merge_quant_scales)
                site = {k: s for k, s in scales.items()
                        if dense_site_kind(k)}
                rest = {k: s for k, s in scales.items()
                        if not dense_site_kind(k)}
                p = dequantize_tree(params, rest)
                return forward(merge_quant_scales(p, site), images)

            return predict_quant_model

        def predict_quant(params, scales, images):
            # dequant INSIDE the jitted program: int8 weights enter as
            # program arguments, `(w * scale).astype(f32)` fuses into each
            # consuming matmul, and no f32 weight tensor outlives the call
            # (VTX-R007 pins this on the lowered args)
            from vitax.serve.quant import dequantize_tree
            return forward(dequantize_tree(params, scales), images)

        return predict_quant

    def _lower_bucket(self, bucket: int):
        """Lower (but do not compile) the predict program for one bucket —
        shared by warmup compilation and the analysis rules, which inspect
        the StableHLO without disturbing compile_count."""
        from vitax.parallel.sharding import param_specs, shardings_of
        batch_sh = self._batch_sharding(bucket)
        param_sh = shardings_of(
            self.mesh, param_specs(self.params, self.cfg, self.mesh))
        s = self.cfg.image_size
        images = jax.ShapeDtypeStruct((bucket, s, s, 3), jnp.uint8,
                                      sharding=batch_sh)
        if self.scales:
            scale_sh = {k: NamedSharding(self.mesh, P())
                        for k in self.scales}
            fn = jax.jit(self._predict_fn(),
                         in_shardings=(param_sh, scale_sh, batch_sh),
                         out_shardings=None)
            lowered = fn.lower(self.params, self.scales, images)
        else:
            fn = jax.jit(self._predict_fn(),
                         in_shardings=(param_sh, batch_sh),
                         out_shardings=None)
            lowered = fn.lower(self.params, images)
        return lowered, batch_sh

    def lower_bucket_mlir(self, bucket: int) -> str:
        """StableHLO text of one bucket's predict program (no compile, no
        compile_count movement) — the VTX-R007 artifact."""
        lowered, _ = self._lower_bucket(bucket)
        return lowered.as_text()

    def trace_bucket_jaxpr(self, bucket: int) -> str:
        """Traced jaxpr text of one bucket's predict program — the VTX-R009
        artifact. Interpret-mode Pallas leaves no custom-call marker in
        StableHLO (the VTX-R008 lesson), so the fused-dequant rule reads the
        jaxpr, where every launch keeps DEQUANT_KERNEL_NAME in its
        pallas_call params and every convert_element_type is visible."""
        s = self.cfg.image_size
        images = jax.ShapeDtypeStruct((bucket, s, s, 3), jnp.uint8)
        fn = self._predict_fn()
        if self.scales:
            jaxpr = jax.make_jaxpr(fn)(self.params, self.scales, images)
        else:
            jaxpr = jax.make_jaxpr(fn)(self.params, images)
        return str(jaxpr)

    def _compile_bucket(self, bucket: int) -> jax.stages.Compiled:
        lowered, batch_sh = self._lower_bucket(bucket)
        compiled = lowered.compile()
        self.compile_count += 1
        self._batch_shardings[bucket] = batch_sh
        return compiled

    def warmup(self) -> Dict[int, float]:
        """AOT-compile every bucket and run each once (first execution pays
        allocator/transfer setup). Returns {bucket: seconds} for the log."""
        timings = {}
        s = self.cfg.image_size
        for b in self.buckets:
            t0 = time.time()
            self._compiled[b] = self._compile_bucket(b)
            zeros = np.zeros((b, s, s, 3), np.uint8)
            idx, probs = self._run(b, zeros)
            jax.block_until_ready((idx, probs))
            timings[b] = time.time() - t0
        self.ready = True
        master_print("serve: warmup compiled buckets "
                     + ", ".join(f"{b}:{t:.2f}s" for b, t in timings.items()))
        return timings

    # --- inference --------------------------------------------------------

    def _run(self, bucket: int, images: np.ndarray):
        batch = jax.device_put(images, self._batch_shardings[bucket])
        if self.scales:
            return self._compiled[bucket](self.params, self.scales, batch)
        return self._compiled[bucket](self.params, batch)

    def predict(self, images: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """(n, H, W, 3) uint8 -> (top-k class ids (n, k) int32,
        top-k probs (n, k) float32). Pads to the next bucket; the padded
        rows' outputs are discarded. Only precompiled buckets execute —
        an unseen shape raises instead of silently recompiling."""
        faults.fire("engine_predict")
        n = images.shape[0]
        bucket = next_bucket(n, self.buckets)
        assert bucket in self._compiled, (
            f"bucket {bucket} not warmed up — call warmup() before serving")
        if n < bucket:
            padded = np.zeros((bucket,) + images.shape[1:], images.dtype)
            padded[:n] = images
            images = padded
        top_i, top_p = self._run(bucket, images)
        return np.asarray(top_i)[:n], np.asarray(top_p)[:n]
