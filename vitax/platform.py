"""Backend platform selection helpers.

Import-order-sensitive: call `force_cpu_if_requested()` before anything queries
`jax.devices()`. Under an experimental TPU plugin (axon), the JAX_PLATFORMS env
var alone does not stop the plugin from claiming the backend — the config flag
set before first backend init does.
"""

import os

import jax


def is_cpu_forced() -> bool:
    """Whether this process is pinned to host CPU (JAX_PLATFORMS=cpu)."""
    return os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu"


def force_cpu_if_requested() -> None:
    """Honor JAX_PLATFORMS=cpu even when a TPU plugin would claim the backend."""
    if is_cpu_forced():
        jax.config.update("jax_platforms", "cpu")
