"""Backend platform selection helpers.

Import-order-sensitive: call `force_cpu_if_requested()` before anything queries
`jax.devices()`. Under an experimental TPU plugin (axon), the JAX_PLATFORMS env
var alone does not stop the plugin from claiming the backend — the config flag
set before first backend init does.
"""

import os

import jax


def force_cpu_if_requested() -> None:
    """Honor JAX_PLATFORMS=cpu even when a TPU plugin would claim the backend."""
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        jax.config.update("jax_platforms", "cpu")
