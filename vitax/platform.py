"""Backend platform selection helpers.

Import-order-sensitive: call `force_cpu_if_requested()` before anything queries
`jax.devices()`. Under an experimental TPU plugin (axon), the JAX_PLATFORMS env
var alone does not stop the plugin from claiming the backend — the config flag
set before first backend init does.
"""

import os

import jax


def is_cpu_forced() -> bool:
    """Whether this process is pinned to host CPU (JAX_PLATFORMS=cpu)."""
    return os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu"


def force_cpu_if_requested() -> None:
    """Honor JAX_PLATFORMS=cpu even when a TPU plugin would claim the backend."""
    if is_cpu_forced():
        jax.config.update("jax_platforms", "cpu")


def backend_platform() -> str:
    """Platform name ("cpu"/"tpu"/"gpu") of the default backend.

    The sanctioned single query point: library code should call this (or
    `device_kind()`) instead of `jax.devices()[0].platform`, so that backend
    selection stays a process-level decision made here.
    """
    return jax.devices()[0].platform  # vtx: ignore[VTX104] sanctioned single query point


def device_kind() -> str:
    """Hardware kind of the default backend's first device (e.g. "TPU v4")."""
    return jax.devices()[0].device_kind  # vtx: ignore[VTX104] sanctioned single query point
