"""Ulysses-style sequence parallelism: all-to-all head<->token resharding.

The second sequence-parallel strategy alongside ring attention
(vitax/parallel/ring_attention.py); both are capability beyond the reference,
which has no sequence scaling at all (SURVEY.md section 5, 'long-context:
absent'). Selected with --sp_impl ulysses.

Scheme (DeepSpeed-Ulysses, arXiv:2309.14509): activations arrive sharded over
the token axis ("sp"). One all-to-all converts token-sharded to head-sharded —
each chip then holds ALL tokens for H/sp of the heads — attention runs locally
(dense, or whole-N/streaming Pallas kernels on TPU since each chip sees the
full sequence), and a second all-to-all restores token sharding.

Trade-off vs ring: two all-to-alls move activations once each way (cheap on
ICI's all-to-all bandwidth) and the inner attention is a plain local kernel
(no per-step ppermute latency on the critical path), but head count must be
divisible by sp * tp, and each chip must fit the full-sequence K/V for its
head slice — ring keeps only O(N/sp) K/V resident. Heads shard over
sp AND tp jointly here; batch stays on (dp, fsdp).
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from vitax.ops.attention import reference_attention
from vitax.parallel.mesh import BATCH_AXES, shard_map


def _ulysses_local(q, k, v, inner: Callable, axis_name: str):
    """shard_map body. q, k, v: (B, N/sp, H/tp, Dh) local shards.

    all_to_all over sp: scatter the head axis, gather the token axis ->
    (B, N, H/(tp*sp), Dh); local full-sequence attention; inverse all_to_all.
    q/k/v are stacked so the inbound reshard is ONE collective, not three
    (XLA does not reliably merge distinct all-to-alls).
    """
    qkv = jnp.stack([q, k, v])  # (3, B, N/sp, H, Dh)
    qkv = jax.lax.all_to_all(
        qkv, axis_name, split_axis=3, concat_axis=2, tiled=True)
    o = inner(qkv[0], qkv[1], qkv[2])
    return jax.lax.all_to_all(  # (B, N, H/sp, Dh) -> (B, N/sp, H, Dh)
        o, axis_name, split_axis=1, concat_axis=2, tiled=True)


def make_ulysses_attention(mesh: Mesh, inner: Optional[Callable] = None,
                           axis_name: str = "sp"):
    """Build a (B, N, H, Dh) -> (B, N, H, Dh) attention core with tokens
    sharded over `axis_name` outside, heads sharded over it inside.

    `inner` computes full-sequence attention on the per-chip head slice
    ((B, N, H_local, Dh) -> same); defaults to the dense jnp core. Requires
    num_heads % (sp * tp) == 0 (checked by the caller,
    vitax.ops.attention.make_attention_impl).
    """
    spec = P(BATCH_AXES, axis_name, "tp", None)
    inner = inner if inner is not None else reference_attention

    def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array) -> jax.Array:
        fn = shard_map(
            functools.partial(_ulysses_local, inner=inner, axis_name=axis_name),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )
        return fn(q, k, v)

    return ulysses_attention


def make_ulysses_dropout(mesh: Mesh, inner_drop: Callable,
                         axis_name: str = "sp"):
    """Ulysses attention with in-kernel attention dropout (round 5): the
    resharded inner kernel sees the FULL sequence on its head slice, so the
    whole-N/streaming dropout kernels apply directly. Each shard holds a
    DIFFERENT (head-slice, batch-shard) of the problem but the same local
    (b, h) block indices, so the linearized shard position is folded into
    the seed (fold_shard_seed — the one fold idiom shared with attention.py)
    — distinct masks per shard, deterministic given (seed, step).

    inner_drop: (q, k, v, seed) -> o on local (B, N, H_local, Dh)."""
    from vitax.ops.attention import fold_shard_seed

    spec = P(BATCH_AXES, axis_name, "tp", None)
    shard_axes = tuple(a for a in (*BATCH_AXES, axis_name, "tp")
                       if mesh.shape.get(a, 1) > 1)

    def body(q, k, v, seed):
        # the a2a choreography is _ulysses_local's — one copy of the layout
        # the dropout oracle test pins (tests/test_ulysses.py)
        seed = fold_shard_seed(mesh, shard_axes, seed)
        return _ulysses_local(
            q, k, v, inner=lambda a, b, c: inner_drop(a, b, c, seed),
            axis_name=axis_name)

    def ulysses_dropout(q, k, v, seed):
        fn = shard_map(
            body, mesh=mesh,
            in_specs=(spec, spec, spec, P()), out_specs=spec,
            check_vma=False,
        )
        return fn(q, k, v, seed)

    return ulysses_dropout


def make_ulysses_dropout_pp(inner_drop: Callable, axis_name: str = "sp"):
    """Ulysses dropout for use INSIDE the pipeline body (pp x sp, tp=1):
    the local a2a body with the in-kernel dropout inner. No seed fold here —
    the pipeline body's per-(tick, layer, shard) keys already decorrelate
    across sp shards (vitax/parallel/pipeline.py shard_idx), and each sp
    shard computes a DISJOINT head slice after the a2a."""
    def body(q, k, v, seed):
        return _ulysses_local(
            q, k, v, inner=lambda a, b, c: inner_drop(a, b, c, seed),
            axis_name=axis_name)
    return body


def make_ulysses_attention_pp(inner: Optional[Callable] = None,
                              axis_name: str = "sp", with_tp: bool = False):
    """Ulysses attention for use INSIDE the pipeline body (pp x sp).

    The pipeline shard_map manualizes "sp" itself (vitax/parallel/pipeline.py
    — a NESTED shard_map would hoist its closure constants into
    manual-computation wrappers whose all-axes sharding encodings Shardy
    rejects in jax 0.9), so this is the LOCAL all-to-all body called
    directly in the already-manual region. With tp active (a GSPMD-auto axis
    in the body), the inner full-sequence attention must be the dense einsum
    path — GSPMD partitions it over the tp-global head dim; a Pallas kernel
    cannot be auto-partitioned."""
    inner = (reference_attention if (inner is None or with_tp) else inner)
    return functools.partial(_ulysses_local, inner=inner,
                             axis_name=axis_name)
