"""Declarative tree-path sharding rules: ordered regex -> PartitionSpec table.

This is the scalax `TreePathShardingRule` shape (SNIPPETS.md §1-3) applied to
vitax's mesh: each parameter's "/"-joined tree path is matched against an
ORDERED rule table (first match wins, strict — an unmatched path raises), and
the matching rule names the structural placement class:

- COLUMN  Megatron column-parallel: output dim (ndim-1) over "tp" (qkv, fc1 —
          kernel AND bias, a bias's only dim is its output dim)
- ROW     Megatron row-parallel: input dim (ndim-2) over "tp" (attn proj / fc2
          kernels only; their biases follow the default rule)
- EXPERT  GShard expert weights: the (E, ...) experts dim over "ep"
- None    default dense leaf: no tp/ep placement

On top of the matched class the resolver applies the placements that are
shape/mesh-dependent and therefore cannot live in a static table:

- the scanned stacked-layers dim (dim 0 of `blocks` leaves under
  --scan_blocks) goes to "pp" when pipelined and is otherwise never sharded;
- ZeRO-3 puts "fsdp" on the largest remaining dim divisible by the axis size.

The table + resolver reproduce `parallel/sharding.py:param_pspec` exactly —
pinned leaf-for-leaf across the dp/zero2/zero3/tp/pp/ep arms by
tests/test_programs.py. `param_pspec` stays as the reference dispatcher the
pin compares against; live spec construction (`sharding.param_specs`) routes
through this table.

Scalar exemption (scalax idiom): 0-dim and total-size-1 leaves skip matching
entirely and replicate — there is nothing to shard and no rule to demand.
"""

from __future__ import annotations

import dataclasses
import math
import re
from typing import Any, Optional, Tuple

from jax.sharding import PartitionSpec as P

from vitax.config import Config

PyTree = Any

# mesh axis order every (dp, fsdp, tp, sp, pp, ep) tuple in this module uses
MESH_AXES = ("dp", "fsdp", "tp", "sp", "pp", "ep")

# placement classes a rule can declare
COLUMN = "column"   # "tp" on the output dim (ndim-1)
ROW = "row"         # "tp" on the input dim (ndim-2)
EXPERT = "expert"   # "ep" on the experts dim (first shardable dim)


@dataclasses.dataclass(frozen=True)
class PathRule:
    """One ordered table entry: regex over the '/'-joined param path."""
    name: str
    pattern: str
    placement: Optional[str] = None  # COLUMN | ROW | EXPERT | None

    def matches(self, path: str) -> bool:
        return re.search(self.pattern, path) is not None


# Ordered: first match wins. The final entry is NOT a catch-all — it
# enumerates the generic dense leaf names (kernel/bias/scale/pos_embed), so a
# new parameter class fails loudly here instead of silently replicating.
RULE_TABLE: Tuple[PathRule, ...] = (
    PathRule("moe-expert-weights",
             r"(^|/)moe/(?:.*/)?(w1|b1|w2|b2)$", EXPERT),
    PathRule("megatron-column-qkv-fc1",
             r"(^|/)(qkv|fc1)(/|$)", COLUMN),
    PathRule("megatron-row-attn-proj",
             r"(^|/)attn/(?:.*/)?proj/kernel$", ROW),
    PathRule("megatron-row-fc2",
             r"(^|/)fc2/kernel$", ROW),
    PathRule("dense-default",
             r"(^|/)(kernel|bias|scale|pos_embed|embedding)$", None),
)


def match_rule(path: str, table: Tuple[PathRule, ...] = RULE_TABLE) -> PathRule:
    """First matching rule for a '/'-joined param path; strict (raises)."""
    for r in table:
        if r.matches(path):
            return r
    raise ValueError(f"Partition rule not found for param: {path}")


def _leaf_path_names(path) -> Tuple[str, ...]:
    # jax KeyPath entries -> plain names (same shape as sharding._path_names;
    # duplicated here so rules.py stays below sharding.py in the import graph)
    names = []
    for p in path:
        if hasattr(p, "key"):
            names.append(str(p.key))
        elif hasattr(p, "name"):
            names.append(str(p.name))
        elif hasattr(p, "idx"):
            names.append(str(p.idx))
        else:
            names.append(str(p))
    return tuple(names)


def rule_pspec(
    names: Tuple[str, ...],
    shape: Tuple[int, ...],
    cfg: Config,
    mesh_shape: Tuple[int, ...],  # (dp, fsdp, tp, sp, pp, ep)
    scanned: bool,
    table: Tuple[PathRule, ...] = RULE_TABLE,
) -> P:
    """Resolve one parameter's PartitionSpec from the rule table."""
    _, fsdp, tp, _, pp, ep = mesh_shape
    ndim = len(shape)

    # scalar exemption: nothing to shard, no rule required
    if ndim == 0 or math.prod(shape) == 1:
        return P(*([None] * ndim))

    rule = match_rule("/".join(names), table)
    spec: list = [None] * ndim

    is_scanned_block = scanned and "blocks" in names
    first_shardable = 1 if is_scanned_block else 0

    if pp > 1 and is_scanned_block:
        assert shape[0] % pp == 0, (
            f"pp: stacked layer dim {shape[0]} of {names} not divisible by "
            f"pp={pp}")
        spec[0] = "pp"

    if ep > 1 and rule.placement == EXPERT:
        e_dim = first_shardable
        assert shape[e_dim] % ep == 0, (
            f"ep: experts dim {e_dim} of {names} {shape} not divisible by "
            f"ep={ep}")
        spec[e_dim] = "ep"
        first_shardable = e_dim + 1

    if tp > 1 and rule.placement in (COLUMN, ROW):
        tp_dim = ndim - 1 if rule.placement == COLUMN else ndim - 2
        if tp_dim >= first_shardable:
            assert shape[tp_dim] % tp == 0, (
                f"TP: dim {tp_dim} of {names} {shape} not divisible by tp={tp}")
            spec[tp_dim] = "tp"

    if fsdp > 1 and not cfg.run_without_fsdp:
        # largest free dim divisible by the fsdp axis (ZeRO-3); small
        # indivisible params stay replicated
        candidates = [
            (shape[d], d) for d in range(first_shardable, ndim)
            if spec[d] is None and shape[d] % fsdp == 0 and shape[d] >= fsdp
        ]
        if candidates:
            _, d = max(candidates)
            spec[d] = "fsdp"

    return P(*spec)


def specs_from_rules(
    abstract_params: PyTree,
    cfg: Config,
    mesh,
    table: Tuple[PathRule, ...] = RULE_TABLE,
) -> PyTree:
    """PartitionSpec tree for an (abstract) param tree via the rule table."""
    import jax

    mesh_shape = tuple(mesh.shape[a] for a in MESH_AXES)
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: rule_pspec(
            _leaf_path_names(path), leaf.shape, cfg, mesh_shape,
            cfg.scan_blocks, table),
        abstract_params,
    )


def describe_table(table: Tuple[PathRule, ...] = RULE_TABLE) -> str:
    """Human-readable rule table (README / debugging)."""
    rows = [f"  {r.name:28s} {r.pattern:44s} -> {r.placement or 'default'}"
            for r in table]
    return "\n".join(rows)
