"""1F1B pipeline schedule: interleaved forward/backward over the "pp" axis.

GPipe (vitax/parallel/pipeline.py) runs all M forward microbatches, then
autodiff replays them backward; 1F1B (Narayanan et al., PipeDream-Flush /
Megatron-LM) interleaves: once stage s has run its warmup forwards, each
tick performs ONE forward and ONE backward, bounding in-flight microbatch
activations at ~2(S-s) per stage instead of the full M+S-1 tick carries.

MEASURED VERDICT (tools/pp_schedule_ab.py, PP_AB.json, 8-device CPU mesh):
in THIS framework the classic 1F1B memory win does not materialize, and
GPipe stays the default. Two reasons, both structural: (1) the pipeline
always runs recompute-everything remat, so GPipe's saved state is already
just the (mb, N, D) tick carries — the per-layer activations 1F1B exists to
evict are never stored in the first place; (2) at fixed global batch,
microbatches shrink as M grows, so both schedules' live sets are O(batch),
flat in M (measured: GPipe 16.7-21.1 MB temp vs 1F1B 17.1-26.3 MB across
M=2..16). Meanwhile the lockstep-SPMD 1F1B tick pays the tail (norm + head
+ loss) on EVERY stage every tick (garbage off the last stage) plus a
second ppermute — measured ~30% step-time overhead. The schedule is kept
selectable (--pp_schedule 1f1b) as the correctness-proven foundation for
the regime where it does pay: no-remat pipelines or M scaling the global
batch (gradient-accumulation style), where per-mb residuals are large and
fixed-size.

TPU-first formulation, lockstep SPMD inside one `jax.shard_map`:

- tick t, stage s: forward of microbatch f = t - s (valid when 0 <= f < M),
  and backward of microbatch b = t - (2S - 2 - s) (valid when 0 <= b < M) —
  the standard 1F1B timetable collapsed onto a single program counter;
  invalid slots compute masked garbage (cf. GPipe's bubble ticks). Total
  ticks: M + 2S - 2.
- The LAST stage closes the loop in-tick: its forward feeds norm + mean-pool
  + head + CE loss immediately, and the loss's cotangent seeds that same
  microbatch's backward — which is why forward and backward can interleave
  at all (the loss lives inside the pipelined region, unlike GPipe's).
- Backward recomputes the stage forward under `jax.vjp` from the SAVED STAGE
  INPUT (a ring buffer of 2S+1 slots — the +1 is a trash slot for masked
  writes). This is the reference checkpoint_module semantics
  (none_saveable): store one (mb, N, D) input per in-flight microbatch,
  recompute everything else.
- Activations hop forward (stage s -> s+1) and cotangents hop backward
  (s -> s-1) as two `ppermute`s per tick, both overlapped with compute by
  XLA's scheduler.
- ZeRO-3 composes exactly as in GPipe: block shards all-gather just-in-time
  inside the (recomputed) stage forward; `jax.vjp` transposes the gather to
  a reduce-scatter, so weight cotangents land back on the "fsdp" shards.
  The head/norm params are gathered the same way. dp/ep replication is
  closed with explicit psums on the accumulated grads.

v1 scope: dense blocks, no dropout (config.validate enforces both) — the
schedule is the point; the GPipe body keeps those features.

Scale limit, PER BACKEND (round 5 update of the round-4 note): on TPU the
stage forward remats per block (`_remat_blocks`), so `jax.vjp(stage_fwd)`
saves one (mb, N, D) carry per layer and re-runs the ZeRO-3 gathers in the
backward — GPipe's just-in-time memory semantics at the 10B shape (proven
by AOT-compiling this engine against a v5p topology,
tools/aot_topology.py --configs 10b_1f1b / AOT_TOPOLOGY.json). On the CPU
backend the per-block checkpoint stays OUT: the jax-0.9 CPU compiler
intermittently aborts on the rematted vjp-inside-shard_map structure
(re-reproduced round 5, ~1-in-3 across repeated 1f1b test runs), so CPU
compiles save gathered layer weights (~35 GB at the 10B pp2 x fsdp4
shape) — immaterial at the toy shapes CPU actually runs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from vitax.config import Config
from vitax.parallel.mesh import BATCH_AXES, optimization_barrier, shard_map
from vitax.parallel.pipeline import _gather_over

import optax


def _remat_blocks(mesh: Mesh) -> bool:
    """Whether the 1F1B stage forward remats per block — decided by the
    COMPILE TARGET's platform (see the stage_fwd comment: the CPU XLA
    backend intermittently aborts on the rematted engine; TPU compiles it)."""
    return next(iter(mesh.devices.flat)).platform == "tpu"


def make_1f1b_value_and_grad(cfg: Config, model, mesh: Mesh, state_specs):
    """(params, batch) -> (loss, grads): the full fwd+bwd of the ViT under
    the 1F1B schedule. Drop-in for jax.value_and_grad(loss_fn) in
    make_train_step when --pp_schedule 1f1b.

    `state_specs.params` provides the PartitionSpec tree (P("pp", ...) on
    blocks, optional "fsdp" dims everywhere) used for the shard_map specs
    and the just-in-time gathers.
    """
    from vitax.models.vit import Block, apply_embed, apply_tail

    S = mesh.shape["pp"]
    M = cfg.pp_microbatches or S
    assert cfg.num_blocks % S == 0, (cfg.num_blocks, S)
    Lps = cfg.num_blocks // S
    W = 2 * S + 1  # ring capacity 2S in-flight + one trash slot
    dp_like = mesh.shape["dp"] * mesh.shape["fsdp"] * mesh.shape["ep"]
    assert cfg.batch_size % (dp_like * M) == 0, (
        f"batch {cfg.batch_size} must divide by data-axes*microbatches "
        f"({dp_like}*{M})")

    bk = model.block_kwargs()
    bk["attention_impl"] = getattr(
        bk["attention_impl"], "vitax_local_impl", bk["attention_impl"])
    bk["token_sharding"] = None
    bk["moe_dispatch_sharding"] = None
    block = Block(**bk)
    dtype = model.dtype

    param_specs = state_specs.params["params"]
    block_specs = param_specs["blocks"]
    is_spec = lambda x: isinstance(x, P)  # noqa: E731
    layer_specs = jax.tree.map(lambda s: P(*s[1:]), block_specs,
                               is_leaf=is_spec)
    tail_specs = {"norm": param_specs["norm"], "head": param_specs["head"]}

    def stage_fwd(stage_params, x):
        def one_block(carry, layer_params):
            if mesh.shape["fsdp"] > 1:
                # pin the gather inside the (rematted) scan iteration: XLA
                # LICM otherwise hoists loop-invariant all-gathers out of
                # the loop, materializing every layer's gathered weights at
                # once (the GPipe body's idiom, vitax/parallel/pipeline.py)
                layer_params, carry = optimization_barrier(
                    (layer_params, carry))
                layer_params = jax.tree.map(
                    lambda s, p: _gather_over(p, s, "fsdp"),
                    layer_specs, layer_params, is_leaf=is_spec)
            return block.apply({"params": layer_params}, carry, True), None
        # per-block checkpoint, TPU ONLY (round 5): jax.vjp(stage_fwd)
        # otherwise saves every layer's GATHERED weights as scan residuals
        # (~35 GB at the 10B pp2 x fsdp4 shape vs GPipe's 13 GB). With the
        # block rematted, the residual is one (mb, N, D) carry per layer and
        # the gather re-runs in the backward — GPipe's just-in-time
        # semantics. The gate is the COMPILE TARGET (mesh devices), not the
        # host: the round-4 intermittent XLA abort re-reproduced under jax
        # 0.9 on the CPU backend (1-in-~3 across repeated
        # tests/test_pipeline.py 1f1b runs — a CPU-compiler bug on this
        # engine's vjp-in-shard_map structure), while the TPU compiler
        # handles it (proven by AOT-compiling this engine at the 10B shape
        # against a v5p topology: tools/aot_topology.py --configs 10b_1f1b,
        # AOT_TOPOLOGY.json temp bytes ~ GPipe level).
        if _remat_blocks(mesh):
            one_block = jax.checkpoint(one_block, prevent_cse=False)
        y, _ = jax.lax.scan(one_block, x, stage_params,
                            unroll=min(cfg.scan_unroll, Lps))
        return y

    def tail_loss(tail_params, y, labels_mb):
        """norm + mean-pool + head + CE on one microbatch, normalized by the
        GLOBAL batch size so per-mb cotangents add up to the global-mean
        loss gradient."""
        if mesh.shape["fsdp"] > 1:
            tail_params = jax.tree.map(
                lambda s, p: _gather_over(p, s, "fsdp"),
                tail_specs, tail_params, is_leaf=is_spec)
        logits = apply_tail(tail_params, y, num_classes=cfg.num_classes,
                            dtype=dtype)
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, labels_mb)
        return jnp.sum(ce) / cfg.batch_size

    def pipeline_body(stage_params, tail_params, x, labels):
        s = jax.lax.axis_index("pp")
        b_loc = x.shape[0]
        mb = b_loc // M
        mbs = x.reshape(M, mb, *x.shape[1:])
        lbs = labels.reshape(M, mb)
        fwd_perm = [(i, (i + 1) % S) for i in range(S)]
        bwd_perm = [(i, (i - 1) % S) for i in range(S)]

        # f32 accumulators regardless of param dtype: under the comm-precision
        # cast (vitax/parallel/sharding.py cast_to_compute) stage params — and
        # so the per-tick cotangents — are bf16; accumulating ticks in bf16
        # would lose low bits. At f32 params the astype below is a no-op and
        # the program is unchanged.
        def _grad_zeros(p):
            z_dtype = (jnp.float32 if jnp.issubdtype(p.dtype, jnp.floating)
                       else p.dtype)
            return jnp.zeros(p.shape, z_dtype)

        g_stage0 = jax.tree.map(_grad_zeros, stage_params)
        g_tail0 = jax.tree.map(_grad_zeros, tail_params)
        buf0 = jnp.zeros((W, mb, *x.shape[1:]), x.dtype)

        def tick(carry, t):
            ring, fmsg, bmsg, g_stage, g_tail, loss_acc = carry

            # ---- forward of microbatch f = t - s ----
            f = t - s
            valid_f = jnp.logical_and(f >= 0, f < M)
            inj = jax.lax.dynamic_index_in_dim(
                mbs, jnp.clip(f, 0, M - 1), 0, keepdims=False)
            x_in = jnp.where(s == 0, inj, fmsg)
            # save the stage input for the recompute-backward; invalid ticks
            # write the trash slot so they can never clobber a live one
            slot = jnp.where(valid_f, f % (W - 1), W - 1)
            ring = jax.lax.dynamic_update_index_in_dim(ring, x_in, slot, 0)
            y = stage_fwd(stage_params, x_in)

            # ---- last stage: tail fwd + loss + cotangent seed (same tick:
            # t_b(S-1, m) == t_f(S-1, m) == S-1+m) ----
            lb = jax.lax.dynamic_index_in_dim(
                lbs, jnp.clip(f, 0, M - 1), 0, keepdims=False)
            loss_mb, tail_vjp = jax.vjp(tail_loss, tail_params, y, lb)
            g_tail_tick, y_cot_seed, _ = tail_vjp(jnp.float32(1.0))
            at_tail = jnp.logical_and(s == S - 1, valid_f)
            loss_acc = loss_acc + jnp.where(at_tail, loss_mb, 0.0)
            g_tail = jax.tree.map(
                lambda a, g: a + jnp.where(at_tail, g, 0.0).astype(a.dtype),
                g_tail, g_tail_tick)

            # ---- backward of microbatch b = t - (2S - 2 - s) ----
            b = t - (2 * S - 2 - s)
            valid_b = jnp.logical_and(b >= 0, b < M)
            cot_in = jnp.where(s == S - 1, y_cot_seed.astype(x.dtype), bmsg)
            x_saved = jax.lax.dynamic_index_in_dim(
                ring, jnp.where(valid_b, b % (W - 1), W - 1), 0,
                keepdims=False)
            _, stage_vjp = jax.vjp(stage_fwd, stage_params, x_saved)
            g_stage_tick, dx = stage_vjp(cot_in)
            g_stage = jax.tree.map(
                lambda a, g: a + jnp.where(valid_b, g, 0.0).astype(a.dtype),
                g_stage, g_stage_tick)
            dx_out = jnp.where(jnp.logical_and(s == 0, valid_b), dx, 0.0)

            # ---- ICI hops: activations forward, cotangents backward ----
            if S > 1:
                fmsg = jax.lax.ppermute(y, "pp", fwd_perm)
                bmsg = jax.lax.ppermute(dx, "pp", bwd_perm)
            else:
                fmsg, bmsg = y, dx
            return (ring, fmsg, bmsg, g_stage, g_tail, loss_acc), dx_out

        zeros_msg = jnp.zeros((mb, *x.shape[1:]), x.dtype)
        T = M + 2 * S - 2
        (_, _, _, g_stage, g_tail, loss_acc), dxs = jax.lax.scan(
            tick,
            (buf0, zeros_msg, zeros_msg, g_stage0, g_tail0,
             jnp.float32(0.0)),
            jnp.arange(T))

        # the stage-0 embed cotangent for mb m was emitted at tick 2S-2+m;
        # only stage 0 wrote nonzero there — slice the M live ticks FIRST,
        # then psum over "pp" (pipeline.py's outs idiom: don't all-reduce
        # the warmup ticks' zeros)
        x_cot = jax.lax.psum(dxs[2 * S - 2:2 * S - 2 + M], "pp")
        x_cot = x_cot.reshape(b_loc, *x.shape[1:])

        # close the data-parallel replication: dp/ep (and, for leaves with
        # no "fsdp"-sharded dim, fsdp too — that axis carries batch) saw
        # different data. Leaves WITH an "fsdp" dim were already summed over
        # fsdp by the gather transposes (psum_scatter) inside the vjps.
        def close_replicas(spec, g):
            axes = {a for part in spec if part is not None
                    for a in (part if isinstance(part, tuple) else (part,))}
            names = ("dp", "ep") + (() if "fsdp" in axes else ("fsdp",))
            return jax.lax.psum(g, names)

        g_stage = jax.tree.map(close_replicas, block_specs, g_stage,
                               is_leaf=is_spec)
        g_tail = jax.tree.map(close_replicas, tail_specs,
                              jax.lax.psum(g_tail, "pp"), is_leaf=is_spec)
        loss = jax.lax.psum(jax.lax.psum(loss_acc, "pp"),
                            ("dp", "fsdp", "ep"))
        return g_stage, g_tail, x_cot, loss

    act_spec = P(BATCH_AXES, None, None)
    label_spec = P(BATCH_AXES)

    def value_and_grad(params, batch, labels):
        p = params["params"]

        def embed_fn(embed_params):
            return apply_embed(embed_params, batch,
                               patch_size=cfg.patch_size,
                               embed_dim=cfg.embed_dim, dtype=dtype)

        embed_params = {"patch_embed": p["patch_embed"],
                        "pos_embed": p["pos_embed"]}
        x, embed_vjp = jax.vjp(embed_fn, embed_params)

        run = shard_map(
            pipeline_body, mesh=mesh,
            in_specs=(block_specs, tail_specs, act_spec, label_spec),
            out_specs=(block_specs, tail_specs, act_spec, P()),
            check_vma=False)
        tail_params = {"norm": p["norm"], "head": p["head"]}
        g_blocks, g_tail, x_cot, loss = run(
            p["blocks"], tail_params, x, labels)
        (g_embed,) = embed_vjp(x_cot.astype(x.dtype))

        grads = {"params": {
            "patch_embed": g_embed["patch_embed"],
            "pos_embed": g_embed["pos_embed"],
            "blocks": g_blocks,
            "norm": g_tail["norm"],
            "head": g_tail["head"],
        }}
        return loss, grads

    return value_and_grad
