"""Ring attention: sequence/context parallelism over the "sp" mesh axis.

Long-context capability beyond the reference (which fixes sequence length at
(image/patch)^2 = 256 tokens and scales only parameters — SURVEY.md section 5
'long-context: absent'): activations are sharded over the token axis, and
attention streams K/V blocks around the ring of "sp" neighbors via
`jax.lax.ppermute` (one ICI hop per step), merging per-block results with a
logsumexp merge (blockwise attention a la Ring Attention, arXiv:2310.01889).
Peak memory per chip is O(N/sp) activations and one K/V block; the (N, N)
score matrix never exists.

Design (TPU-first):
- The sp-step block loop is UNROLLED (sp is a mesh-axis size — small and
  static), and each step issues the K/V rotation for the NEXT block *before*
  computing the current one. The rotation has no data dependence on the block
  product, so XLA's latency-hiding scheduler turns each collective-permute
  into a start/done pair overlapped with the MXU work — double buffering,
  scheduled by the compiler.
- Exactly sp-1 rotations TOTAL: K and V ride one stacked buffer so each ring
  step is a single collective-permute (XLA does not reliably merge distinct
  ppermutes — the ulysses.py lesson), and the last block computes without a
  permute (there is no next block to fetch).
- The local block product runs on the Pallas kernels on TPU, selected by the
  same policy cascade as full-sequence dispatch
  (vitax/ops/attention.py:_select_path): the 4D whole-N kernel when a legal
  head grouping fits VMEM (no HBM relayouts — these would otherwise run once
  per ring step per tensor), the BH whole-N kernel as fallback, the
  streaming (blocked) kernel past MAX_SEQ_IN_VMEM local tokens. All return
  (o, lse) differentiable in both, so the merge is plain autodiff. Off-TPU
  (CPU tests) the dense jnp block product is used.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from vitax.parallel.mesh import BATCH_AXES, axis_size, shard_map
from vitax.platform import backend_platform


def _dense_block(q, k, v, scale: float):
    """Dense jnp block product: q (B, nq, H, Dh) x k/v (B, nk, H, Dh) ->
    (o (B, nq, H, Dh) f32 softmax-normalized within the block, lse (B, H, nq))."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bhqk,bkhd->bqhd", p / l, v.astype(jnp.float32))
    lse = (m + jnp.log(l))[..., 0]  # (B, H, nq)
    return o, lse


def _kernel_block(q, k, v, scale: float):
    """Pallas block product via the shared with-lse kernel selector
    (vitax/ops/attention.py:block_kernel_with_lse — ONE policy site): 4D
    whole-N kernel when the local shape has a legal head grouping (no HBM
    relayouts, which would otherwise run once per ring step per tensor), BH
    whole-N fallback, streaming kernel past MAX_SEQ_IN_VMEM. All are
    differentiable in both outputs (the merge is plain autodiff)."""
    from vitax.ops.attention import block_kernel_with_lse

    b, nq, h, dh = q.shape
    kern = block_kernel_with_lse(nq, h, dh, q.dtype.itemsize)
    o, lse = kern(q, k, v, scale)
    return o.astype(jnp.float32), lse


def _merge(o, lse, o_blk, lse_blk):
    """Combine two softmax-normalized partial results via their logsumexps."""
    lse_new = jnp.logaddexp(lse, lse_blk)                    # (B, H, N)
    w = jnp.exp(lse - lse_new).transpose(0, 2, 1)[..., None]         # (B,N,H,1)
    w_blk = jnp.exp(lse_blk - lse_new).transpose(0, 2, 1)[..., None]
    return o * w + o_blk * w_blk, lse_new


def _ring_attention_local(q, k, v, *, axis_name: str, scale: float,
                          block_fn: Callable, step_args: Callable = None):
    """shard_map body. q, k, v: (B, N_loc, H, Dh) — the local token shard.
    Streams K/V blocks around the ring; each device visits all sp blocks.

    step_args(step) -> tuple of extra positional args appended to each
    block_fn call (the dropout path's per-step seedvec); None for the plain
    (q, k, v, scale) products. ONE copy of the ring machinery — the
    prefetch-before-compute ordering below is load-bearing for the
    latency hiding described in the module docstring."""
    sp = axis_size(axis_name)
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    # K and V ride ONE stacked (2, B, N_loc, H, Dh) buffer so each ring step
    # issues a SINGLE collective-permute — XLA does not reliably merge
    # distinct ppermutes into one transfer (the same lesson as ulysses.py's
    # stacked all-to-all), and two hops per step means two latencies to hide
    kv_blk = jnp.stack([k, v])
    o = lse = None
    for step in range(sp):
        last = step == sp - 1
        if not last:
            # issue the rotation BEFORE the block product — no data dependence,
            # so the collective-permute overlaps the MXU work (double buffer)
            kv_nxt = jax.lax.ppermute(kv_blk, axis_name, perm)
        extra = () if step_args is None else step_args(step)
        o_blk, lse_blk = block_fn(q, kv_blk[0], kv_blk[1], scale, *extra)
        o_blk = o_blk.astype(jnp.float32)
        o, lse = (o_blk, lse_blk) if o is None else _merge(o, lse, o_blk, lse_blk)
        if not last:
            kv_blk = kv_nxt
    return o.astype(q.dtype)


def make_ring_attention(mesh: Mesh, axis_name: str = "sp",
                        use_kernel: Optional[bool] = None):
    """Build a (B, N, H, Dh) -> (B, N, H, Dh) attention core with the token
    axis sharded over `axis_name`; batch over (dp, fsdp), heads over tp.

    use_kernel: True -> Pallas block product (interpret mode off-TPU),
    False -> dense jnp, None -> Pallas exactly on TPU.
    """
    if use_kernel is None:
        use_kernel = backend_platform() == "tpu"
    block_fn = _kernel_block if use_kernel else _dense_block
    spec = P(BATCH_AXES, axis_name, "tp", None)

    def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array) -> jax.Array:
        scale = q.shape[-1] ** -0.5
        fn = shard_map(
            functools.partial(_ring_attention_local, axis_name=axis_name,
                              scale=scale, block_fn=block_fn),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )
        return fn(q, k, v)

    return ring_attention


def _dense_block_drop(q, k, v, seedvec, scale: float, rate: float):
    """Dense jnp block product with the shared counter-hash dropout mask at
    GLOBAL coordinates (seedvec = [seed, q0, k0]); numerator masked, l/lse
    unmasked, (1-rate) folded per block — linear, so the merge of per-block
    results equals dense softmax-then-drop exactly."""
    from vitax.ops.attention import dropout_keep_mask

    b, nq, h, dh = q.shape
    nk = k.shape[1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    bh = jnp.arange(b * h, dtype=jnp.uint32)
    mask = jax.vmap(lambda i: dropout_keep_mask(
        seedvec[0], i, nq, nk, rate,
        q0=seedvec[1], k0=seedvec[2]))(bh).reshape(b, h, nq, nk)
    o = jnp.einsum("bhqk,bkhd->bqhd", p * mask / (l * (1.0 - rate)),
                   v.astype(jnp.float32))
    lse = (m + jnp.log(l))[..., 0]  # (B, H, nq)
    return o, lse


def _kernel_block_drop(q, k, v, seedvec, scale: float, rate: float):
    """Pallas dropout block product (block_dropout_kernel_with_lse — same
    selection cascade as _kernel_block)."""
    from vitax.ops.attention import block_dropout_kernel_with_lse

    b, nq, h, dh = q.shape
    kern = block_dropout_kernel_with_lse(nq, h, dh, q.dtype.itemsize)
    o, lse = kern(q, k, v, seedvec, scale, rate)
    return o.astype(jnp.float32), lse


def _ring_attention_local_drop(q, k, v, seed, *, axis_name: str,
                               scale: float, rate: float,
                               block_fn: Callable):
    """Ring body with in-kernel dropout: each (q-shard, kv-block) product
    masks its numerator at the pair's GLOBAL (q0, k0) token offsets, so the
    merged result equals dense masked attention for the same seed — every
    (q, k) element is computed by exactly one shard at its global
    coordinates (tests pin this against the dense oracle). The ring loop
    itself is _ring_attention_local's (one copy of the machinery); only the
    per-step seedvec differs."""
    from vitax.ops.attention import _seedvec

    sp = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    n_loc = q.shape[1]
    q0 = idx.astype(jnp.int32) * n_loc

    def step_args(step):
        # after `step` rotations this shard holds the block that ORIGINATED
        # on shard (idx - step): its global token offset keys the mask
        src = (idx - step) % sp
        return (_seedvec(seed, q0, src.astype(jnp.int32) * n_loc), rate)

    def block_with_drop(q, kk, vv, scale, sv, rate):
        return block_fn(q, kk, vv, sv, scale, rate)

    return _ring_attention_local(q, k, v, axis_name=axis_name, scale=scale,
                                 block_fn=block_with_drop,
                                 step_args=step_args)


def make_ring_dropout(mesh: Mesh, rate: float, axis_name: str = "sp",
                      use_kernel: Optional[bool] = None):
    """Ring attention with in-kernel attention dropout (round 5): (q, k, v,
    seed) -> o with the token axis sharded over `axis_name`. The seed is
    folded over the batch/tp shard position but NOT over sp — sp shards
    must agree on the global mask for the ring-equals-dense property."""
    if use_kernel is None:
        use_kernel = backend_platform() == "tpu"
    block_fn = _kernel_block_drop if use_kernel else _dense_block_drop
    spec = P(BATCH_AXES, axis_name, "tp", None)

    def ring_dropout(q, k, v, seed):
        from vitax.ops.attention import fold_shard_seed

        scale = q.shape[-1] ** -0.5
        shard_axes = tuple(a for a in (*BATCH_AXES, "tp")
                           if mesh.shape.get(a, 1) > 1)

        def body(q, k, v, seed):
            seed = fold_shard_seed(mesh, shard_axes, seed)
            return _ring_attention_local_drop(
                q, k, v, seed, axis_name=axis_name, scale=scale, rate=rate,
                block_fn=block_fn)

        fn = shard_map(
            body, mesh=mesh, in_specs=(spec, spec, spec, P()),
            out_specs=spec, check_vma=False,
        )
        return fn(q, k, v, seed)

    return ring_dropout


def make_ring_dropout_pp(rate: float, axis_name: str = "sp",
                         use_kernel: Optional[bool] = None):
    """Ring dropout for use INSIDE the pipeline body (pp x sp, tp=1): the
    local ring body with the dropout block products. The seed comes from
    the pipeline's per-(tick, layer, shard) keys, which DIFFER across sp
    shards — valid here: each (q, k) element is computed exactly once, by
    its q-owner shard, with that shard's seed deciding the mask identically
    in forward and backward (no cross-shard mask agreement is needed; the
    global-offset coordinates still decorrelate the kv blocks)."""
    if use_kernel is None:
        use_kernel = backend_platform() == "tpu"
    block_fn = _kernel_block_drop if use_kernel else _dense_block_drop

    def ring_dropout_local(q, k, v, seed):
        scale = q.shape[-1] ** -0.5
        return _ring_attention_local_drop(
            q, k, v, seed, axis_name=axis_name, scale=scale, rate=rate,
            block_fn=block_fn)

    return ring_dropout_local


def make_ring_attention_pp(axis_name: str = "sp",
                           use_kernel: Optional[bool] = None,
                           with_tp: bool = False):
    """Ring attention for use INSIDE the pipeline body (pp x sp composition).

    The pipeline shard_map manualizes "sp" itself (vitax/parallel/pipeline.py
    — a NESTED shard_map would hoist its closure constants into
    manual-computation wrappers whose all-axes sharding encodings Shardy
    rejects in jax 0.9), so this is simply the LOCAL ring body called
    directly in the already-manual region: operands are the per-device
    (B_loc, N/sp, H, Dh) shards and the ppermute rotates over the in-scope
    "sp" axis. With tp active (with_tp — tp stays a GSPMD-auto axis in the
    body), the block product must be the dense einsum path: GSPMD partitions
    the einsums over the tp-global head dim, whereas a Pallas kernel cannot
    be auto-partitioned."""
    if use_kernel is None:
        use_kernel = backend_platform() == "tpu"
    block_fn = _kernel_block if (use_kernel and not with_tp) else _dense_block

    def ring_attention_local(q: jax.Array, k: jax.Array,
                             v: jax.Array) -> jax.Array:
        scale = q.shape[-1] ** -0.5
        return _ring_attention_local(q, k, v, axis_name=axis_name,
                                     scale=scale, block_fn=block_fn)

    return ring_attention_local
