"""Ring attention: sequence/context parallelism over the "sp" mesh axis.

Long-context capability beyond the reference (which fixes sequence length at
(image/patch)^2 = 256 tokens and scales only parameters — SURVEY.md section 5
'long-context: absent'): activations are sharded over the token axis, and
attention streams K/V blocks around the ring of "sp" neighbors via
`jax.lax.ppermute` (one ICI hop per step), merging partial results with the
online-softmax recurrence (blockwise attention a la Ring Attention,
arXiv:2310.01889). Peak memory per chip is O(N/sp) activations and one K/V
block; the (N, N) score matrix never exists.

Collectives ride the ICI ring — ppermute is the bandwidth-optimal primitive
for neighbor exchange (see the scaling-book recipe: shard, permute, overlap).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def _ring_attention_local(q, k, v, *, axis_name: str, scale: float):
    """shard_map body. q, k, v: (B, N_loc, H, Dh) — the local token shard.
    Streams K/V blocks around the ring, merging with online softmax."""
    sp = jax.lax.axis_size(axis_name)
    b, n_loc, h, dh = q.shape

    qf = q.astype(jnp.float32)
    m = jnp.full((b, h, n_loc, 1), -jnp.inf, jnp.float32)   # running row max
    l = jnp.zeros((b, h, n_loc, 1), jnp.float32)            # running denominator
    o = jnp.zeros((b, h, n_loc, dh), jnp.float32)           # unnormalized out

    perm = [(i, (i + 1) % sp) for i in range(sp)]

    def body(i, carry):
        k_blk, v_blk, m, l, o = carry
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, k_blk.astype(jnp.float32)) * scale
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        o = o * corr + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32))
        # rotate K/V to the next ring neighbor (skipped after the last block)
        k_nxt = jax.lax.ppermute(k_blk, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_blk, axis_name, perm)
        return k_nxt, v_nxt, m_new, l, o

    _, _, _, l, o = jax.lax.fori_loop(0, sp, body, (k, v, m, l, o))
    out = (o / l).transpose(0, 2, 1, 3)  # (B, N_loc, H, Dh)
    return out.astype(q.dtype)


def make_ring_attention(mesh: Mesh, axis_name: str = "sp"):
    """Build a (B, N, H, Dh) -> (B, N, H, Dh) attention core with the token
    axis sharded over `axis_name`; batch over (dp, fsdp), heads over tp."""
    spec = P(("dp", "fsdp"), axis_name, "tp", None)

    def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array) -> jax.Array:
        scale = q.shape[-1] ** -0.5
        fn = jax.shard_map(
            functools.partial(_ring_attention_local, axis_name=axis_name, scale=scale),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )
        return fn(q, k, v)

    return ring_attention
