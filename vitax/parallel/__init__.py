from vitax.parallel.mesh import MESH_AXES, build_mesh, resolve_mesh_shape, batch_pspec  # noqa: F401
from vitax.parallel.sharding import (  # noqa: F401
    gather_over_fsdp,
    init_sharded_params,
    param_pspec,
    param_specs,
    state_specs_like,
)
