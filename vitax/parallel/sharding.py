"""Sharding rules: FSDP/ZeRO-3 as PartitionSpec assignment, not module wrappers.

This module is the TPU-native core replacing XlaFullyShardedDataParallel
(reference run_vit_training.py:13,177-181; SURVEY.md section 2.2 row 1):

- ZeRO-3  = every parameter (and its grad and AdamW moments) carries a
  PartitionSpec placing one dim on the "fsdp" mesh axis. GSPMD then emits the
  per-block all-gather before use and reduce-scatter of grads — the exact
  collectives the reference gets from nested FSDP wrapping, but scheduled by
  the XLA compiler with compute/communication overlap.
- ZeRO-2  = `--no_reshard_after_forward`: params are gathered once per step
  (see `gather_over_fsdp`) and stay live through backward; grads/opt state stay
  sharded.
- DP      = `--run_without_fsdp`: params replicated, batch sharded; the grad
  all-reduce the reference does manually (xm.reduce_gradients,
  run_vit_training.py:273) falls out of GSPMD.
- TP      = name-based rules sharding attention heads / MLP hidden over "tp"
  (capability the reference lacks; mesh axis reserved in SURVEY.md section 2.3).
- `--flatten_parameters` is accepted but a no-op: flattening exists in torch FSDP
  to amortize many small all-gathers; under GSPMD the compiler already fuses and
  schedules collectives, so there is nothing to flatten.

Sharded init (`init_sharded_params`) jits the initializer with output shardings
so a 10B+ model is *born sharded* — no host or device ever materializes the full
parameter tree. This subsumes the reference's `--shard_on_cpu` workaround
(run_vit_training.py:175-181, pytorch/xla#3992); with `--shard_on_cpu` we instead
init on host CPU and device_put shard-by-shard, which is the literal equivalent.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from vitax.config import Config

PyTree = Any

# Parameters consumed at float32 by the model: the head Dense ("head + loss in
# float32", vitax/models/vit.py), the MoE router Dense (vitax/models/moe.py),
# and every LayerNorm's scale/bias (flax normalizes in f32 and folds the scale
# in BEFORE casting the output to the compute dtype, so LN params never pass
# through a bf16 cast). Downcasting them would change the math — f32(bf16(w))
# != w — so the comm cast skips any leaf under these names. All are O(d) or
# O(d*num_classes): their f32 gathers are noise next to the O(d^2) block
# matrices the policy targets.
KEEP_F32_PARAMS = ("head", "router", "norm", "norm1", "norm2")


def _path_names(path) -> Tuple[str, ...]:
    names = []
    for p in path:
        if hasattr(p, "key"):
            names.append(str(p.key))
        elif hasattr(p, "name"):
            names.append(str(p.name))
        elif hasattr(p, "idx"):
            names.append(str(p.idx))
        else:
            names.append(str(p))
    return tuple(names)


# TP rules: (predicate on path names) -> dim sharded over "tp".
# Column-parallel: qkv and fc1 shard their *output* dim; row-parallel: proj and
# fc2 shard their *input* dim (Megatron layout: one all-reduce per pair, here
# inserted automatically by GSPMD).
def _tp_dim(names: Tuple[str, ...], ndim: int, last_two: Tuple[int, int]) -> Optional[int]:
    in_dim, out_dim = last_two
    if "qkv" in names or "fc1" in names:
        return out_dim if names[-1] == "kernel" else (ndim - 1)  # bias: its only dim
    if "proj" in names and "attn" in names and names[-1] == "kernel":
        return in_dim
    if "fc2" in names and names[-1] == "kernel":
        return in_dim
    return None


def param_pspec(
    path,
    shape: Tuple[int, ...],
    cfg: Config,
    mesh_shape: Tuple[int, ...],  # (dp, fsdp, tp, sp, pp, ep)
    scanned: bool,
) -> P:
    """Assign a PartitionSpec to one parameter.

    Strategy: apply the TP rule (if tp > 1), then FSDP-shard the largest
    remaining dim divisible by the fsdp axis size. The leading stacked-layers
    dim of scanned block params is never sharded over fsdp (lax.scan slices it
    per iteration; sharding it would serialize a gather per layer) — but under
    pipeline parallelism it IS the partitioned dim: each "pp" stage holds its
    own contiguous slice of layers (vitax/parallel/pipeline.py).
    """
    _, fsdp, tp, _, pp, ep = mesh_shape
    ndim = len(shape)
    names = _path_names(path)
    spec: list = [None] * ndim

    is_scanned_block = scanned and "blocks" in names
    first_shardable = 1 if is_scanned_block else 0

    if pp > 1 and is_scanned_block:
        assert shape[0] % pp == 0, (
            f"pp: stacked layer dim {shape[0]} of {names} not divisible by "
            f"pp={pp}")
        spec[0] = "pp"

    if ep > 1 and "moe" in names and names[-1] in ("w1", "b1", "w2", "b2"):
        # expert weights: the (E, ...) experts dim shards over "ep" (the
        # GShard layout — vitax/models/moe.py); router params follow the
        # default rules like any dense weight
        e_dim = first_shardable
        assert shape[e_dim] % ep == 0, (
            f"ep: experts dim {e_dim} of {names} {shape} not divisible by "
            f"ep={ep}")
        spec[e_dim] = "ep"
        first_shardable = e_dim + 1  # fsdp picks among the remaining dims

    if tp > 1:
        tp_dim = _tp_dim(names, ndim, (ndim - 2, ndim - 1))
        if tp_dim is not None and tp_dim >= first_shardable:
            assert shape[tp_dim] % tp == 0, (
                f"TP: dim {tp_dim} of {names} {shape} not divisible by tp={tp}")
            spec[tp_dim] = "tp"

    if fsdp > 1 and not cfg.run_without_fsdp:
        # largest free dim divisible by fsdp size (ZeRO-3 shards every param;
        # small indivisible params stay replicated, matching FSDP's handling of
        # leftover/root params)
        candidates = [
            (shape[d], d) for d in range(first_shardable, ndim)
            if spec[d] is None and shape[d] % fsdp == 0 and shape[d] >= fsdp
        ]
        if candidates:
            _, d = max(candidates)
            spec[d] = "fsdp"

    return P(*spec)


def param_specs(abstract_params: PyTree, cfg: Config, mesh: Mesh) -> PyTree:
    """PartitionSpec tree matching an (abstract) parameter tree.

    Routed through the declarative rule table (vitax/parallel/rules.py,
    scalax `TreePathShardingRule` style). `param_pspec` above remains the
    reference dispatcher the table is pinned against leaf-for-leaf across
    the dp/zero2/zero3/tp/pp/ep arms (tests/test_programs.py)."""
    from vitax.parallel import rules as _rules

    return _rules.specs_from_rules(abstract_params, cfg, mesh)


def state_specs_like(abstract_state: PyTree, params_specs: PyTree) -> PyTree:
    """Spec tree for a TrainState-like pytree: leaves under a `mu`/`nu` (AdamW
    moments) or `params` subtree inherit the matching parameter's spec; scalars
    and everything else are replicated.

    This is how optimizer-state sharding (ZeRO-1) 'falls out' of param sharding
    (SURVEY.md section 2.3): AdamW moments are param-shaped pytrees, so they
    reuse the param specs leaf-for-leaf.
    """
    flat_specs = {
        _path_names(path): spec
        for path, spec in jax.tree_util.tree_flatten_with_path(params_specs)[0]
    }

    def assign(path, leaf):
        names = _path_names(path)
        for marker in ("mu", "nu", "params"):
            if marker in names:
                # exact-path match: the subpath after the marker must name a
                # parameter (mu/nu ARE param-shaped trees; `params` in the
                # state is the param tree itself). Suffix matching is a
                # silent-misplacement landmine with colliding leaf names.
                sub = names[names.index(marker) + 1:]
                spec = flat_specs.get(sub)
                if spec is None:
                    raise ValueError(
                        f"state leaf {'/'.join(names)}: no parameter at "
                        f"subpath {'/'.join(sub) or '<root>'} — cannot infer "
                        "its sharding (new optimizer state needs an explicit "
                        "rule here)")
                if len(leaf.shape) != len(spec):
                    raise ValueError(
                        f"state leaf {'/'.join(names)} has rank "
                        f"{len(leaf.shape)} but the parameter spec at "
                        f"{'/'.join(sub)} is rank {len(spec)} — non-param-"
                        "shaped aux state (e.g. factored moments) needs an "
                        "explicit sharding rule")
                return spec
        return P()

    return jax.tree_util.tree_map_with_path(assign, abstract_state)


def shardings_of(mesh: Mesh, specs: PyTree) -> PyTree:
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P))


def gather_over_fsdp(specs: PyTree) -> PyTree:
    """ZeRO-2 view of the param specs: drop the "fsdp" placement (params fully
    gathered over fsdp for the whole step), keep TP placements. Used when
    `--no_reshard_after_forward` is set (reference run_vit_training.py:358,174)."""
    def strip(spec: P) -> P:
        return P(*[None if axis == "fsdp" else axis for axis in spec])
    return jax.tree.map(strip, specs, is_leaf=lambda x: isinstance(x, P))


def gather_overlap_active(cfg: Config, mesh: Mesh) -> bool:
    """Resolve --gather_overlap {auto,off,on} against the actual mesh.

    `on` is taken at its word (Config.validate already rejected structurally
    impossible configs; on a mesh without an fsdp axis the prefetch constraints
    degenerate to no-ops and the schedule is merely pointless, not wrong).
    `auto` engages only where the schedule both applies and preserves the
    requested semantics: ZeRO-3 per-block gathers, the scanned stacked tree,
    per-block remat with none_saveable (the overlap backward re-gathers and
    recomputes each block — exactly those semantics), no pipeline, and an
    fsdp axis that actually shards (otherwise there is nothing to overlap)."""
    mode = getattr(cfg, "gather_overlap", "auto")
    if mode == "off":
        return False
    if mode == "on":
        return True
    return (cfg.reshard_after_forward
            and not cfg.run_without_fsdp
            and cfg.scan_blocks
            and cfg.grad_ckpt
            and cfg.remat_policy == "none_saveable"
            and getattr(cfg, "pp_size", 1) == 1
            and mesh.shape.get("fsdp", 1) > 1)


def prefetch_gather(stacked: PyTree, start, length: int,
                    mesh: Mesh, block_specs: PyTree) -> PyTree:
    """Explicitly all-gather `length` layers of the stacked block-param tree
    over the "fsdp" axis, starting at layer `start` (a traced scalar is fine).

    This is the collective the double-buffered scan schedule issues one
    iteration ahead of use (--gather_overlap): slicing the stacked (L, ...)
    leaves first and constraining the slice to the fsdp-stripped layout makes
    GSPMD emit the gather HERE — on the prefetch slot feeding the scan carry —
    instead of at the parameter use sites inside the next block's matmuls.
    Composes with the comm-precision cast (cast_to_compute): the cast runs on
    the sharded stacked tree before the forward, so under the bf16 policy the
    prefetched gather moves bf16 bytes (KEEP_F32_PARAMS leaves gather f32,
    as at the use sites).

    `block_specs` is the PartitionSpec tree of the stacked block params (the
    `state_specs.params["params"]["blocks"]` subtree); the returned tree holds
    (length, ...) leaves gathered over fsdp with every other placement (tp,
    ep) intact."""
    # specs lead the tree.maps: P is a tuple subclass and must be the
    # is_leaf-guarded first tree (see vitax/parallel/pipeline.py)
    is_spec = lambda x: isinstance(x, P)
    sharded = jax.tree.map(lambda spec: NamedSharding(mesh, spec),
                           block_specs, is_leaf=is_spec)
    gathered = jax.tree.map(
        lambda spec: NamedSharding(
            mesh, P(*[None if ax == "fsdp" else ax for ax in spec])),
        block_specs, is_leaf=is_spec)

    def leaf(sh_in, sh_out, x):
        s = jax.lax.dynamic_slice_in_dim(x, start, length, axis=0)
        # pin the slice to the stacked tree's own (fsdp-sharded) layout
        # first: without this GSPMD back-propagates the replicated
        # constraint through the dynamic_slice and hoists the all-gather
        # ABOVE it — gathering the entire (L, ...) stack every iteration
        # instead of one group's slice
        s = jax.lax.with_sharding_constraint(s, sh_in)
        return jax.lax.with_sharding_constraint(s, sh_out)

    return jax.tree.map(leaf, sharded, gathered, stacked)


def cast_to_compute(
    params: PyTree,
    dtype: Any = jnp.bfloat16,
    shardings: Optional[PyTree] = None,
    grad_reduce_dtype: Any = jnp.float32,
    keep_f32: Tuple[str, ...] = KEEP_F32_PARAMS,
) -> PyTree:
    """Downcast the param tree to the compute dtype *while still sharded*.

    The point: flax's `promote_dtype` casts params at the use site — *after*
    GSPMD has gathered them — so every FSDP all-gather moves f32 bytes even in
    a bf16 run. Casting each shard first commutes with the gather (a gather
    rearranges bits, a cast maps them elementwise), so applying the model with
    the pre-cast tree is bitwise-identical to gather-then-cast while every
    param collective (ZeRO-3 per-block gathers, the ZeRO-2 step-top gather,
    pipeline in-body gathers) moves half the bytes.

    Each cast leaf is a `custom_vjp` convert:

    - forward: `astype(dtype)` + re-anchor to the leaf's own NamedSharding (the
      cast must not perturb GSPMD's placement of the downstream gather);
    - backward: upcast the cotangent to f32 and pin it to the shard layout —
      with `grad_reduce_dtype=float32` the convert runs *before* the sharded
      anchor, so the grad reduce-scatter / all-reduce happens on f32 bits
      (exact current numerics); with bfloat16 the anchor is applied to the
      bf16 cotangent first, pinning the reduction on bf16 bits (2x fewer grad
      bytes, an opt-in precision trade).

    Leaves consumed at f32 by the model (`keep_f32`: head, router) and non-f32
    leaves pass through untouched. `shardings` must mirror `params`
    (leaf-for-leaf NamedShardings) or be None (no re-anchor; single-device).
    """
    cdtype = jnp.dtype(dtype)
    reduce_bf16 = jnp.dtype(grad_reduce_dtype) == jnp.bfloat16

    def leaf_fn(path, x, sh):
        names = _path_names(path)
        if x.dtype != jnp.float32 or any(k in names for k in keep_f32):
            return x

        def _fwd_impl(v):
            y = v.astype(cdtype)
            if sh is not None:
                y = jax.lax.with_sharding_constraint(y, sh)
            return y

        @jax.custom_vjp
        def cast(v):
            return _fwd_impl(v)

        def fwd(v):
            return _fwd_impl(v), None

        def bwd(_, g):
            if reduce_bf16 and sh is not None:
                g = jax.lax.with_sharding_constraint(g, sh)
            g = g.astype(jnp.float32)
            if not reduce_bf16 and sh is not None:
                g = jax.lax.with_sharding_constraint(g, sh)
            return (g,)

        cast.defvjp(fwd, bwd)
        return cast(x)

    if shardings is None:
        return jax.tree_util.tree_map_with_path(
            lambda p, x: leaf_fn(p, x, None), params)
    return jax.tree_util.tree_map_with_path(leaf_fn, params, shardings)


class CommPrecision:
    """Resolved comm-precision policy for one (cfg, mesh, param-spec) triple.

    Built by `make_comm_precision` only when the policy is active
    (cfg.comm_cast_active); callers hold `Optional[CommPrecision]` and treat
    None as "f32 collectives, pre-PR program".

    `cast` downcasts the tree (see `cast_to_compute`); apply it *inside* the
    differentiated function where possible so the convert-vjp upcasts and pins
    the cotangent at the cast boundary. `finalize_grads` is the explicit
    equivalent for paths that cast outside autodiff (ZeRO-2's step-top gather,
    the 1f1b hand-assembled backward): it upcasts any bf16 grad leaf to f32,
    pinning the reduction dtype the same way. It is a no-op on f32 leaves, so
    applying it unconditionally after any grad path is safe.
    """

    def __init__(self, cfg: Config, mesh: Mesh, params_specs: PyTree):
        self.dtype = jnp.dtype(cfg.dtype)
        self.reduce_bf16 = cfg.grad_reduce_dtype == "bfloat16"
        self.grad_reduce_dtype = (
            jnp.bfloat16 if self.reduce_bf16 else jnp.float32)
        self.shardings = shardings_of(mesh, params_specs)

    def cast(self, params: PyTree) -> PyTree:
        return cast_to_compute(
            params, self.dtype, self.shardings, self.grad_reduce_dtype)

    def finalize_grads(self, grads: PyTree) -> PyTree:
        def leaf(g, sh):
            if g.dtype != self.dtype:
                return g
            if self.reduce_bf16:
                g = jax.lax.with_sharding_constraint(g, sh)
            return g.astype(jnp.float32)
        return jax.tree.map(leaf, grads, self.shardings)


def make_comm_precision(
    cfg: Config, mesh: Mesh, params_specs: PyTree,
) -> Optional[CommPrecision]:
    """CommPrecision when the bf16 comm-cast policy is active, else None."""
    if not cfg.comm_cast_active:
        return None
    return CommPrecision(cfg, mesh, params_specs)


def jit_init_sharded(
    init_fn: Callable[[jax.Array], PyTree],
    rng: jax.Array,
    shardings: PyTree,
    shard_on_cpu: bool = False,
) -> PyTree:
    """Run an initializer so its outputs are born sharded.

    Default path: `jax.jit(init_fn, out_shardings=...)` — XLA materializes each
    array already laid out across the mesh; peak memory per device is the shard
    size, not the full model (SURVEY.md section 7 'hard parts' #1).

    `shard_on_cpu` path: run the initializer on host CPU, then `device_put`
    leaf-by-leaf to the target sharding (each host slices out only its
    addressable shards). Literal equivalent of FSDP's CPU-side shard
    construction (reference run_vit_training.py:175-181, pytorch/xla#3992).
    """
    if shard_on_cpu:
        cpu = jax.local_devices(backend="cpu")[0]
        with jax.default_device(cpu):
            host_tree = jax.jit(init_fn)(jax.device_put(rng, cpu))
        # device_put of a host array can be zero-copy ADOPTED by the CPU
        # backend, leaving the params backed by malloc-heap memory that the
        # donating train step later reuses in place (same hazard as
        # checkpoint/peer.assemble_state). Launder each leaf through a jitted
        # on-device copy so the returned tree is backed by fresh XLA-owned
        # buffers, exactly like the jit-init path below.
        placed = jax.tree.map(
            lambda x, s: jax.device_put(np.asarray(x).copy(), s),
            host_tree, shardings)
        return jax.tree.map(jax.jit(jnp.copy), placed)
    return jax.jit(init_fn, out_shardings=shardings)(rng)


def init_sharded_params(
    init_fn: Callable[[jax.Array], PyTree],
    rng: jax.Array,
    cfg: Config,
    mesh: Mesh,
) -> Tuple[PyTree, PyTree]:
    """Initialize parameters directly into their FSDP/TP shards."""
    abstract = jax.eval_shape(init_fn, rng)
    specs = param_specs(abstract, cfg, mesh)
    params = jit_init_sharded(init_fn, rng, shardings_of(mesh, specs), cfg.shard_on_cpu)
    return params, specs
