"""Device mesh construction.

TPU-native replacement for the reference's process-per-core world
(xmp.spawn, reference run_vit_training.py:364): one process per host, all
devices arranged in a 6-axis `jax.sharding.Mesh`:

  axes = ("dp", "fsdp", "tp", "sp", "pp", "ep")

- "dp":   pure data parallelism (params replicated across it)
- "fsdp": ZeRO-3 axis — params/grads/optimizer state sharded across it, and it
          also carries batch parallelism (the reference's single 'data' axis)
- "tp":   tensor parallelism (attention heads / MLP hidden sharded)
- "sp":   sequence/context parallelism (ring attention over the token axis)
- "pp":   pipeline parallelism (GPipe stages over the stacked layer axis —
          vitax/parallel/pipeline.py; composes with dp, fsdp/ZeRO-3, and
          tp/sp — the latter ride as GSPMD-auto axes inside the body)
- "ep":   expert parallelism (vitax/models/moe.py) — carries batch like dp,
          and MoE expert weights shard their leading (E, ...) dim across it;
          GSPMD inserts the batch<->expert all-to-alls from the specs

The reference's FSDP corresponds to mesh shape (1, n_devices, 1, 1); its
--run_without_fsdp DP baseline to (n_devices, 1, 1, 1). GSPMD emits the
all-gather / reduce-scatter / all-reduce collectives over ICI from the sharding
specs alone (SURVEY.md section 2.4).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from vitax.config import Config

MESH_AXES = ("dp", "fsdp", "tp", "sp", "pp", "ep")


def shard_map(f, mesh, in_specs, out_specs, check_vma=False, axis_names=None):
    """jax.shard_map across jax versions — the single spelling every vitax
    shard_map site goes through. jax >= 0.5 exposes the public jax.shard_map
    (replication checking under `check_vma`, manual axes under `axis_names`);
    on 0.4.x the same transform is jax.experimental.shard_map.shard_map with
    `check_rep` and the complementary `auto` set instead."""
    if hasattr(jax, "shard_map"):
        kw = {} if axis_names is None else {"axis_names": axis_names}
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma, **kw)
    from jax.experimental.shard_map import shard_map as _shard_map
    kw = {}
    if axis_names is not None:
        kw["auto"] = frozenset(mesh.axis_names) - frozenset(axis_names)
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=check_vma, **kw)


def axis_size(axis_name: str) -> int:
    """jax.lax.axis_size across versions: 0.4.x has no axis_size, but
    psum(1, axis) of a Python int constant-folds to the bound axis size."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


@jax.custom_jvp
def optimization_barrier(x):
    """Differentiable jax.lax.optimization_barrier: 0.4.x defines no
    differentiation rule for the primitive, so route autodiff around it —
    the primal is barriered, tangents/cotangents flow through as identity
    (the barrier IS the identity; only XLA scheduling sees it, and the
    primal-side barrier is what pins the gather hoisting)."""
    return jax.lax.optimization_barrier(x)


@optimization_barrier.defjvp
def _optimization_barrier_jvp(primals, tangents):
    (x,), (t,) = primals, tangents
    return jax.lax.optimization_barrier(x), t


def resolve_mesh_shape(cfg: Config, n_devices: Optional[int] = None) -> Tuple[int, ...]:
    """Resolve (dp, fsdp, tp, sp, pp, ep) against the device count. One axis may be
    -1 (= all remaining devices). `--run_without_fsdp` forces everything onto dp
    (the reference's pure-DP baseline, run_vit_training.py:171-172). Pipeline
    parallelism (pp > 1) composes with dp, fsdp (ZeRO-3 gathers run
    just-in-time inside the pipeline body), and tp/sp (GSPMD-auto axes
    inside the body — see vitax/parallel/pipeline.py; the 1F1B schedule
    and MoE-under-pp remain dense/tp-free, enforced by Config.validate)."""
    n = n_devices if n_devices is not None else jax.device_count()
    dp, fsdp, tp, sp = cfg.dp_size, cfg.fsdp_size, cfg.tp_size, cfg.sp_size
    pp = getattr(cfg, "pp_size", 1)
    ep = getattr(cfg, "ep_size", 1)

    if cfg.run_without_fsdp:
        if fsdp not in (-1, 1):
            raise ValueError("--run_without_fsdp is incompatible with --fsdp_size > 1")
        fsdp = 1
        if dp == 1 and tp == 1 and sp == 1 and pp == 1 and ep == 1:
            dp = -1  # default DP baseline: all devices data-parallel

    if pp > 1:
        # fsdp composes: ZeRO-3 shards are gathered just-in-time inside the
        # pipeline body (vitax/parallel/pipeline.py). With --fsdp_size 1 the
        # remaining devices default to carrying the batch on dp; an explicit
        # --dp_size -1 wins over fsdp's -1 default (round-2 CLI behavior).
        if fsdp == 1 and dp == 1:
            dp = -1
        elif dp == -1 and fsdp == -1:
            fsdp = 1

    sizes = [dp, fsdp, tp, sp, pp, ep]
    n_auto = sum(1 for s in sizes if s == -1)
    if n_auto > 1:
        raise ValueError(f"at most one mesh axis may be -1, got {sizes}")
    fixed = int(np.prod([s for s in sizes if s != -1]))
    if n_auto == 1:
        if n % fixed != 0:
            raise ValueError(f"device count {n} not divisible by fixed mesh axes {sizes}")
        sizes[sizes.index(-1)] = n // fixed
    elif fixed != n:
        raise ValueError(f"mesh {sizes} does not cover {n} devices")
    return tuple(sizes)  # type: ignore[return-value]


def build_mesh(cfg: Config, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build the 6-axis mesh. Device order follows jax.devices(), which on TPU
    reflects physical torus coordinates — keeping the fastest-varying axis
    ("sp", then "tp") on the closest ICI neighbors."""
    devices = list(devices) if devices is not None else jax.devices()  # vtx: ignore[VTX104] mesh wants real devices
    shape = resolve_mesh_shape(cfg, len(devices))
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, MESH_AXES)


BATCH_AXES = ("dp", "fsdp", "ep")  # mesh axes that carry the global batch


def batch_pspec(sp_shard_tokens: bool = False) -> P:
    """PartitionSpec for a (B, ...) batch: batch over dp+fsdp+ep.

    The reference shards the global batch across all ranks
    (DistributedSampler, run_vit_training.py:62-64); here the same statement is
    one PartitionSpec. With sequence parallelism the token axis of activations
    is additionally sharded over "sp" (handled inside the model/step, not on the
    raw image batch). "ep" carries batch too — expert parallelism is data
    parallelism whose MoE expert weights are sharded instead of replicated.
    """
    del sp_shard_tokens
    return P(BATCH_AXES)
