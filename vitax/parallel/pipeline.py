"""Pipeline parallelism: GPipe stages over the "pp" mesh axis.

Capability beyond the reference (SURVEY.md section 2.3 lists PP as absent).
TPU-first formulation: the model's blocks are ALREADY a stacked (L, ...)
parameter tree (the lax.scan layout) — pipeline parallelism is nothing more
than sharding that leading layer axis over a mesh axis
(`PartitionSpec("pp", ...)`, vitax/parallel/sharding.py:param_pspec) and
running the stage schedule inside `jax.shard_map`:

- Stage s holds layers [s*L/S, (s+1)*L/S) — its shard of the stacked tree.
- The local batch is split into M microbatches (`--pp_microbatches`,
  default S). At tick t (t = 0..M+S-2), stage s processes microbatch t-s
  (bubble ticks compute masked garbage — lockstep SPMD, standard GPipe),
  then hands its activation to stage s+1 via `jax.lax.ppermute` — one ICI
  hop, overlapped with the next tick's compute by XLA's scheduler.
- The last stage's valid outputs are the tick outputs [S-1, S-1+M); a psum
  over "pp" (one nonzero contributor) replicates them so the head/loss run
  under plain GSPMD afterwards.
- Backward is plain autodiff through the scan/ppermute: bubble-tick
  computations receive zero cotangents (their outputs are masked), so only
  real microbatches contribute gradients, which land on each stage's own
  param shard.

Composes with dp AND fsdp/ZeRO-3 (tp/sp are excluded): block params may
carry "fsdp" placements on their weight dims in addition to "pp" on the
layer dim. Inside the pipeline body each block's leaves are all-gathered
over "fsdp" right before use — the manual form of the per-block gather
GSPMD emits on the scan path — and autodiff's transpose of that gather is
a reduce-scatter, so gradients land back on the ZeRO-3 shards. With remat
the gather sits inside the checkpointed block, so the backward re-gathers
instead of keeping gathered weights live: full ZeRO-3 memory semantics
inside GPipe. Embed/head run data-parallel outside the pipeline, reusing
the SAME param tree as the scan path functionally — init and checkpoints
are identical between pp and non-pp topologies, so Orbax cross-topology
restore covers pp<->fsdp resizes. Dropout is excluded under pp
(config.validate).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from vitax.config import Config
from vitax.parallel.mesh import BATCH_AXES


def _gather_over(x, spec: P, axis_name: str):
    """All-gather the dims of `x` that `spec` places on `axis_name` (tiled:
    the gathered dim returns to its full size in place)."""
    for dim, ax in enumerate(spec):
        if ax == axis_name:
            x = jax.lax.all_gather(x, axis_name, axis=dim, tiled=True)
    return x


def make_pp_forward(cfg: Config, model, mesh: Mesh, block_specs=None):
    """(params, images, deterministic) -> logits, GPipe-pipelined over "pp".

    `model` is the same VisionTransformer the scan path uses — its param tree
    is reused leaf-for-leaf; this function only changes HOW blocks are
    applied. `block_specs` is the PartitionSpec tree of the stacked block
    params (P("pp", ...) with optional "fsdp" dims) — when omitted, a
    pp-only layout is assumed (stage params whole per device).
    """
    import flax.linen as nn

    from vitax.models.vit import _REMAT_POLICIES, Block, PatchEmbed

    S = mesh.shape["pp"]
    M = cfg.pp_microbatches or S
    assert cfg.num_blocks % S == 0, (cfg.num_blocks, S)
    dp_like = (mesh.shape["dp"] * mesh.shape["fsdp"] * mesh.shape["ep"])
    assert cfg.batch_size % (dp_like * M) == 0, (
        f"batch {cfg.batch_size} must divide by data-axes*microbatches "
        f"({dp_like}*{M})")

    # the model's attention impl may be shard_map-wrapped (multi-device
    # meshes); inside pipeline_body we are ALREADY inside shard_map and the
    # operands are local, so unwrap to the raw kernel (same selection,
    # including the dryrun's interpret-mode forcing)
    bk = model.block_kwargs()
    bk["attention_impl"] = getattr(
        bk["attention_impl"], "vitax_local_impl", bk["attention_impl"])
    # mesh-level sharding anchors are meaningless on the per-device values
    # inside shard_map (and NamedSharding constraints are illegal there)
    bk["token_sharding"] = None
    block = Block(**bk)

    # per-layer specs: drop the leading (stacked/"pp") dim of each leaf spec
    is_spec = lambda x: isinstance(x, P)  # noqa: E731
    layer_specs = (None if block_specs is None else jax.tree.map(
        lambda s: P(*s[1:]), block_specs, is_leaf=is_spec))

    def one_block(carry, layer_params):
        if layer_specs is not None and mesh.shape["fsdp"] > 1:
            # ZeRO-3 inside the pipeline: gather this block's shards over
            # "fsdp" just-in-time (under remat this sits inside the
            # checkpointed region, so backward re-gathers rather than
            # holding gathered weights live; the gather's transpose
            # reduce-scatters the weight cotangents onto the shards).
            # NOTE specs lead the tree.map: P is a tuple subclass, so it
            # must be the is_leaf-guarded first tree
            layer_params = jax.tree.map(
                lambda s, x: _gather_over(x, s, "fsdp"),
                layer_specs, layer_params, is_leaf=is_spec)
        return block.apply({"params": layer_params}, carry, True), None

    if cfg.grad_ckpt:
        one_block = jax.checkpoint(
            one_block, policy=_REMAT_POLICIES[cfg.remat_policy],
            prevent_cse=False)

    def stage_fn(stage_params, x):
        y, _ = jax.lax.scan(one_block, x, stage_params,
                            unroll=min(cfg.scan_unroll, cfg.num_blocks // S))
        return y

    def pipeline_body(stage_params, x):
        # per-device view: stage_params = this stage's (L/S, ...) tree,
        # x = this dp-shard's (B_loc, N, D) activations (replicated over pp)
        s = jax.lax.axis_index("pp")
        b_loc = x.shape[0]
        mbs = x.reshape(M, b_loc // M, *x.shape[1:])
        perm = [(i, (i + 1) % S) for i in range(S)]

        def tick(buf, t):
            inj = jax.lax.dynamic_index_in_dim(
                mbs, jnp.clip(t, 0, M - 1), 0, keepdims=False)
            x_in = jnp.where(s == 0, inj, buf)
            y = stage_fn(stage_params, x_in)
            y_out = jnp.where(s == S - 1, y, jnp.zeros_like(y))
            if S > 1:
                # the final tick's carry is never read — skip its ICI hop
                # (cond predicate is uniform across devices, so the
                # collective stays SPMD-legal; cf. ring attention's
                # "exactly sp-1 rotations")
                buf = jax.lax.cond(
                    t < M + S - 2,
                    lambda v: jax.lax.ppermute(v, "pp", perm),
                    lambda v: v, y)
            else:
                buf = y
            return buf, y_out

        _, ys = jax.lax.scan(tick, jnp.zeros_like(mbs[0]),
                             jnp.arange(M + S - 1))
        outs = ys[S - 1:S - 1 + M]          # microbatch i at tick S-1+i
        outs = jax.lax.psum(outs, "pp")     # one nonzero contributor
        return outs.reshape(b_loc, *x.shape[1:])

    act_spec = P(BATCH_AXES, None, None)

    def stacked_specs(tree):
        return jax.tree.map(
            lambda leaf: P(*("pp",) + (None,) * (leaf.ndim - 1)), tree)

    dtype = model.dtype

    def forward(params, images, deterministic: bool = True):
        del deterministic  # pp excludes dropout (config.validate), so the
        # deterministic and non-deterministic paths coincide
        p = params["params"]
        x = PatchEmbed(
            patch_size=cfg.patch_size, embed_dim=cfg.embed_dim, dtype=dtype,
        ).apply({"params": p["patch_embed"]}, images.astype(dtype))
        x = x + p["pos_embed"].astype(dtype)

        stacked = p["blocks"]
        in_specs = (block_specs if block_specs is not None
                    else stacked_specs(stacked))
        run = jax.shard_map(
            pipeline_body, mesh=mesh,
            in_specs=(in_specs, act_spec), out_specs=act_spec,
            check_vma=False)
        x = run(stacked, x)

        x = nn.LayerNorm(
            epsilon=1e-6, dtype=dtype, param_dtype=jnp.float32,
        ).apply({"params": p["norm"]}, x)
        x = jnp.mean(x, axis=1)
        return nn.Dense(
            cfg.num_classes, dtype=jnp.float32, param_dtype=jnp.float32,
        ).apply({"params": p["head"]}, x)

    return forward
